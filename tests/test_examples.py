"""Example CLIs as subprocess smoke tests.

The reference's de-facto test strategy is runnable examples
(SURVEY.md §4); this repo's examples are its user-facing surface, so
each one runs here at tiny sizes — exit code, key output lines, and
the learning signal are asserted. Sizes are chosen to keep each run
under ~1 minute on the 8-virtual-CPU-device world.
"""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=300, tmp=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch a TPU plugin
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    argv = [sys.executable, os.path.join(_ROOT, "examples", args[0]), *args[1:]]
    if tmp is not None:  # artifact-writing examples land in tmp_path
        argv += ["--out-dir", str(tmp)]
    p = subprocess.run(
        argv, capture_output=True, text=True, timeout=timeout, env=env,
        cwd=_ROOT,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    return p.stdout


@pytest.mark.examples
def test_example_subgroup_parity():
    out = _run(["example_subgroup.py"])
    assert "subgroup 0 gathered: [0, 1, 2, 3]" in out
    assert "subgroup 1 gathered: [4, 5, 6, 7]" in out


@pytest.mark.examples
def test_vae_hpo_example(tmp_path):
    # --synthetic-size keeps it hermetic (no MNIST download attempt)
    # and tiny; --out-dir keeps artifacts out of the repo tree
    out = _run(["vae_hpo.py", "--epochs", "1", "--ngroups", "2",
                "--batch-size", "128", "--synthetic-size", "2048"],
               tmp=tmp_path)
    assert "trial 0:" in out and "trial 1:" in out
    assert "test loss" in out
    assert (tmp_path / "trial-0" / "metrics.json").exists()


@pytest.mark.examples
def test_lm_hpo_example():
    out = _run(["lm_hpo.py", "--ngroups", "2", "--seq-len", "64",
                "--steps", "12"])
    assert out.count("perplexity") == 2


@pytest.mark.examples
def test_lm_hpo_example_fused_dispatch():
    # The production dispatch shape (docs/DISPATCH.md): K fused steps
    # per device round-trip via make_lm_multi_step.
    out = _run(["lm_hpo.py", "--ngroups", "2", "--seq-len", "64",
                "--steps", "12", "--fused-steps", "4"])
    assert out.count("perplexity") == 2


@pytest.mark.examples
def test_lm_long_context_example():
    out = _run(["lm_long_context.py", "--seq-len", "64", "--steps", "8"])
    assert "greedy decode matches" in out


@pytest.mark.examples
def test_lm_long_context_byte_corpus():
    out = _run(["lm_long_context.py", "--seq-len", "64", "--steps", "8",
                "--corpus", os.path.join(_ROOT, "README.md")])
    assert "byte-modeling README.md" in out
    assert "decoded:" in out


@pytest.mark.examples
def test_pbt_example(tmp_path):
    out = _run(["pbt_vae.py", "--population", "4", "--generations", "2",
                "--steps-per-generation", "4", "--synthetic-size", "512"],
               tmp=tmp_path)
    assert "best" in out.lower()
    assert "[submesh]" in out


@pytest.mark.examples
def test_pbt_example_fused(tmp_path):
    out = _run(["pbt_vae.py", "--population", "4", "--generations", "2",
                "--steps-per-generation", "4", "--synthetic-size", "512",
                "--fused"], tmp=tmp_path)
    assert "[fused]" in out
    # one fused generation program = one dispatch per generation
    assert "1.0 dispatches/gen" in out


@pytest.mark.examples
def test_resnet_hpo_example():
    out = _run(["resnet_hpo.py", "--ngroups", "2", "--epochs", "1",
                "--base-channels", "8", "--synthetic-size", "512",
                "--batch-size", "64"])
    assert out.count("test acc") == 2


@pytest.mark.examples
def test_beta_vae_cifar_example(tmp_path):
    out = _run(["beta_vae_cifar.py", "--ngroups", "4", "--epochs", "1",
                "--synthetic-size", "512", "--batch-size", "32"],
               tmp=tmp_path)
    assert "trial" in out


@pytest.mark.examples
def test_moe_vae_hpo_example(tmp_path):
    out = _run(["moe_vae_hpo.py", "--ngroups", "2", "--model-parallel",
                "2", "--epochs", "1", "--synthetic-size", "512"],
               tmp=tmp_path)
    assert "trial" in out
