"""int8 weight-only quantization (train/lm_quant.py): reconstruction
bounds, structural contract, and decode accuracy on a trained model."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from multidisttorch_tpu.data import synthetic_corpus
from multidisttorch_tpu.models.transformer import TransformerLM
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.lm import create_lm_state, make_lm_train_step
from multidisttorch_tpu.train.lm_decode import make_cached_lm_sample
from multidisttorch_tpu.train.lm_quant import (
    dequantize_lm_params,
    quantize_lm_params,
)


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(0, 0.3, (64, 32)).astype(np.float32))
    params = {"layer": {"kernel": w, "bias": jnp.zeros((32,))}}
    q = quantize_lm_params(params)
    assert q["layer"]["q"].dtype == jnp.int8
    assert q["layer"]["scale"].shape == (32,)
    assert "kernel" not in q["layer"]
    deq = dequantize_lm_params(q)
    # symmetric rounding: per-element error <= scale/2 of its column
    err = np.abs(np.asarray(deq["layer"]["kernel"]) - np.asarray(w))
    bound = np.asarray(q["layer"]["scale"])[None, :] / 2 + 1e-8
    assert (err <= bound).all()


def test_quantize_leaves_non_kernels_alone():
    (g,) = setup_groups(1)
    model = TransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=1, max_len=16
    )
    params = model.init(
        {"params": jax.random.key(0)}, jnp.zeros((1, 16), jnp.int32)
    )["params"]
    q = quantize_lm_params(params)
    # embeddings + norms untouched, every dense kernel rewritten
    assert q["tok_embed"]["embedding"].dtype == jnp.float32
    assert q["ln_out"]["scale"].dtype == jnp.float32
    for name in ("q", "k", "v", "proj", "up", "down"):
        assert q["block_0"][name]["q"].dtype == jnp.int8
    assert q["head"]["q"].dtype == jnp.int8
    assert q["head"]["bias"].dtype == jnp.float32


def test_quantized_decode_agrees_with_f32():
    # Train the small LM until confident, then compare greedy decodes:
    # int8 weights must agree with f32 on nearly every generated token.
    (g,) = setup_groups(1)
    t = 32
    corpus = synthetic_corpus(n=4096, vocab_size=16)
    model = TransformerLM(
        vocab_size=16, d_model=32, num_heads=2, num_layers=2, max_len=t
    )
    tx = optax.adam(5e-3)
    state = create_lm_state(g, model, tx, jax.random.key(0), example_len=t)
    step = make_lm_train_step(g, model, tx)
    rng = np.random.default_rng(0)
    for _ in range(300):
        state, _ = step(
            state,
            jax.device_put(
                jnp.asarray(corpus.batch(rng, 8, t)), g.batch_sharding
            ),
        )

    buf = jnp.asarray(corpus.batch(np.random.default_rng(42), 8, t))
    sample = make_cached_lm_sample(g, model)
    out_f32 = np.asarray(sample(state, buf, 16, jax.random.key(1)))

    qstate = state.replace(params=quantize_lm_params(state.params))
    out_q = np.asarray(sample(qstate, buf, 16, jax.random.key(1)))
    agreement = (out_q == out_f32).mean()
    assert agreement >= 0.95, agreement
    assert out_q.min() >= 0 and out_q.max() < 16
