"""Expert-parallel MoE (ops/moe.py): routing semantics, EP-vs-replicated
parity, and training through the dispatch einsums. The reference has no
MoE/EP at all (SURVEY.md §2c). 8 virtual CPU devices."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from jax.sharding import PartitionSpec as P

from multidisttorch_tpu.ops.moe import MoEMLP, moe_ep_shardings
from multidisttorch_tpu.parallel.mesh import MODEL_AXIS, setup_groups


def _model(e=4, cap=4.0):
    return MoEMLP(
        num_experts=e, hidden_dim=16, out_dim=8, capacity_factor=cap
    )


def _init(model, d=12, b=16):
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(b, d)).astype(np.float32)
    )
    params = model.init(jax.random.key(0), x)["params"]
    return params, x


def test_forward_shapes_and_aux():
    model = _model()
    params, x = _init(model)
    y, aux = model.apply({"params": params}, x)
    assert y.shape == (16, 8)
    assert np.isfinite(float(aux))
    # aux is minimized at 1.0 for perfectly uniform routing; >= ~1 here
    assert float(aux) >= 0.99


def test_capacity_drops_overflow_tokens():
    # capacity_factor small enough that at most 1 token per expert is
    # served: dropped tokens must contribute exactly zero output.
    model = _model(e=2, cap=0.1)  # cap = ceil(16*0.1/2) = 1
    params, x = _init(model, b=16)
    y, _ = model.apply({"params": params}, x)
    served = np.count_nonzero(np.any(np.asarray(y) != 0.0, axis=-1))
    # at most one token per expert — and at least one token actually
    # served, so an all-zero combine path can't pass vacuously
    assert 1 <= served <= 2


def test_expert_parallel_matches_replicated():
    # The same params evaluated replicated vs expert-sharded over a
    # (data x model) submesh must agree — GSPMD partitioning of the
    # dispatch/compute/combine einsums is semantics-preserving.
    model = _model()
    params, x = _init(model)
    y_ref, aux_ref = model.apply({"params": params}, x)

    (g,) = setup_groups(1, model_parallel=4)
    sh = moe_ep_shardings(g, params)
    assert sh["w1"].spec == P(MODEL_AXIS, None, None)
    assert sh["gate"]["kernel"].spec == P()
    params_ep = jax.device_put(params, sh)
    x_ep = jax.device_put(x, g.batch_sharding)

    @jax.jit
    def fwd(p, xx):
        return model.apply({"params": p}, xx)

    y_ep, aux_ep = fwd(params_ep, x_ep)
    np.testing.assert_allclose(
        np.asarray(y_ep), np.asarray(y_ref), rtol=2e-4, atol=1e-5
    )
    assert float(aux_ep) == pytest.approx(float(aux_ref), rel=1e-4)
    # experts are physically sharded: 4 experts over model axis of 4
    assert params_ep["w1"].addressable_shards[0].data.shape[0] == 1


def test_moe_trains_expert_sharded():
    model = _model()
    params, x = _init(model)
    target = jnp.asarray(
        np.random.default_rng(1).normal(size=(16, 8)).astype(np.float32)
    )
    (g,) = setup_groups(1, model_parallel=2)
    sh = moe_ep_shardings(g, params)
    params = jax.device_put(params, sh)
    x_ep = jax.device_put(x, g.batch_sharding)
    tx = optax.adam(3e-3)
    # computation-follows-data: moments inherit the expert sharding
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            y, aux = model.apply({"params": p}, x_ep)
            return jnp.mean((y - target) ** 2) + 0.01 * aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    losses = []
    for _ in range(30):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_ep_shardings_reject_indivisible_experts():
    model = MoEMLP(num_experts=3, hidden_dim=8, out_dim=4)
    params, _ = _init(model)
    (g,) = setup_groups(1, model_parallel=2)
    with pytest.raises(ValueError, match="num_experts"):
        moe_ep_shardings(g, params)
