"""Expert-parallel MoE (ops/moe.py): routing semantics, EP-vs-replicated
parity, and training through the dispatch einsums. The reference has no
MoE/EP at all (SURVEY.md §2c). 8 virtual CPU devices."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from jax.sharding import PartitionSpec as P

from multidisttorch_tpu.ops.moe import MoEMLP, moe_ep_shardings
from multidisttorch_tpu.parallel.mesh import MODEL_AXIS, setup_groups


def _model(e=4, cap=4.0):
    return MoEMLP(
        num_experts=e, hidden_dim=16, out_dim=8, capacity_factor=cap
    )


def _init(model, d=12, b=16):
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(b, d)).astype(np.float32)
    )
    params = model.init(jax.random.key(0), x)["params"]
    return params, x


def test_forward_shapes_and_aux():
    model = _model()
    params, x = _init(model)
    y, aux = model.apply({"params": params}, x)
    assert y.shape == (16, 8)
    assert np.isfinite(float(aux))
    # aux is minimized at 1.0 for perfectly uniform routing; >= ~1 here
    assert float(aux) >= 0.99


def test_capacity_drops_overflow_tokens():
    # capacity_factor small enough that at most 1 token per expert is
    # served: dropped tokens must contribute exactly zero output.
    model = _model(e=2, cap=0.1)  # cap = ceil(16*0.1/2) = 1
    params, x = _init(model, b=16)
    y, _ = model.apply({"params": params}, x)
    served = np.count_nonzero(np.any(np.asarray(y) != 0.0, axis=-1))
    # at most one token per expert — and at least one token actually
    # served, so an all-zero combine path can't pass vacuously
    assert 1 <= served <= 2


def test_expert_parallel_matches_replicated():
    # The same params evaluated replicated vs expert-sharded over a
    # (data x model) submesh must agree — GSPMD partitioning of the
    # dispatch/compute/combine einsums is semantics-preserving.
    model = _model()
    params, x = _init(model)
    y_ref, aux_ref = model.apply({"params": params}, x)

    (g,) = setup_groups(1, model_parallel=4)
    sh = moe_ep_shardings(g, params)
    assert sh["w1"].spec == P(MODEL_AXIS, None, None)
    assert sh["gate"]["kernel"].spec == P()
    params_ep = jax.device_put(params, sh)
    x_ep = jax.device_put(x, g.batch_sharding)

    @jax.jit
    def fwd(p, xx):
        return model.apply({"params": p}, xx)

    y_ep, aux_ep = fwd(params_ep, x_ep)
    np.testing.assert_allclose(
        np.asarray(y_ep), np.asarray(y_ref), rtol=2e-4, atol=1e-5
    )
    assert float(aux_ep) == pytest.approx(float(aux_ref), rel=1e-4)
    # experts are physically sharded: 4 experts over model axis of 4
    assert params_ep["w1"].addressable_shards[0].data.shape[0] == 1


def test_moe_trains_expert_sharded():
    model = _model()
    params, x = _init(model)
    target = jnp.asarray(
        np.random.default_rng(1).normal(size=(16, 8)).astype(np.float32)
    )
    (g,) = setup_groups(1, model_parallel=2)
    sh = moe_ep_shardings(g, params)
    params = jax.device_put(params, sh)
    x_ep = jax.device_put(x, g.batch_sharding)
    tx = optax.adam(3e-3)
    # computation-follows-data: moments inherit the expert sharding
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            y, aux = model.apply({"params": p}, x_ep)
            return jnp.mean((y - target) ** 2) + 0.01 * aux

        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    losses = []
    for _ in range(30):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_ep_shardings_reject_indivisible_experts():
    model = MoEMLP(num_experts=3, hidden_dim=8, out_dim=4)
    params, _ = _init(model)
    (g,) = setup_groups(1, model_parallel=2)
    with pytest.raises(ValueError, match="num_experts"):
        moe_ep_shardings(g, params)


def test_moe_vae_runs_through_full_hpo_driver():
    # The model-family contract: an MoE-decoder VAE drops into the HPO
    # driver via model_builder with zero scaffolding changes — trial x
    # data parallelism from the driver, the MoE block inside.
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
    from multidisttorch_tpu.models.moe_vae import MoEVAE

    train = synthetic_mnist(96, seed=0)
    test = synthetic_mnist(32, seed=1)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        results = run_hpo(
            [
                TrialConfig(t, epochs=1, batch_size=16, hidden_dim=32,
                            latent_dim=8, seed=t)
                for t in range(2)
            ],
            train,
            test,
            out_dir=td,
            verbose=False,
            save_images=False,
            model_builder=lambda cfg: MoEVAE(
                hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim,
                num_experts=2,
            ),
        )
    for r in results:
        assert r.status == "completed"
        assert np.isfinite(r.final_train_loss)
        assert np.isfinite(r.final_test_loss)


def test_moe_vae_expert_parallel_train_step():
    # data x model submesh: experts sharded within the trial; TP-style
    # state pinning through the standard step builder.
    from multidisttorch_tpu.models.moe_vae import MoEVAE, moe_vae_ep_shardings
    from multidisttorch_tpu.train.steps import (
        create_train_state,
        make_train_step,
        state_shardings,
    )

    (g,) = setup_groups(1, model_parallel=2)  # 4 data x 2 model
    model = MoEVAE(hidden_dim=32, latent_dim=8, num_experts=2)
    tx = optax.adam(1e-3)
    state = create_train_state(
        g, model, tx, jax.random.key(0),
        param_shardings=moe_vae_ep_shardings(g, model),
    )
    # experts physically split: (2, latent, hidden) -> (1, ...) shards
    w1 = state.params["moe"]["w1"]
    assert w1.addressable_shards[0].data.shape[0] == 1
    step = make_train_step(g, model, tx, shardings=state_shardings(state))
    batch = jax.device_put(
        jnp.asarray(
            np.random.default_rng(0).uniform(0, 1, (16, 784)).astype(np.float32)
        ),
        g.batch_sharding,
    )
    losses = []
    for i in range(4):
        state, m = step(state, batch, jax.random.fold_in(jax.random.key(5), i))
        losses.append(float(m["loss_sum"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_moe_lm_trains_with_expert_parallelism():
    # The MoE transformer LM on a (data x model) submesh: experts
    # physically split over the model axis, Switch aux loss in the
    # objective, next-token loss falls on the periodic corpus.
    import optax

    from multidisttorch_tpu.models.transformer import (
        MoETransformerLM,
        moe_lm_ep_shardings,
    )
    from multidisttorch_tpu.train.lm import create_lm_state, make_lm_train_step
    from multidisttorch_tpu.train.steps import state_shardings

    (g,) = setup_groups(1, model_parallel=2)  # data 4 x model 2
    model = MoETransformerLM(
        vocab_size=16, d_model=16, num_heads=2, num_layers=2,
        num_experts=2, max_len=16,
    )
    tx = optax.adam(3e-3)
    psh = moe_lm_ep_shardings(g, model)
    state = create_lm_state(
        g, model, tx, jax.random.key(0), example_len=16, param_shardings=psh
    )
    # expert leaves physically split: E=2 over model axis of 2
    w1 = state.params["block_0"]["moe"]["w1"]
    assert w1.shape[0] == 2 and w1.addressable_shards[0].data.shape[0] == 1

    step = make_lm_train_step(
        g, model, tx, shardings=state_shardings(state)
    )
    base = np.tile(np.arange(8), 2)[:16]
    tokens = jax.device_put(
        jnp.asarray(
            np.stack([(base + r) % 16 for r in range(8)]).astype(np.int32)
        ),
        g.batch_sharding,
    )
    losses = []
    for _ in range(30):
        state, m = step(state, tokens)
        losses.append(float(m["loss"]))
    assert losses[0] > 1.5
    assert losses[-1] < losses[0] * 0.5, losses


def test_moe_lm_multi_step_matches_sequential():
    # The scan-fused LM dispatch must stay a pure fusion for the MoE
    # model too: its (logits, aux) output shape and the Switch aux term
    # flow through the shared step body (train/lm.py _build_lm_step_fn),
    # so K fused steps reproduce K sequential ones, expert sharding
    # included.
    import optax

    from multidisttorch_tpu.models.transformer import (
        MoETransformerLM,
        moe_lm_ep_shardings,
    )
    from multidisttorch_tpu.train.lm import (
        create_lm_state,
        lm_chunk_sharding,
        make_lm_multi_step,
        make_lm_train_step,
    )
    from multidisttorch_tpu.train.steps import state_shardings

    (g,) = setup_groups(1, model_parallel=2)
    model = MoETransformerLM(
        vocab_size=16, d_model=16, num_heads=2, num_layers=2,
        num_experts=2, max_len=16,
    )
    tx = optax.adam(3e-3)
    psh = moe_lm_ep_shardings(g, model)
    tokens = np.random.default_rng(4).integers(
        0, 16, (3, 8, 16), dtype=np.int32
    )

    def fresh():
        return create_lm_state(
            g, model, tx, jax.random.key(0), example_len=16,
            param_shardings=psh,
        )

    state_a = fresh()
    step = make_lm_train_step(g, model, tx, shardings=state_shardings(state_a))
    seq_losses = []
    for i in range(3):
        state_a, m = step(
            state_a, jax.device_put(jnp.asarray(tokens[i]), g.batch_sharding)
        )
        seq_losses.append(float(m["loss"]))

    state_b = fresh()
    multi = make_lm_multi_step(g, model, tx, shardings=state_shardings(state_b))
    state_b, m = multi(
        state_b, jax.device_put(jnp.asarray(tokens), lm_chunk_sharding(g))
    )
    np.testing.assert_allclose(
        np.asarray(m["loss"]), seq_losses, rtol=1e-5, atol=1e-6
    )
    assert int(state_b.step) == int(state_a.step) == 3
    # Params get a BOUNDED-divergence check, not bit parity: top-1
    # routing is discrete, so the fused and sequential programs'
    # different-but-equally-valid float reassociation can flip an
    # argmax tie and legitimately take one optimizer step down a
    # different expert (measured here: ~2e-3 worst leaf on a tie).
    # Gross fusion breakage (wrong aux handling, dropped steps) shows
    # up orders of magnitude larger — and in the loss assert above.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=1e-2
        ),
        jax.device_get(state_b.params),
        jax.device_get(state_a.params),
    )


def test_moe_lm_composes_with_sequence_parallelism():
    # EP x SP in one model: ring attention shards the context over the
    # data axis while the MoE experts shard over the model axis.
    import optax

    from multidisttorch_tpu.models.transformer import (
        MoETransformerLM,
        moe_lm_ep_shardings,
    )
    from multidisttorch_tpu.ops.ring_attention import make_ring_attention
    from multidisttorch_tpu.parallel.mesh import DATA_AXIS
    from multidisttorch_tpu.train.lm import create_lm_state, make_lm_train_step
    from multidisttorch_tpu.train.steps import state_shardings

    (g,) = setup_groups(1, model_parallel=2)
    t = 8 * g.data_size
    model = MoETransformerLM(
        vocab_size=16, d_model=16, num_heads=2, num_layers=1,
        num_experts=2, max_len=t,
        attention=make_ring_attention(g, causal=True, shard_heads=False),
    )
    tx = optax.adam(3e-3)
    state = create_lm_state(
        g, model, tx, jax.random.key(0), example_len=t,
        param_shardings=moe_lm_ep_shardings(g, model),
    )
    step = make_lm_train_step(
        g, model, tx, sequence_parallel=True,
        shardings=state_shardings(state),
    )
    base = np.tile(np.arange(8), t // 8 + 1)[:t]
    tokens = g.device_put(
        np.stack([base, (base + 3) % 16]).astype(np.int32),
        g.sharding(None, DATA_AXIS),
    )
    state, m0 = step(state, tokens)
    for _ in range(25):
        state, m = step(state, tokens)
    assert float(m["loss"]) < float(m0["loss"]) * 0.5
