"""log0 tests — parity with /root/reference/utils.py:165-174 (print0),
plus the stdlib-logging level routing (sweeps can silence per-step
chatter without losing the reference's per-trial contract)."""

import io
import logging

import pytest

from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.utils.logging import (
    LOGGER_NAME,
    log0,
    log0_enabled,
)


def test_one_line_per_group():
    groups = setup_groups(2)
    buf = io.StringIO()
    printed = [log0("epoch done", trial=g, file=buf) for g in groups]
    # Single-controller: this process owns every group head -> one line each.
    assert printed == [True, True]
    lines = buf.getvalue().strip().split("\n")
    assert len(lines) == 2
    for line in lines:
        assert line == "[0:0] epoch done"  # reference prefix shape [world:group]


def test_global_mode_prints_once():
    buf = io.StringIO()
    assert log0("hello", "world", file=buf) is True
    assert buf.getvalue() == "[0:0] hello world\n"


def test_sep_honored():
    buf = io.StringIO()
    log0("a", "b", sep="|", file=buf)
    assert buf.getvalue() == "[0:0] a|b\n"


@pytest.fixture
def _restore_level():
    logger = logging.getLogger(LOGGER_NAME)
    # Touch log0 once so the handler/level initialization has happened.
    log0("init", file=io.StringIO())
    before = logger.level
    yield logger
    logger.setLevel(before)


def test_default_level_prints_debug_chatter(_restore_level):
    # The logger defaults to DEBUG so reference-parity output (which
    # includes the DEBUG-tagged per-step lines) is unchanged by default.
    buf = io.StringIO()
    assert log0("step line", file=buf, level=logging.DEBUG) is True
    assert buf.getvalue() == "[0:0] step line\n"
    assert log0_enabled(logging.DEBUG)


def test_raised_level_silences_step_chatter(_restore_level):
    logger = _restore_level
    logger.setLevel(logging.INFO)
    buf = io.StringIO()
    # Per-step chatter (DEBUG) is dropped without touching the stream...
    assert log0("step line", file=buf, level=logging.DEBUG) is False
    assert buf.getvalue() == ""
    assert not log0_enabled(logging.DEBUG)
    # ...while the per-trial contract (INFO lines) is preserved
    # bit-for-bit.
    assert log0("====> Epoch: 1", file=buf) is True
    assert buf.getvalue() == "[0:0] ====> Epoch: 1\n"


def test_stdout_routing_through_stdlib_handler(_restore_level, capsys):
    # Without file=, emission goes through the stdlib logger's handler
    # to the CURRENT sys.stdout — prefix preserved bit-for-bit.
    assert log0("hello", "world") is True
    assert capsys.readouterr().out == "[0:0] hello world\n"


def test_driver_step_chatter_gated_by_level(_restore_level, tmp_path):
    # End-to-end: a sweep at INFO level emits the per-trial lines but
    # not one "Train Epoch:" step line — and skips the per-step device
    # sync entirely (host_syncs drops to the 2-per-epoch floor).
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo

    logger = _restore_level
    cfg = [
        TrialConfig(trial_id=0, epochs=1, batch_size=16, hidden_dim=16,
                    latent_dim=4, log_interval=1),
        TrialConfig(trial_id=1, epochs=1, batch_size=16, hidden_dim=16,
                    latent_dim=4, log_interval=1),
    ]
    data = synthetic_mnist(48, seed=0)
    logger.setLevel(logging.INFO)
    results = run_hpo(
        cfg, data, data, num_groups=2, out_dir=str(tmp_path),
        save_images=False,
    )
    # log_interval=1 would have logged (and synced) every one of the 3
    # steps per trial; at INFO those syncs are skipped wholesale.
    assert all(r.host_syncs == 2 for r in results)
