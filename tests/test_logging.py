"""log0 tests — parity with /root/reference/utils.py:165-174 (print0)."""

import io

from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.utils.logging import log0


def test_one_line_per_group():
    groups = setup_groups(2)
    buf = io.StringIO()
    printed = [log0("epoch done", trial=g, file=buf) for g in groups]
    # Single-controller: this process owns every group head -> one line each.
    assert printed == [True, True]
    lines = buf.getvalue().strip().split("\n")
    assert len(lines) == 2
    for line in lines:
        assert line == "[0:0] epoch done"  # reference prefix shape [world:group]


def test_global_mode_prints_once():
    buf = io.StringIO()
    assert log0("hello", "world", file=buf) is True
    assert buf.getvalue() == "[0:0] hello world\n"


def test_sep_honored():
    buf = io.StringIO()
    log0("a", "b", sep="|", file=buf)
    assert buf.getvalue() == "[0:0] a|b\n"
