"""Pipeline parallelism: numerical parity with the sequential model,
training through the pipeline, and DP x PP composition.

The reference has no pipeline parallelism (SURVEY.md §2c); these tests
validate the from-scratch GPipe-style implementation in
``parallel/pipeline.py`` on the 8-virtual-device harness.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multidisttorch_tpu.parallel.mesh import (
    DATA_AXIS,
    PIPE_AXIS,
    setup_groups,
)
from multidisttorch_tpu.parallel.pipeline import (
    pack_stage_params,
    pipeline_apply,
    pipeline_apply_stages,
    sequential_reference,
    sequential_stages_reference,
    stage_params_sharding,
    unpack_stage_params,
)

WIDTH = 16


def mlp_stage(params, x):
    """One equal-width residual MLP stage: x + relu(x @ w + b)."""
    return x + jax.nn.relu(x @ params["w"] + params["b"])


def make_stacked_params(num_stages, key, width=WIDTH):
    kw, kb = jax.random.split(key)
    return {
        "w": jax.random.normal(kw, (num_stages, width, width)) * 0.1,
        "b": jax.random.normal(kb, (num_stages, width)) * 0.01,
    }


def test_mesh_carve_with_pipe_axis():
    (trial,) = setup_groups(1, pipeline_parallel=4)
    assert trial.pipe_size == 4
    assert trial.data_size == 2
    assert trial.model_size == 1
    assert dict(trial.mesh.shape) == {DATA_AXIS: 2, PIPE_AXIS: 4}
    # pipe neighbors are adjacent device positions (model_parallel=1)
    grid = trial.mesh.devices
    assert [d.id for d in grid[0]] == [0, 1, 2, 3]


def test_pipeline_matches_sequential():
    (trial,) = setup_groups(2, pipeline_parallel=4)[:1]
    params = make_stacked_params(4, jax.random.key(0))
    params = jax.device_put(params, stage_params_sharding(trial))
    batch = jax.random.normal(jax.random.key(1), (8, WIDTH))

    apply = pipeline_apply(trial, mlp_stage, num_microbatches=4)
    got = jax.jit(apply)(params, batch)
    want = sequential_reference(mlp_stage, jax.device_get(params), batch)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_pipeline_grads_match_sequential():
    (trial,) = setup_groups(1, pipeline_parallel=8)
    params = make_stacked_params(8, jax.random.key(2))
    params = jax.device_put(params, stage_params_sharding(trial))
    batch = jax.random.normal(jax.random.key(3), (16, WIDTH))
    target = jax.random.normal(jax.random.key(4), (16, WIDTH))

    apply = pipeline_apply(trial, mlp_stage, num_microbatches=4)

    def pipe_loss(p):
        return jnp.mean((apply(p, batch) - target) ** 2)

    def seq_loss(p):
        return jnp.mean(
            (sequential_reference(mlp_stage, p, batch) - target) ** 2
        )

    g_pipe = jax.jit(jax.grad(pipe_loss))(params)
    g_seq = jax.grad(seq_loss)(jax.device_get(params))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        jax.device_get(g_pipe),
        g_seq,
    )


def test_pipeline_training_decreases_loss_dp_x_pp():
    """Train a stage-sharded MLP on a (data=2, pipe=4) submesh: gradients
    flow through the ppermute schedule and are reduced over the data
    axis by GSPMD — DP x PP from one jitted program."""
    import optax

    (trial,) = setup_groups(1, pipeline_parallel=4)
    assert trial.data_size == 2 and trial.pipe_size == 4
    params = make_stacked_params(4, jax.random.key(5))
    params = jax.device_put(params, stage_params_sharding(trial))
    batch = jax.random.normal(jax.random.key(6), (32, WIDTH))
    target = jnp.tanh(batch @ jax.random.normal(jax.random.key(7), (WIDTH, WIDTH)))

    apply = pipeline_apply(trial, mlp_stage, num_microbatches=8)
    tx = optax.adam(1e-2)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            return jnp.mean((apply(p, batch) - target) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses
    # stage weights actually live one-per-pipe-device
    shard = params["w"].addressable_shards[0]
    assert shard.data.shape[0] == 1


def test_pipeline_rejects_shape_changing_stage():
    (trial,) = setup_groups(1, pipeline_parallel=4)
    params = {"w": jnp.zeros((4, WIDTH, WIDTH // 2))}
    batch = jnp.zeros((8, WIDTH))
    apply = pipeline_apply(
        trial, lambda p, x: x @ p["w"], num_microbatches=2
    )
    with pytest.raises(ValueError, match="preserve activation shape"):
        jax.jit(apply)(params, batch)


def test_pipeline_rejects_wrong_stage_count():
    (trial,) = setup_groups(1, pipeline_parallel=4)
    params = make_stacked_params(3, jax.random.key(0))
    apply = pipeline_apply(trial, mlp_stage, num_microbatches=2)
    with pytest.raises(ValueError, match="leading axis 3"):
        apply(params, jnp.zeros((8, WIDTH)))


def test_pipeline_requires_pipe_axis():
    (trial,) = setup_groups(1)
    with pytest.raises(ValueError, match="no 'pipe' axis"):
        pipeline_apply(trial, mlp_stage, num_microbatches=2)


# --- heterogeneous stages (pipeline_apply_stages): real models --------------


def _hetero_stage_fns_params(key):
    """A deliberately shape-changing 4-stage chain: widths 12→20→6→6→3."""
    widths = [12, 20, 6, 6, 3]
    fns, params = [], []
    keys = jax.random.split(key, len(widths) - 1)
    for i, k in enumerate(keys):
        fns.append(lambda p, x: jax.nn.tanh(x @ p["w"] + p["b"]))
        params.append(
            {
                "w": jax.random.normal(k, (widths[i], widths[i + 1])) * 0.3,
                "b": jnp.zeros((widths[i + 1],)),
            }
        )
    return fns, params


def test_pack_unpack_roundtrip():
    _, params = _hetero_stage_fns_params(jax.random.key(0))
    packed, metas = pack_stage_params(params)
    assert packed.shape == (4, max(12 * 20 + 20, 20 * 6 + 6))
    for s, (tree, meta) in enumerate(zip(params, metas)):
        got = unpack_stage_params(packed[s], meta)
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            got,
            tree,
        )


def test_pack_rejects_non_float32():
    with pytest.raises(ValueError, match="float32"):
        pack_stage_params([{"w": jnp.zeros((2, 2), jnp.bfloat16)}])


def test_hetero_pipeline_matches_sequential():
    (trial,) = setup_groups(1, pipeline_parallel=4)
    fns, params = _hetero_stage_fns_params(jax.random.key(0))
    apply, packed = pipeline_apply_stages(
        trial, fns, params, num_microbatches=4
    )
    packed = jax.device_put(packed, stage_params_sharding(trial))
    batch = jax.random.normal(jax.random.key(1), (16, 12))
    got = jax.jit(apply)(packed, batch)
    want = sequential_stages_reference(fns, params, batch)
    assert got.shape == (16, 3)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_hetero_pipeline_grads_match_sequential():
    (trial,) = setup_groups(1, pipeline_parallel=4)
    fns, params = _hetero_stage_fns_params(jax.random.key(2))
    apply, packed0 = pipeline_apply_stages(
        trial, fns, params, num_microbatches=4
    )
    packed = jax.device_put(packed0, stage_params_sharding(trial))
    batch = jax.random.normal(jax.random.key(3), (16, 12))
    target = jax.random.normal(jax.random.key(4), (16, 3))

    g_pipe = jax.jit(
        jax.grad(lambda p: jnp.mean((apply(p, batch) - target) ** 2))
    )(packed)

    # Same loss via the sequential reference, differentiated w.r.t. the
    # packed array through the same pack/unpack bijection.
    _, metas = pack_stage_params(params)

    def seq_loss(packed_arr):
        trees = [
            unpack_stage_params(packed_arr[s], m) for s, m in enumerate(metas)
        ]
        return jnp.mean(
            (sequential_stages_reference(fns, trees, batch) - target) ** 2
        )

    g_seq = jax.grad(seq_loss)(packed0)
    np.testing.assert_allclose(
        np.asarray(g_pipe), np.asarray(g_seq), rtol=1e-4, atol=1e-6
    )


def test_resnet_pipeline_training_decreases_loss_dp_x_pp():
    """VERDICT r3 item 4's bar: a SHIPPED model (the repo's ResNet,
    BASELINE.md config 4) trains across pipeline stages with decreasing
    loss under DP x PP — heterogeneous activation shapes (stem chunk
    emits (16,16,8), head chunk emits (10,) logits) through the padded
    carry, Adam running directly on the packed stage params."""
    import optax

    from multidisttorch_tpu.models.resnet import ResNet, resnet_pipeline_stages
    from multidisttorch_tpu.ops.losses import softmax_cross_entropy_mean

    (trial,) = setup_groups(1, pipeline_parallel=2)  # data=4 x pipe=2
    assert trial.data_size == 4 and trial.pipe_size == 2

    model = ResNet(stage_sizes=(1, 1), base_channels=8, image_hw=16)
    stages = resnet_pipeline_stages(model, 2)
    rngs = jax.random.split(jax.random.key(0), 2)
    dummies = [jnp.zeros((1, 16 * 16 * 3), jnp.float32)]
    params = []
    for st, rng in zip(stages, rngs):
        params.append(st.init({"params": rng}, dummies[-1])["params"])
        dummies.append(st.apply({"params": params[-1]}, dummies[-1]))
    fns = [
        (lambda st: lambda p, x: st.apply({"params": p}, x))(st)
        for st in stages
    ]

    apply, packed = pipeline_apply_stages(trial, fns, params, num_microbatches=4)
    packed = jax.device_put(packed, stage_params_sharding(trial))

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.uniform(0, 1, (32, 16 * 16 * 3)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, (32,)).astype(np.int32))

    tx = optax.adam(1e-2)
    opt_state = tx.init(packed)

    @jax.jit
    def step(packed, opt_state):
        def loss_fn(p):
            return softmax_cross_entropy_mean(apply(p, images), labels)

        loss, grads = jax.value_and_grad(loss_fn)(packed)
        updates, opt_state = tx.update(grads, opt_state, packed)
        return optax.apply_updates(packed, updates), opt_state, loss

    losses = []
    for _ in range(15):
        packed, opt_state, loss = step(packed, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses
    # each pipe device physically holds one stage's packed row
    assert packed.addressable_shards[0].data.shape[0] == 1
    # parity of the pipelined forward with running the stages directly
    got = apply(packed, images)
    packed_host = jax.device_get(packed)
    _, metas = pack_stage_params(params)
    trees = [unpack_stage_params(packed_host[s], m) for s, m in enumerate(metas)]
    want = sequential_stages_reference(fns, trees, images)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_pipelined_lm_matches_plain_model():
    # TransformerLM blocks staged over (data=4 x pipe=2): the pipelined
    # forward must equal the plain model's logits on identical params.
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.train.lm_pipeline import (
        make_pipelined_lm,
        stage_params_sharding,
    )

    (trial,) = setup_groups(1, pipeline_parallel=2)
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=2, max_len=16
    )
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (8, 16), dtype=np.int32)
    )
    params = model.init({"params": jax.random.key(0)}, tokens)["params"]

    apply, packed, outer = make_pipelined_lm(
        trial, model, params, num_microbatches=2
    )
    packed = jax.device_put(packed, stage_params_sharding(trial))
    got = apply(packed, outer, tokens)
    want = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_pipelined_lm_accepts_flash_and_rejects_ring():
    # The staging gate checks carries_collectives by VALUE: the flash
    # callable (collective-free pallas_call, marked False) stages fine
    # and matches the dense-staged forward exactly; a ring callable
    # (shard_map + ppermute, marked True) is rejected with the
    # documented error.
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.ops.pallas_attention import make_flash_attention
    from multidisttorch_tpu.ops.ring_attention import make_ring_attention
    from multidisttorch_tpu.train.lm_pipeline import (
        make_pipelined_lm,
        stage_params_sharding,
    )

    (trial,) = setup_groups(1, pipeline_parallel=2)
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=2, max_len=16
    )
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, (8, 16), dtype=np.int32)
    )
    params = model.init({"params": jax.random.key(0)}, tokens)["params"]
    apply, packed, outer = make_pipelined_lm(
        trial, model, params, num_microbatches=2,
        attention=make_flash_attention(causal=True),
    )
    packed = jax.device_put(packed, stage_params_sharding(trial))
    got = apply(packed, outer, tokens)
    want = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )
    with pytest.raises(ValueError, match="collective-free"):
        make_pipelined_lm(
            trial, model, params, num_microbatches=2,
            attention=make_ring_attention(trial, causal=True),
        )


def test_pipelined_lm_bf16_close_to_plain_model():
    # A bf16 model keeps its compute dtype inside the stages; the f32
    # inter-stage carry costs one cast per boundary, so parity is
    # approximate at bf16 storage precision, not bitwise.
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.train.lm_pipeline import (
        make_pipelined_lm,
        stage_params_sharding,
    )

    (trial,) = setup_groups(1, pipeline_parallel=2)
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=2, max_len=16,
        dtype=jnp.bfloat16,
    )
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 32, (8, 16), dtype=np.int32)
    )
    params = model.init({"params": jax.random.key(0)}, tokens)["params"]
    apply, packed, outer = make_pipelined_lm(
        trial, model, params, num_microbatches=2
    )
    packed = jax.device_put(packed, stage_params_sharding(trial))
    got = apply(packed, outer, tokens)
    want = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=5e-2, atol=5e-2
    )


def test_pipelined_lm_trains_dp_x_pp():
    # One jitted Adam step over (packed, outer) — DP x PP from a single
    # program; next-token loss falls on the periodic corpus.
    import optax

    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.train.lm import lm_loss_mean
    from multidisttorch_tpu.train.lm_pipeline import (
        make_pipelined_lm,
        stage_params_sharding,
    )

    (trial,) = setup_groups(1, pipeline_parallel=2)
    model = TransformerLM(
        vocab_size=16, d_model=16, num_heads=2, num_layers=2, max_len=16
    )
    base = np.tile(np.arange(8), 2)[:16]
    tokens = jnp.asarray(
        np.stack([(base + r) % 16 for r in range(8)]).astype(np.int32)
    )
    params = model.init({"params": jax.random.key(0)}, tokens)["params"]
    apply, packed, outer = make_pipelined_lm(
        trial, model, params, num_microbatches=2
    )
    packed = jax.device_put(packed, stage_params_sharding(trial))
    tx = optax.adam(3e-3)
    opt = tx.init((packed, outer))

    @jax.jit
    def step(packed_arr, outer_params, opt):
        loss, grads = jax.value_and_grad(
            lambda po: lm_loss_mean(apply(po[0], po[1], tokens), tokens)
        )((packed_arr, outer_params))
        upd, opt = tx.update(grads, opt, (packed_arr, outer_params))
        new = optax.apply_updates((packed_arr, outer_params), upd)
        return new[0], new[1], opt, loss

    losses = []
    for _ in range(30):
        packed, outer, opt, loss = step(packed, outer, opt)
        losses.append(float(loss))
    assert losses[0] > 1.5
    assert losses[-1] < losses[0] * 0.5, losses
    # each pipe device holds one stage's packed row
    assert packed.addressable_shards[0].data.shape[0] == 1


@pytest.mark.parametrize("model_parallel", [1, 2])
def test_pipelined_lm_rejects_ring_attention(model_parallel):
    # Any ring callable — sequence-sharded (1-D) or head-sharded (2-D)
    # — carries shard_map collectives and must be rejected, not staged.
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.ops.ring_attention import make_ring_attention
    from multidisttorch_tpu.train.lm_pipeline import make_pipelined_lm

    (trial,) = setup_groups(
        1, pipeline_parallel=2, model_parallel=model_parallel
    )
    ring = make_ring_attention(trial, causal=True)
    model = TransformerLM(
        vocab_size=8, d_model=8, num_heads=2, num_layers=2, max_len=8,
        attention=ring,
    )
    params = model.init(
        {"params": jax.random.key(0)},
        jnp.zeros((1, 8), jnp.int32),
    )["params"]
    with pytest.raises(ValueError, match="collective-free"):
        make_pipelined_lm(trial, model, params, num_microbatches=2)


def test_pipelined_lm_rejects_overlong_sequence():
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.train.lm_pipeline import (
        make_pipelined_lm,
        stage_params_sharding,
    )

    (trial,) = setup_groups(1, pipeline_parallel=2)
    model = TransformerLM(
        vocab_size=8, d_model=8, num_heads=2, num_layers=2, max_len=16
    )
    params = model.init(
        {"params": jax.random.key(0)}, jnp.zeros((1, 16), jnp.int32)
    )["params"]
    apply, packed, outer = make_pipelined_lm(
        trial, model, params, num_microbatches=2
    )
    packed = jax.device_put(packed, stage_params_sharding(trial))
    long_tokens = jnp.zeros((8, 32), jnp.int32)  # 32 > max_len=16
    with pytest.raises(ValueError, match="exceeds max_len"):
        apply(packed, outer, long_tokens)


def test_hetero_pipeline_rejects_wrong_stage_count():
    (trial,) = setup_groups(1, pipeline_parallel=4)
    fns, params = _hetero_stage_fns_params(jax.random.key(0))
    with pytest.raises(ValueError, match="stage_fns"):
        pipeline_apply_stages(trial, fns[:3], params[:3], num_microbatches=2)


def test_three_axis_carve_dp_pp_tp():
    """(data, pipe, model) 3-D carve: 8 = 2 x 2 x 2."""
    (trial,) = setup_groups(1, pipeline_parallel=2, model_parallel=2)
    assert dict(trial.mesh.shape) == {
        DATA_AXIS: 2,
        PIPE_AXIS: 2,
        "model": 2,
    }
    assert (trial.data_size, trial.pipe_size, trial.model_size) == (2, 2, 2)
