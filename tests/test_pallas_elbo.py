"""Fused Pallas ELBO kernel: value + gradient parity with the jnp path
(interpreter mode on CPU; same code compiles for real TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multidisttorch_tpu.ops.losses import elbo_loss_sum
from multidisttorch_tpu.ops.pallas_elbo import fused_elbo_loss_sum


@pytest.fixture(scope="module")
def arrays():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(0, 2, (16, 784)).astype(np.float32))
    x = jnp.asarray(rng.uniform(0, 1, (16, 784)).astype(np.float32))
    mu = jnp.asarray(rng.normal(0, 1, (16, 20)).astype(np.float32))
    logvar = jnp.asarray(rng.normal(0, 0.5, (16, 20)).astype(np.float32))
    return logits, x, mu, logvar


def test_value_parity(arrays):
    logits, x, mu, logvar = arrays
    fused = float(fused_elbo_loss_sum(logits, x, mu, logvar, 1.0))
    plain = float(elbo_loss_sum(logits, x, mu, logvar, 1.0))
    assert fused == pytest.approx(plain, rel=1e-5)


def test_value_parity_beta(arrays):
    logits, x, mu, logvar = arrays
    fused = float(fused_elbo_loss_sum(logits, x, mu, logvar, 4.0))
    plain = float(elbo_loss_sum(logits, x, mu, logvar, 4.0))
    assert fused == pytest.approx(plain, rel=1e-5)


def test_gradient_parity(arrays):
    logits, x, mu, logvar = arrays

    g_fused = jax.grad(
        lambda l, m, lv: fused_elbo_loss_sum(l, x, m, lv, 2.0), argnums=(0, 1, 2)
    )(logits, mu, logvar)
    g_plain = jax.grad(
        lambda l, m, lv: elbo_loss_sum(l, x, m, lv, 2.0), argnums=(0, 1, 2)
    )(logits, mu, logvar)
    for a, b in zip(g_fused, g_plain):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_multi_block_grid_matches_plain(monkeypatch):
    # VERDICT r3 item 5: the kernel must tile over batch blocks instead
    # of staging whole operands in VMEM. Shrink the budget so a modest
    # batch needs a multi-step grid, and check value+grad parity through
    # the SMEM scalar accumulation across grid steps.
    from multidisttorch_tpu.ops import pallas_elbo

    monkeypatch.setattr(pallas_elbo, "_VMEM_BUDGET_BYTES", 64 * 1024)
    rng = np.random.default_rng(7)
    b, d, lat = 96, 784, 20
    logits = jnp.asarray(rng.normal(0, 2, (b, d)).astype(np.float32))
    x = jnp.asarray(rng.uniform(0, 1, (b, d)).astype(np.float32))
    mu = jnp.asarray(rng.normal(0, 1, (b, lat)).astype(np.float32))
    logvar = jnp.asarray(rng.normal(0, 0.5, (b, lat)).astype(np.float32))
    assert pallas_elbo._block_rows(logits, x, mu, logvar) < b  # grid > 1

    fused = float(fused_elbo_loss_sum(logits, x, mu, logvar, 1.5))
    plain = float(elbo_loss_sum(logits, x, mu, logvar, 1.5))
    assert fused == pytest.approx(plain, rel=1e-5)

    g_fused = jax.grad(
        lambda l, m, lv: fused_elbo_loss_sum(l, x, m, lv, 1.5),
        argnums=(0, 1, 2),
    )(logits, mu, logvar)
    g_plain = jax.grad(
        lambda l, m, lv: elbo_loss_sum(l, x, m, lv, 1.5), argnums=(0, 1, 2)
    )(logits, mu, logvar)
    for a, b_ in zip(g_fused, g_plain):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=1e-5, atol=1e-6
        )


def test_block_rows_divides_batch():
    from multidisttorch_tpu.ops.pallas_elbo import _block_rows

    for batch in (1, 7, 96, 128, 10000):
        for dt in (jnp.float32, jnp.bfloat16):
            args = (
                jnp.zeros((batch, 784), dt),
                jnp.zeros((batch, 784), jnp.float32),
                jnp.zeros((batch, 20), dt),
                jnp.zeros((batch, 20), dt),
            )
            bb = _block_rows(*args)
            assert 1 <= bb <= batch and batch % bb == 0
    # bf16 operands halve the bytes per row -> at least as many rows
    # per grid step as f32 under the same VMEM budget.
    f32 = (jnp.zeros((10000, 784)), jnp.zeros((10000, 784)),
           jnp.zeros((10000, 20)), jnp.zeros((10000, 20)))
    b16 = tuple(a.astype(jnp.bfloat16) for a in f32[:1]) + (f32[1],) + tuple(
        a.astype(jnp.bfloat16) for a in f32[2:]
    )
    assert _block_rows(*b16) >= _block_rows(*f32)


def test_bf16_inputs_match_plain(arrays):
    # The TPU train path feeds bf16 activations (logits/mu/logvar) with
    # f32 targets; the first real-TPU bench run crashed on exactly this
    # mix ("Invalid dtype for `swap`: f32 ref, bf16 value"). The kernel
    # must accept mixed dtypes, reduce in f32, and hand back cotangents
    # in each primal's own dtype.
    logits, x, mu, logvar = arrays
    lb, mb, vb = (a.astype(jnp.bfloat16) for a in (logits, mu, logvar))

    fused = float(fused_elbo_loss_sum(lb, x, mb, vb, 1.0))
    plain = float(
        elbo_loss_sum(
            lb.astype(jnp.float32), x,
            mb.astype(jnp.float32), vb.astype(jnp.float32), 1.0,
        )
    )
    assert fused == pytest.approx(plain, rel=1e-5)

    g_fused = jax.grad(
        lambda l, m, lv: fused_elbo_loss_sum(l, x, m, lv, 1.0),
        argnums=(0, 1, 2),
    )(lb, mb, vb)
    g_plain = jax.grad(
        lambda l, m, lv: elbo_loss_sum(l, x, m, lv, 1.0), argnums=(0, 1, 2)
    )(logits, mu, logvar)
    for got, ref, primal in zip(g_fused, g_plain, (lb, mb, vb)):
        assert got.dtype == primal.dtype
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(ref),
            rtol=2e-2, atol=2e-2,  # bf16 storage precision
        )


def test_bf16_multi_block_accumulator(monkeypatch):
    # The round-4 hardware failure ("Invalid dtype for `swap`: Ref
    # float32 vs value bfloat16", BENCH_r05.json's embedded r4 payload)
    # lived in the fwd kernel's SMEM accumulator when bf16 operands
    # crossed a multi-block grid — the one path the earlier bf16 test
    # (single block) and multi-block test (f32) each missed. Interpret
    # mode can't reproduce Mosaic's swap dtype check, so this pins the
    # code-level contract instead: bf16 inputs + shrunken VMEM budget
    # force the grid>1 accumulate store, and values must still match the
    # plain path (the explicit .astype(out_ref.dtype) casts keep the
    # stored dtype equal to the ref dtype by construction — the same
    # program Mosaic compiles; bench_kernel_smoke banks the hardware
    # proof each TPU window).
    from multidisttorch_tpu.ops import pallas_elbo

    monkeypatch.setattr(pallas_elbo, "_VMEM_BUDGET_BYTES", 64 * 1024)
    rng = np.random.default_rng(11)
    b, d, lat = 96, 784, 20
    logits = jnp.asarray(rng.normal(0, 2, (b, d)), jnp.bfloat16)
    x = jnp.asarray(rng.uniform(0, 1, (b, d)).astype(np.float32))
    mu = jnp.asarray(rng.normal(0, 1, (b, lat)), jnp.bfloat16)
    logvar = jnp.asarray(rng.normal(0, 0.5, (b, lat)), jnp.bfloat16)
    assert pallas_elbo._block_rows(logits, x, mu, logvar) < b  # grid > 1

    fused = float(fused_elbo_loss_sum(logits, x, mu, logvar, 1.0))
    plain = float(
        elbo_loss_sum(
            logits.astype(jnp.float32), x,
            mu.astype(jnp.float32), logvar.astype(jnp.float32), 1.0,
        )
    )
    assert fused == pytest.approx(plain, rel=1e-5)

    g_fused = jax.grad(
        lambda l, m, lv: fused_elbo_loss_sum(l, x, m, lv, 1.0),
        argnums=(0, 1, 2),
    )(logits, mu, logvar)
    for got, primal in zip(g_fused, (logits, mu, logvar)):
        # cotangents come back at each primal's own storage dtype
        assert got.dtype == primal.dtype
        assert bool(jnp.all(jnp.isfinite(got.astype(jnp.float32))))


def test_works_under_jit_and_scaling(arrays):
    logits, x, mu, logvar = arrays

    @jax.jit
    def f(l):
        return fused_elbo_loss_sum(l, x, mu, logvar, 1.0) * 2.0

    expected = 2.0 * float(elbo_loss_sum(logits, x, mu, logvar, 1.0))
    assert float(f(logits)) == pytest.approx(expected, rel=1e-5)
    # cotangent scaling flows through the custom VJP
    g = jax.grad(f)(logits)
    g_ref = jax.grad(lambda l: 2.0 * elbo_loss_sum(l, x, mu, logvar, 1.0))(logits)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), rtol=1e-5, atol=1e-6
    )


def test_fused_loss_in_train_step_matches_plain():
    # The use_fused_loss train-step path must train identically.
    import optax

    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import (
        create_train_state,
        make_train_step,
    )

    model = VAE(hidden_dim=16, latent_dim=4)
    tx = optax.adam(1e-3)
    trial = setup_groups(8)[0]
    batch = jnp.asarray(
        np.random.default_rng(5).uniform(0, 1, (8, 784)).astype(np.float32)
    )
    key = jax.random.key(0)
    s1 = create_train_state(trial, model, tx, jax.random.key(1))
    s2 = create_train_state(trial, model, tx, jax.random.key(1))
    s1, m1 = make_train_step(trial, model, tx)(s1, batch, key)
    s2, m2 = make_train_step(trial, model, tx, use_fused_loss=True)(
        s2, batch, key
    )
    assert float(m1["loss_sum"]) == pytest.approx(
        float(m2["loss_sum"]), rel=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s1.params,
        s2.params,
    )


def test_fused_loss_sharded_submesh_matches_plain():
    # Multi-device submesh: the fused loss runs per-shard under
    # shard_map + psum; training must match the plain path.
    import optax

    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import (
        create_train_state,
        make_train_step,
    )

    model = VAE(hidden_dim=16, latent_dim=4)
    tx = optax.adam(1e-3)
    trial = setup_groups(2)[0]  # 4 devices
    batch = jnp.asarray(
        np.random.default_rng(6).uniform(0, 1, (16, 784)).astype(np.float32)
    )
    key = jax.random.key(0)
    s1 = create_train_state(trial, model, tx, jax.random.key(1))
    s2 = create_train_state(trial, model, tx, jax.random.key(1))
    s1, m1 = make_train_step(trial, model, tx)(s1, batch, key)
    s2, m2 = make_train_step(trial, model, tx, use_fused_loss=True)(
        s2, batch, key
    )
    assert float(m1["loss_sum"]) == pytest.approx(
        float(m2["loss_sum"]), rel=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        ),
        s1.params,
        s2.params,
    )
