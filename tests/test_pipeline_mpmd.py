"""MPMD pipeline parallelism + ZeRO sharded weight update (ISSUE 12).

Covers the two tentpole mechanisms and their service threading:

- ``parallel/fsdp.py`` sharded-update mode: losses bit/tolerance-equal
  to the replicated reference, per-device optimizer bytes ~1/n_data;
- gradient-accumulation/microbatch parity (the pipeline schedule's
  correctness foundation): a scan-of-microbatches step equals the
  full-batch step within a pinned tolerance on XLA:CPU;
- ``parallel/pipeline.py`` MpmdPipeline: cross-submesh GPipe schedule
  bit-equal to the single-mesh reference step, measured bubble equal
  to the analytic (S-1)/(S-1+M) model, per-stage programs registered
  as ``pipe_*`` kinds;
- ``service/scheduler.py`` multi-block placement: all-or-nothing
  vector allocation, deadlock-free rollback, fair-share charged the
  SUM of stage slices (±10% property test with mixed traffic);
- the service runtime placing and completing a 2-stage pipelined
  trial end to end, with per-stage checkpoint/restore.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.fsdp import (
    optimizer_state_bytes,
    place_zero_state,
    zero_update_shardings,
)
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, setup_groups
from multidisttorch_tpu.parallel.pipeline import (
    MpmdPipeline,
    analytic_bubble_fraction,
    make_mpmd_reference_step,
    make_vae_stage_eval_fns,
    make_vae_stage_fns,
    merge_stage_params,
    split_stage_params,
)
from multidisttorch_tpu.service.scheduler import (
    FairShareScheduler,
    PendingTrial,
    SlicePool,
)
from multidisttorch_tpu.train.steps import (
    accumulate_gradients,
    build_train_state,
    create_train_state,
    make_train_step,
)

pytestmark = pytest.mark.pipeline

# The pinned parity tolerance (docs/PARALLEL.md): XLA:CPU reassociates
# the cross-device grad reduction between the replicated and
# reduce-scatter schedules; measured drift is 0 on this toolchain but
# the contract allows last-ulp wiggle.
PARITY_RTOL = 2e-6


def _pool_state(pool: SlicePool) -> list:
    return list(pool._free)


def _entry(
    tenant,
    *,
    sub_id,
    size=1,
    sizes=None,
    cost=None,
    bucket=None,
    priority=1,
):
    total = sum(sizes) if sizes is not None else size
    return PendingTrial(
        sub_id=sub_id,
        tenant=tenant,
        priority=priority,
        cfg=None,
        bucket=bucket if bucket is not None else ("unstackable", sub_id),
        size=total,
        cost=float(cost if cost is not None else 10.0 * total),
        submit_ts=0.0,
        sizes=tuple(sizes) if sizes is not None else None,
    )


class TestSlicePoolMulti:
    def test_all_or_nothing_success_stage_order(self):
        pool = SlicePool(8)
        starts = pool.alloc_multi([2, 2])
        assert starts is not None and len(starts) == 2
        # disjoint blocks
        spans = [set(range(s, s + 2)) for s in starts]
        assert not (spans[0] & spans[1])
        assert pool.free_total == 4

    def test_rollback_leaves_pool_untouched(self):
        pool = SlicePool(6)
        # fragment: occupy slices 1 and 4 -> free runs [0,1],[2,2],[5,1]
        assert pool.alloc_at(1, 1) and pool.alloc_at(4, 1)
        before = _pool_state(pool)
        # needs a 3-run: impossible -> must roll the 2-run claim back
        assert pool.alloc_multi([2, 3]) is None
        assert _pool_state(pool) == before

    def test_largest_first_claims_survive_fragmentation(self):
        pool = SlicePool(6)
        # free runs [0,3] and [4,2] (slice 3 occupied)
        assert pool.alloc_at(3, 1)
        # stage order (1, 3): naive stage-order allocation would put
        # the 1-slice stage at 0 and have no 3-run left; largest-first
        # claims the 3-run for stage 1 first.
        starts = pool.alloc_multi([1, 3])
        assert starts is not None
        assert starts[1] == 0 and starts[0] == 4

    def test_bad_sizes_raise(self):
        pool = SlicePool(4)
        with pytest.raises(ValueError):
            pool.alloc_multi([])
        with pytest.raises(ValueError):
            pool.alloc_multi([0, 2])


class TestVectorScheduling:
    def test_vector_placed_all_or_nothing_with_blocks(self):
        pool = SlicePool(8)
        sched = FairShareScheduler()
        sched.push(_entry("t", sub_id="v1", sizes=(2, 2)))
        got = sched.schedule(pool)
        assert len(got) == 1
        p = got[0]
        assert p.blocks is not None and len(p.blocks) == 2
        assert p.size == 4
        assert pool.free_total == 4

    def test_vector_blocked_stamps_starvation_clock(self):
        pool = SlicePool(4)
        # fragment so no two 2-runs exist: occupy slice 1
        assert pool.alloc_at(1, 1)
        sched = FairShareScheduler()
        e = _entry("t", sub_id="v1", sizes=(2, 2))
        sched.push(e)
        assert sched.schedule(pool, now=100.0) == []
        assert e.blocked_since == 100.0
        # pool untouched by the failed attempt
        assert pool.free_total == 3
        # free the fragmenting slice: now placeable
        pool.free(1, 1)
        got = sched.schedule(pool, now=101.0)
        assert len(got) == 1 and e.blocked_since is None

    def test_vector_never_copacks(self):
        pool = SlicePool(8)
        sched = FairShareScheduler()
        sched.push(_entry("t", sub_id="v1", sizes=(1, 1), bucket="b"))
        sched.push(_entry("t", sub_id="v2", sizes=(1, 1), bucket="b"))
        got = sched.schedule(pool, max_lanes=4)
        assert len(got) == 2
        assert all(len(p.members) == 1 for p in got)

    def test_fair_share_charges_sum_of_stage_slices(self):
        """The vtime fix: a 2-stage whale (2x1-slice blocks) must be
        charged BOTH blocks' cost — equal-weight tenants submitting
        vector vs single traffic converge to equal contended cost
        within the ±10% share bound."""
        rng = np.random.RandomState(7)
        pool = SlicePool(4)
        sched = FairShareScheduler()
        live = []  # (start, size) blocks to free as capacity churns
        serial = [0]

        def submit(tenant, k):
            # Tenant A ships 2-stage vector trials (1 slice per
            # stage), tenant B single 2-slice trials: both occupy 2
            # slices per placement. Cost = steps x total slices (the
            # runtime's rule), steps identical — so equal weights must
            # yield ~equal contended cost.
            serial[0] += 1
            if tenant == "vec":
                return _entry(
                    tenant, sub_id=f"v{serial[0]}", sizes=(1, 1),
                    cost=10.0 * 2,
                )
            return _entry(
                tenant, sub_id=f"s{serial[0]}", size=2, cost=10.0 * 2
            )

        for t in ("vec", "single"):
            for k in range(3):
                sched.push(submit(t, k))
        for round_no in range(200):
            placed = sched.schedule(pool, now=float(round_no))
            for p in placed:
                live.append(p)
            # random completion churn: free one placement at a time
            if live and (rng.rand() < 0.8 or pool.free_total == 0):
                p = live.pop(rng.randint(len(live)))
                for start, size in (
                    p.blocks if p.blocks else [(p.start, p.size)]
                ):
                    pool.free(start, size)
            # keep both backlogs nonempty (contended throughout)
            for t in ("vec", "single"):
                while (
                    sum(
                        1
                        for e in sched.pending_entries()
                        if e.tenant == t
                    )
                    < 2
                ):
                    sched.push(submit(t, 0))
        report = sched.fair_share_report()
        for t in ("vec", "single"):
            ratio = report[t]["ratio_to_weight"]
            assert ratio is not None and abs(ratio - 1.0) <= 0.10, report


class TestZeroUpdate:
    def _mesh(self):
        return setup_groups(2)[0]  # 4 devices

    def test_losses_match_replicated_reference(self):
        trial = self._mesh()
        model = VAE()
        tx = optax.adam(1e-3)
        ref = create_train_state(trial, model, tx, jax.random.key(0))
        zstate, zsh = place_zero_state(
            trial, create_train_state(trial, model, tx, jax.random.key(0))
        )
        ref_step = make_train_step(trial, model, tx)
        z_step = make_train_step(trial, model, tx, shardings=zsh)
        batch = jax.device_put(
            jnp.asarray(
                np.random.RandomState(0).rand(128, 784), jnp.float32
            ),
            trial.batch_sharding,
        )
        key = jax.random.key(1)
        for i in range(3):
            r = jax.random.fold_in(key, i)
            ref, mr = ref_step(ref, batch, r)
            zstate, mz = z_step(zstate, batch, r)
            np.testing.assert_allclose(
                float(mz["loss_sum"]), float(mr["loss_sum"]),
                rtol=PARITY_RTOL,
            )
        for a, b in zip(
            jax.tree.leaves(jax.device_get(zstate.params)),
            jax.tree.leaves(jax.device_get(ref.params)),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=PARITY_RTOL
            )

    def test_per_device_optimizer_bytes_ratio(self):
        trial = self._mesh()
        model = VAE()
        tx = optax.adam(1e-3)
        ref = create_train_state(trial, model, tx, jax.random.key(0))
        zstate, _ = place_zero_state(
            trial, create_train_state(trial, model, tx, jax.random.key(0))
        )
        rb = optimizer_state_bytes(ref)
        zb = optimizer_state_bytes(zstate)
        n = trial.data_size
        assert rb["per_device_bytes"] == rb["total_bytes"]
        # <= 1/n x replicated + epsilon (the replicated small leaves:
        # biases below min_size and Adam's count)
        assert zb["per_device_bytes"] <= rb["per_device_bytes"] / n * 1.02
        assert zb["total_bytes"] == rb["total_bytes"]

    def test_shardings_tree_shape(self):
        trial = self._mesh()
        model = VAE()
        tx = optax.adam(1e-3)
        state = build_train_state(model, tx, jax.random.key(0))
        sh = zero_update_shardings(trial, state)
        # params replicated, large moments sharded over data
        for s in jax.tree.leaves(sh.params):
            assert s.spec == jax.sharding.PartitionSpec()
        specs = [s.spec for s in jax.tree.leaves(sh.opt_state)]
        assert any(DATA_AXIS in (ax for ax in s if ax) for s in specs)

    def test_run_hpo_zero_trial_completes_with_memory_books(self, tmp_path):
        train = synthetic_mnist(256, seed=0)
        groups = setup_groups(2)
        cfgs = [
            TrialConfig(trial_id=0, epochs=1, batch_size=64,
                        zero_update=True),
            TrialConfig(trial_id=1, epochs=1, batch_size=64),
        ]
        results = run_hpo(
            cfgs, train, groups=groups, out_dir=str(tmp_path),
            save_images=False, verbose=False,
        )
        assert [r.status for r in results] == ["completed", "completed"]
        z, ref = results
        assert z.optimizer_state_bytes > 0
        assert ref.optimizer_state_bytes > 0
        n = groups[0].data_size
        assert z.optimizer_state_bytes <= ref.optimizer_state_bytes / n * 1.02
        # and the two trained the same config shape -> same loss scale
        assert np.isfinite(z.final_train_loss)

    def test_zero_config_never_stacks(self):
        from multidisttorch_tpu.hpo.driver import config_is_stackable

        assert not config_is_stackable(
            TrialConfig(trial_id=0, zero_update=True)
        )
        assert not config_is_stackable(
            TrialConfig(trial_id=0, pipeline_stages=2)
        )


class TestGradAccumMicrobatchParity:
    """Satellite: the scan-of-microbatches step must equal the
    full-batch step on XLA:CPU — the pipeline schedule's correctness
    foundation (its backward IS microbatch gradient accumulation)."""

    def test_accumulated_grads_equal_full_batch(self):
        trial = setup_groups(2)[0]
        model = VAE()
        state = build_train_state(
            model, optax.adam(1e-3), jax.random.key(0)
        )
        batch = jnp.asarray(
            np.random.RandomState(1).rand(64, 784), jnp.float32
        )

        def det_loss(params, mb):
            # Deterministic posterior-mean ELBO (no reparam draw): the
            # full-batch and microbatch streams see identical math.
            from multidisttorch_tpu.ops.losses import elbo_loss_sum

            mu, logvar = model.apply(
                {"params": params}, mb, method="encode"
            )
            logits = model.apply({"params": params}, mu, method="decode")
            return elbo_loss_sum(
                logits, mb.reshape(mb.shape[0], -1), mu, logvar, 1.0
            ) / mb.shape[0]

        full_loss, full_grads = jax.jit(
            jax.value_and_grad(det_loss)
        )(state.params, batch)

        @jax.jit
        def accum(params, b):
            return accumulate_gradients(
                trial,
                lambda p, mb: (det_loss(p, mb), ()),
                params,
                (b,),
                grad_accum=4,
            )

        acc_loss, _, acc_grads = accum(state.params, batch)
        np.testing.assert_allclose(
            float(acc_loss), float(full_loss), rtol=PARITY_RTOL
        )
        for a, b in zip(
            jax.tree.leaves(acc_grads), jax.tree.leaves(full_grads)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b),
                rtol=5e-5, atol=1e-7,
            )


class TestMpmdPipeline:
    def _build(self, microbatches=4, zero_update=False, registry_keys=None):
        groups = setup_groups(4)  # 4 x 2 devices
        model = VAE()
        tx = optax.adam(1e-3)
        full = build_train_state(model, tx, jax.random.key(0))
        stage_fns, last_fn, keys = make_vae_stage_fns(model, beta=1.0)
        pipe = MpmdPipeline(
            [groups[0], groups[1]],
            stage_fns,
            last_fn,
            split_stage_params(full.params, keys),
            lr=1e-3,
            microbatches=microbatches,
            zero_update=zero_update,
            registry_keys=registry_keys,
            eval_fns=make_vae_stage_eval_fns(model, 1.0),
        )
        ref_state = groups[2].device_put(
            build_train_state(model, tx, jax.random.key(0))
        )
        ref_step = make_mpmd_reference_step(
            groups[2], stage_fns, last_fn, tx, microbatches=microbatches
        )
        return groups, pipe, ref_state, ref_step

    def test_parity_with_single_mesh_reference(self):
        groups, pipe, ref_state, ref_step = self._build()
        key = jax.random.key(1)
        rs = np.random.RandomState(0)
        for i in range(3):
            b = jnp.asarray(rs.rand(64, 784), jnp.float32)
            r = jax.random.fold_in(key, i)
            m = pipe.step(
                jax.device_put(b, groups[0].batch_sharding), r
            )
            ref_state, mr = ref_step(
                ref_state, jax.device_put(b, groups[2].batch_sharding), r
            )
            np.testing.assert_allclose(
                float(m["loss_sum"]), float(mr["loss_sum"]),
                rtol=PARITY_RTOL,
            )
        merged = merge_stage_params(
            [jax.device_get(s.params) for s in pipe.states]
        )
        ref_params = jax.device_get(ref_state.params)
        for k in merged:
            for a, b in zip(
                jax.tree.leaves(merged[k]),
                jax.tree.leaves(ref_params[k]),
            ):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=PARITY_RTOL
                )

    def test_zero_update_composes_per_stage(self):
        groups, pipe, ref_state, ref_step = self._build(
            microbatches=2, zero_update=True
        )
        b = jnp.asarray(
            np.random.RandomState(0).rand(64, 784), jnp.float32
        )
        r = jax.random.key(2)
        m = pipe.step(jax.device_put(b, groups[0].batch_sharding), r)
        ref_state, mr = ref_step(
            ref_state, jax.device_put(b, groups[2].batch_sharding), r
        )
        np.testing.assert_allclose(
            float(m["loss_sum"]), float(mr["loss_sum"]), rtol=PARITY_RTOL
        )
        ob = pipe.optimizer_state_bytes()
        assert ob["per_device_bytes"] < ob["total_bytes"]

    def test_measured_bubble_matches_analytic(self):
        groups, pipe, _, _ = self._build(microbatches=4)
        b = jnp.asarray(
            np.random.RandomState(0).rand(64, 784), jnp.float32
        )
        for i in range(2):
            pipe.step(
                jax.device_put(b, groups[0].batch_sharding),
                jax.random.key(i),
            )
        measured = pipe.measured_bubble()
        analytic = analytic_bubble_fraction(2, 4)
        assert measured is not None
        assert abs(measured - analytic) <= 0.10 * analytic
        books = pipe.schedule_books()
        assert books["transfers"] > 0 and books["transfer_bytes"] > 0

    def test_stage_programs_register_as_pipe_kinds(self):
        from multidisttorch_tpu.compile import programs as cprog
        from multidisttorch_tpu.compile.registry import (
            READY,
            get_executable_registry,
        )

        groups = setup_groups(4)
        cfg = TrialConfig(
            trial_id=0, batch_size=64, grad_accum=2, pipeline_stages=2
        )
        from multidisttorch_tpu.hpo.driver import stack_bucket_key

        keys = cprog.pipeline_stage_keys(
            [groups[0], groups[1]], cfg, stack_bucket_key(cfg),
            microbatches=2,
        )
        assert set(k for k, _ in keys) == {"fwd", "bwd", "update"}
        kinds = {key[0] for key in keys.values()}
        assert kinds == {cprog.PIPE_FWD, cprog.PIPE_BWD, cprog.PIPE_UPDATE}
        # distinct per-stage mesh fingerprints
        assert keys[("fwd", 0)][3] != keys[("fwd", 1)][3]
        # labels render without falling back to repr
        for key in keys.values():
            assert "pipe_" in cprog.program_label(key)

        model = VAE()
        full = build_train_state(
            model, optax.adam(1e-3), jax.random.key(0)
        )
        stage_fns, last_fn, pk = make_vae_stage_fns(model, 1.0)
        pipe = MpmdPipeline(
            [groups[0], groups[1]], stage_fns, last_fn,
            split_stage_params(full.params, pk),
            lr=1e-3, microbatches=2, registry_keys=keys,
        )
        b = jnp.asarray(
            np.random.RandomState(0).rand(64, 784), jnp.float32
        )
        pipe.step(
            jax.device_put(b, groups[0].batch_sharding), jax.random.key(0)
        )
        reg = get_executable_registry()
        for key in keys.values():
            assert reg.status(key) == READY


class TestPipelineRunner:
    def test_runner_completes_with_books_and_reference_parity(
        self, tmp_path
    ):
        from multidisttorch_tpu.data.sampler import TrialDataIterator
        from multidisttorch_tpu.hpo.pipeline_run import (
            PIPELINE_BOOKS_NAME,
            run_pipeline_trial,
        )

        groups = setup_groups(4)
        train = synthetic_mnist(256, seed=0)
        test = synthetic_mnist(64, seed=1)
        cfg = TrialConfig(
            trial_id=0, epochs=2, batch_size=64, grad_accum=4,
            pipeline_stages=2,
        )
        res = run_pipeline_trial(
            cfg, train, test,
            stage_meshes=[groups[0], groups[1]],
            out_dir=str(tmp_path),
        )
        assert res.status == "completed"
        assert res.steps == 2 * (256 // 64)
        assert res.optimizer_state_bytes > 0
        books = json.load(
            open(os.path.join(res.out_dir, PIPELINE_BOOKS_NAME))
        )
        sched = books["schedule"]
        assert sched["measured_bubble"] is not None
        assert (
            abs(sched["measured_bubble"] - sched["analytic_bubble"])
            <= 0.10 * sched["analytic_bubble"]
        )
        assert len(books["stage_groups"]) == 2

        # Single-mesh reference over the SAME data stream (the
        # iterator's order is a pure function of (seed, epoch)).
        model = VAE()
        tx = optax.adam(cfg.lr)
        stage_fns, last_fn, pk = make_vae_stage_fns(model, cfg.beta)
        ref_mesh = groups[2]
        ref_state = ref_mesh.device_put(
            build_train_state(model, tx, jax.random.key(cfg.seed))
        )
        ref_step = make_mpmd_reference_step(
            ref_mesh, stage_fns, last_fn, tx, microbatches=4
        )
        it = TrialDataIterator(
            train, ref_mesh, cfg.batch_size, seed=cfg.seed
        )
        key = jax.random.key(cfg.seed + 1)
        step_no = 0
        for epoch in (1, 2):
            sum_dev = None
            for batch in it.epoch(epoch):
                r = jax.random.fold_in(key, step_no)
                ref_state, m = ref_step(ref_state, batch, r)
                step_no += 1
                sum_dev = (
                    m["loss_sum"]
                    if sum_dev is None
                    else sum_dev + m["loss_sum"]
                )
            avg = float(sum_dev) / it.samples_per_epoch
            np.testing.assert_allclose(
                res.history[epoch - 1]["avg_train_loss"], avg,
                rtol=PARITY_RTOL,
            )

    def test_per_stage_checkpoint_scan_restore(self, tmp_path):
        from multidisttorch_tpu.hpo.pipeline_run import _PipelineTrialRun

        groups = setup_groups(4)
        train = synthetic_mnist(128, seed=0)
        cfg = TrialConfig(
            trial_id=7, epochs=2, batch_size=64, grad_accum=2,
            pipeline_stages=2,
        )
        run1 = _PipelineTrialRun(
            [groups[0], groups[1]], cfg, train, None, str(tmp_path)
        )
        for _ in run1.run():
            pass
        assert run1.result.status == "completed"
        assert os.path.exists(run1._ckpt_paths[0])
        assert os.path.exists(run1._ckpt_paths[1])

        # Extend epochs and resume: restores at epoch 2.
        from dataclasses import replace

        cfg3 = replace(cfg, epochs=3)
        run2 = _PipelineTrialRun(
            [groups[0], groups[1]], cfg3, train, None, str(tmp_path),
            resume="scan",
        )
        assert run2.result.resumed_from_step == 2 * (128 // 64)
        # The restored checkpoint's history is adopted: the settled
        # summary must cover the WHOLE training, not just the resumed
        # epochs.
        assert [h["epoch"] for h in run2.result.history] == [1, 2]
        for _ in run2.run():
            pass
        assert run2.result.status == "completed"
        assert run2.result.steps == 3 * (128 // 64)
        assert [h["epoch"] for h in run2.result.history] == [1, 2, 3]

        # Torn stage-1 checkpoint pulls BOTH stages back to the last
        # step every stage verifies (or scratch when history is gone).
        with open(run2._ckpt_paths[1], "wb") as f:
            f.write(b"torn")
        run3 = _PipelineTrialRun(
            [groups[0], groups[1]], cfg3, train, None, str(tmp_path),
            resume="scan",
        )
        # keep_last=1: no surviving common step -> scratch
        assert run3.result.resumed_from_step == 0

    def test_unsupported_knobs_rejected_loudly(self, tmp_path):
        """eval_sampled / fused_steps / remat are not wired through the
        MPMD stage programs: the runner raises instead of silently
        training/evaluating something else (the service mirrors this
        at admission with rejected_invalid)."""
        from multidisttorch_tpu.hpo.pipeline_run import _PipelineTrialRun

        groups = setup_groups(4)
        train = synthetic_mnist(128, seed=0)
        for kw in (
            {"eval_sampled": True},
            {"fused_steps": 2},
            {"remat": True},
        ):
            cfg = TrialConfig(
                trial_id=0, epochs=1, batch_size=64,
                pipeline_stages=2, **kw,
            )
            with pytest.raises(ValueError, match="unpipelined"):
                _PipelineTrialRun(
                    [groups[0], groups[1]], cfg, train, None,
                    str(tmp_path),
                )

    def test_run_hpo_rejects_pipeline_configs(self, tmp_path):
        train = synthetic_mnist(128, seed=0)
        with pytest.raises(ValueError, match="vector"):
            run_hpo(
                [
                    TrialConfig(
                        trial_id=0, epochs=1, batch_size=64,
                        pipeline_stages=2,
                    )
                ],
                train,
                num_groups=1,
                out_dir=str(tmp_path),
                save_images=False,
            )


class TestServicePipeline:
    def test_pipelined_submission_places_vector_and_completes(
        self, tmp_path
    ):
        from multidisttorch_tpu import telemetry
        from multidisttorch_tpu.service.queue import SweepClient
        from multidisttorch_tpu.service.runtime import SweepService
        from multidisttorch_tpu.telemetry.events import read_events
        from multidisttorch_tpu.telemetry.export import run_summary

        d = str(tmp_path)
        tel = os.path.join(d, "tel")
        client = SweepClient(d, tenant="whale")
        sid = client.submit(
            {
                "epochs": 1,
                "batch_size": 64,
                "grad_accum": 4,
                "pipeline_stages": 2,
            },
            size=2,
        )
        with telemetry.telemetry_run(tel):
            svc = SweepService(
                d,
                train_data=synthetic_mnist(128, seed=0),
                verbose=False,
            )
            out = svc.serve(exit_when_drained=True, max_wall_s=240)
        assert out["settled"] == {sid: "completed"}
        recs = [
            json.loads(line)
            for line in open(os.path.join(d, "queue.jsonl"))
        ]
        placed = [r for r in recs if r.get("event") == "placed"]
        assert len(placed) == 1
        blocks = placed[0].get("blocks")
        assert blocks is not None and len(blocks) == 2
        # all-or-nothing: both stage blocks, disjoint, size 2 each
        spans = [set(range(s, s + n)) for s, n in blocks]
        assert all(len(sp) == 2 for sp in spans)
        assert not (spans[0] & spans[1])
        # books: pipeline trial dir carries the schedule measurement
        tdir = os.path.join(d, f"trial-{placed[0]['trial_id']}")
        books = json.load(
            open(os.path.join(tdir, "pipeline_books.json"))
        )
        assert books["schedule"]["measured_bubble"] is not None
        # run_summary folds pipeline + optimizer_state events
        summary = run_summary(
            read_events(os.path.join(tel, "events.jsonl")),
            registry=None,
        )
        tid = str(placed[0]["trial_id"])
        trial = summary["trials"][int(tid)] if int(
            tid
        ) in summary["trials"] else summary["trials"][tid]
        assert trial.get("optimizer_state_bytes", 0) > 0
        assert trial.get("pipeline", {}).get("measured_bubble") is not None

    def test_oversized_vector_rejected(self, tmp_path):
        from multidisttorch_tpu.service.queue import SweepClient
        from multidisttorch_tpu.service.runtime import SweepService

        d = str(tmp_path)
        client = SweepClient(d, tenant="t")
        sid = client.submit(
            {"epochs": 1, "batch_size": 64, "pipeline_stages": 2},
            size=8,  # 2 stages x 8 slices > 8-slice world
        )
        # Everything the pipelined runner would raise on is rejected
        # with a verdict at admission — placed-then-raise would
        # classify INFRA and burn the retry budget on a deterministic
        # config error.
        sid2 = client.submit(
            {
                "epochs": 1,
                "batch_size": 64,
                "pipeline_stages": 2,
                "eval_sampled": True,
            },
            size=1,
        )
        sid3 = client.submit(  # executing runner covers S=2 only
            {"epochs": 1, "batch_size": 64, "pipeline_stages": 3},
            size=1,
        )
        sid4 = client.submit(  # batch does not divide into microbatches
            {
                "epochs": 1,
                "batch_size": 64,
                "grad_accum": 5,
                "pipeline_stages": 2,
            },
            size=1,
        )
        svc = SweepService(
            d, train_data=synthetic_mnist(128, seed=0), verbose=False
        )
        out = svc.serve(exit_when_drained=True, max_wall_s=60)
        assert out["settled"][sid] == "rejected_invalid"
        assert out["settled"][sid2] == "rejected_invalid"
        assert out["settled"][sid3] == "rejected_invalid"
        assert out["settled"][sid4] == "rejected_invalid"
