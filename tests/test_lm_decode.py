"""KV-cache decode (train/lm_decode.py) vs the full-recompute sampler:
the two formulations must produce identical greedy decodes — this is
the parity pin that keeps the hand-written per-position math from
drifting away from models.transformer.Block."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.models.transformer import TransformerLM
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.lm import create_lm_state, make_lm_sample
from multidisttorch_tpu.train.lm_decode import make_cached_lm_sample


def _setup(seed=0, t=24):
    (g,) = setup_groups(1)
    model = TransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=2, max_len=t
    )
    state = create_lm_state(
        g, model, optax.adam(1e-3), jax.random.key(seed), example_len=t
    )
    return g, model, state


@pytest.mark.parametrize("prompt_len", [1, 5, 23])
def test_cached_decode_matches_full_recompute(prompt_len):
    t = 24
    g, model, state = _setup(t=t)
    rng = np.random.default_rng(3)
    buf = jnp.asarray(rng.integers(0, 32, (8, t), dtype=np.int32))

    full = make_lm_sample(g, model)
    cached = make_cached_lm_sample(g, model)
    out_full = np.asarray(full(state, buf, prompt_len, jax.random.key(0)))
    out_cached = np.asarray(cached(state, buf, prompt_len, jax.random.key(0)))
    np.testing.assert_array_equal(out_cached, out_full)
    # the prompt region is untouched
    np.testing.assert_array_equal(
        out_cached[:, :prompt_len], np.asarray(buf)[:, :prompt_len]
    )


def test_cached_decode_prompt_len_zero_clamps():
    g, model, state = _setup()
    buf = jnp.asarray(
        np.random.default_rng(1).integers(0, 32, (8, 24), dtype=np.int32)
    )
    cached = make_cached_lm_sample(g, model)
    out = np.asarray(cached(state, buf, 0, jax.random.key(0)))
    np.testing.assert_array_equal(out[:, 0], np.asarray(buf)[:, 0])
    # and matches the full-recompute sampler under the same clamp
    full = make_lm_sample(g, model)
    np.testing.assert_array_equal(
        out, np.asarray(full(state, buf, 0, jax.random.key(0)))
    )


def test_cached_temperature_stream_matches_full_recompute():
    # The rng draw order must match the full-recompute sampler exactly
    # (prefill makes no draws), so identical seeds give identical
    # stochastic samples from either implementation.
    g, model, state = _setup()
    buf = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, (8, 24), dtype=np.int32)
    )
    hot_cached = make_cached_lm_sample(g, model, temperature=1.0)
    hot_full = make_lm_sample(g, model, temperature=1.0)
    a = np.asarray(hot_cached(state, buf, 4, jax.random.key(7)))
    b = np.asarray(hot_full(state, buf, 4, jax.random.key(7)))
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 0 and a.max() < 32


def test_cached_decode_rejects_bf16_models():
    (g,) = setup_groups(1)
    model = TransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=1, max_len=16,
        dtype=jnp.bfloat16,
    )
    with pytest.raises(ValueError, match="float32"):
        make_cached_lm_sample(g, model)


def test_cached_decode_rejects_overlong_buffer():
    g, model, state = _setup(t=24)  # max_len = 24
    cached = make_cached_lm_sample(g, model)
    long_buf = jnp.zeros((8, 32), jnp.int32)
    with pytest.raises(ValueError, match="exceeds max_len"):
        cached(state, long_buf, 4, jax.random.key(0))


def test_cached_decode_rejects_moe_models():
    from multidisttorch_tpu.models.transformer import MoETransformerLM

    (g,) = setup_groups(1)
    moe = MoETransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=1,
        num_experts=2, max_len=16,
    )
    with pytest.raises(ValueError, match="dense-block"):
        make_cached_lm_sample(g, moe)


def test_cached_decode_with_ring_attention_model():
    # A ring-attention model prefills through its own ring callable
    # (linear memory on long contexts); greedy decode must still match
    # the full-recompute sampler on the same model.
    from multidisttorch_tpu.ops.ring_attention import make_ring_attention

    (g,) = setup_groups(1)
    t = 24  # divides the 8-device ring
    model = TransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=2, max_len=t,
        attention=make_ring_attention(g, causal=True),
    )
    state = create_lm_state(
        g, model, optax.adam(1e-3), jax.random.key(0), example_len=t
    )
    buf = jnp.asarray(
        np.random.default_rng(4).integers(0, 32, (8, t), dtype=np.int32)
    )
    out_cached = np.asarray(
        make_cached_lm_sample(g, model)(state, buf, 6, jax.random.key(0))
    )
    out_full = np.asarray(
        make_lm_sample(g, model)(state, buf, 6, jax.random.key(0))
    )
    np.testing.assert_array_equal(out_cached, out_full)
