"""KV-cache decode (train/lm_decode.py) vs the full-recompute sampler:
the two formulations must produce identical greedy decodes — this is
the parity pin that keeps the hand-written per-position math from
drifting away from models.transformer.Block."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.models.transformer import TransformerLM
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.lm import create_lm_state, make_lm_sample
from multidisttorch_tpu.train.lm_decode import make_cached_lm_sample


def _setup(seed=0, t=24):
    (g,) = setup_groups(1)
    model = TransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=2, max_len=t
    )
    state = create_lm_state(
        g, model, optax.adam(1e-3), jax.random.key(seed), example_len=t
    )
    return g, model, state


@pytest.mark.parametrize("prompt_len", [1, 5, 23])
def test_cached_decode_matches_full_recompute(prompt_len):
    t = 24
    g, model, state = _setup(t=t)
    rng = np.random.default_rng(3)
    buf = jnp.asarray(rng.integers(0, 32, (8, t), dtype=np.int32))

    full = make_lm_sample(g, model)
    cached = make_cached_lm_sample(g, model)
    out_full = np.asarray(full(state, buf, prompt_len, jax.random.key(0)))
    out_cached = np.asarray(cached(state, buf, prompt_len, jax.random.key(0)))
    np.testing.assert_array_equal(out_cached, out_full)
    # the prompt region is untouched
    np.testing.assert_array_equal(
        out_cached[:, :prompt_len], np.asarray(buf)[:, :prompt_len]
    )


@pytest.mark.parametrize(
    "layers,heads,d_model,t",
    [
        # Drift guard (VERDICT r4 weak #5): lm_decode re-implements the
        # Block forward by hand, pinned ONLY by parity with the flax
        # model — so the parity sweep must cover a spread of shapes, not
        # one fixed config, or the hand-rolled forward can drift on an
        # untested shape. Drawn from rng(17) over layers∈[1,4],
        # heads∈{1,2,4,8}, d_model∈{16..64 multiples of heads}, t∈[8,48]
        # then frozen, so failures are reproducible.
        (3, 8, 64, 17),
        (1, 1, 24, 8),
        (4, 2, 40, 31),
        (2, 4, 16, 48),
        (3, 2, 56, 9),
        (1, 8, 32, 29),
    ],
)
def test_cached_decode_shape_sweep_parity(layers, heads, d_model, t):
    (g,) = setup_groups(1)
    model = TransformerLM(
        vocab_size=48, d_model=d_model, num_heads=heads,
        num_layers=layers, max_len=t,
    )
    state = create_lm_state(
        g, model, optax.adam(1e-3), jax.random.key(layers * 31 + t),
        example_len=t,
    )
    buf = jnp.asarray(
        np.random.default_rng(t).integers(0, 48, (8, t), dtype=np.int32)
    )
    prompt_len = max(1, t // 3)
    full = make_lm_sample(g, model)
    cached = make_cached_lm_sample(g, model)
    np.testing.assert_array_equal(
        np.asarray(cached(state, buf, prompt_len, jax.random.key(1))),
        np.asarray(full(state, buf, prompt_len, jax.random.key(1))),
    )


def test_cached_decode_prompt_len_zero_clamps():
    g, model, state = _setup()
    buf = jnp.asarray(
        np.random.default_rng(1).integers(0, 32, (8, 24), dtype=np.int32)
    )
    cached = make_cached_lm_sample(g, model)
    out = np.asarray(cached(state, buf, 0, jax.random.key(0)))
    np.testing.assert_array_equal(out[:, 0], np.asarray(buf)[:, 0])
    # and matches the full-recompute sampler under the same clamp
    full = make_lm_sample(g, model)
    np.testing.assert_array_equal(
        out, np.asarray(full(state, buf, 0, jax.random.key(0)))
    )


def test_cached_temperature_stream_matches_full_recompute():
    # The rng draw order must match the full-recompute sampler exactly
    # (prefill makes no draws), so identical seeds give identical
    # stochastic samples from either implementation.
    g, model, state = _setup()
    buf = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, (8, 24), dtype=np.int32)
    )
    hot_cached = make_cached_lm_sample(g, model, temperature=1.0)
    hot_full = make_lm_sample(g, model, temperature=1.0)
    a = np.asarray(hot_cached(state, buf, 4, jax.random.key(7)))
    b = np.asarray(hot_full(state, buf, 4, jax.random.key(7)))
    np.testing.assert_array_equal(a, b)
    assert a.min() >= 0 and a.max() < 32


def test_cached_decode_rejects_bf16_models():
    (g,) = setup_groups(1)
    model = TransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=1, max_len=16,
        dtype=jnp.bfloat16,
    )
    with pytest.raises(ValueError, match="float32"):
        make_cached_lm_sample(g, model)


def test_cached_decode_rejects_overlong_buffer():
    g, model, state = _setup(t=24)  # max_len = 24
    cached = make_cached_lm_sample(g, model)
    long_buf = jnp.zeros((8, 32), jnp.int32)
    with pytest.raises(ValueError, match="exceeds max_len"):
        cached(state, long_buf, 4, jax.random.key(0))


def test_cached_decode_rejects_moe_models():
    from multidisttorch_tpu.models.transformer import MoETransformerLM

    (g,) = setup_groups(1)
    moe = MoETransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=1,
        num_experts=2, max_len=16,
    )
    with pytest.raises(ValueError, match="dense-block"):
        make_cached_lm_sample(g, moe)


def test_cached_decode_with_ring_attention_model():
    # A ring-attention model prefills through its own ring callable
    # (linear memory on long contexts); greedy decode must still match
    # the full-recompute sampler on the same model.
    from multidisttorch_tpu.ops.ring_attention import make_ring_attention

    (g,) = setup_groups(1)
    t = 24  # divides the 8-device ring
    model = TransformerLM(
        vocab_size=32, d_model=32, num_heads=4, num_layers=2, max_len=t,
        attention=make_ring_attention(g, causal=True),
    )
    state = create_lm_state(
        g, model, optax.adam(1e-3), jax.random.key(0), example_len=t
    )
    buf = jnp.asarray(
        np.random.default_rng(4).integers(0, 32, (8, t), dtype=np.int32)
    )
    out_cached = np.asarray(
        make_cached_lm_sample(g, model)(state, buf, 6, jax.random.key(0))
    )
    out_full = np.asarray(
        make_lm_sample(g, model)(state, buf, 6, jax.random.key(0))
    )
    np.testing.assert_array_equal(out_cached, out_full)


def test_filter_logits_top_k_and_top_p():
    from multidisttorch_tpu.train.lm import _filter_logits

    logits = jnp.asarray([[3.0, 1.0, 2.0, 0.0]])
    k2 = np.asarray(_filter_logits(logits, top_k=2, top_p=None))
    assert np.isfinite(k2[0, [0, 2]]).all()
    assert np.isneginf(k2[0, [1, 3]]).all()
    # top_p tight enough to keep only the argmax
    p_small = np.asarray(_filter_logits(logits, top_k=None, top_p=0.1))
    assert np.isfinite(p_small[0, 0]) and np.isneginf(p_small[0, 1:]).all()
    # top_p=1.0 keeps everything
    p_all = np.asarray(_filter_logits(logits, top_k=None, top_p=1.0))
    assert np.isfinite(p_all).all()


def test_top_k_one_equals_greedy_and_samplers_agree():
    g, model, state = _setup(seed=5)
    buf = jnp.asarray(
        np.random.default_rng(6).integers(0, 32, (8, 24), dtype=np.int32)
    )
    greedy = make_cached_lm_sample(g, model)
    k1 = make_cached_lm_sample(g, model, temperature=1.0, top_k=1)
    np.testing.assert_array_equal(
        np.asarray(k1(state, buf, 4, jax.random.key(0))),
        np.asarray(greedy(state, buf, 4, jax.random.key(0))),
    )
    # filtered stochastic sampling agrees across both implementations
    a = make_cached_lm_sample(g, model, temperature=1.0, top_k=5, top_p=0.9)
    b = make_lm_sample(g, model, temperature=1.0, top_k=5, top_p=0.9)
    np.testing.assert_array_equal(
        np.asarray(a(state, buf, 4, jax.random.key(3))),
        np.asarray(b(state, buf, 4, jax.random.key(3))),
    )


def test_top_k_beyond_vocab_fails_at_build():
    # Factories know the model's vocab, so an impossible top_k is a
    # construction error, not a first-jitted-call trace error — the
    # 'fail at construction' contract (ADVICE r4). vocab_size here: 32.
    from multidisttorch_tpu.train.lm import make_lm_sample

    g, model, _ = _setup()
    for factory in (make_cached_lm_sample, make_lm_sample):
        with pytest.raises(ValueError, match="vocab_size"):
            factory(g, model, temperature=1.0, top_k=33)
        factory(g, model, temperature=1.0, top_k=32)  # boundary is fine


def test_filter_logits_exact_on_ties_and_validates():
    from multidisttorch_tpu.train.lm import _filter_logits

    # uniform row: rank-based filtering still keeps exactly k / the
    # top-p prefix (value thresholds would keep everything)
    uniform = jnp.zeros((1, 8))
    k3 = np.asarray(_filter_logits(uniform, top_k=3, top_p=None))
    assert np.isfinite(k3).sum() == 3
    p_small = np.asarray(_filter_logits(uniform, top_k=None, top_p=0.2))
    assert np.isfinite(p_small).sum() == 2  # ceil to reach 0.2 of mass
    # rank 0 is exactly argmax on ties (stable order)
    tied = jnp.asarray([[1.0, 5.0, 5.0, 0.0]])
    k1 = np.asarray(_filter_logits(tied, top_k=1, top_p=None))
    assert np.isfinite(k1[0, 1]) and np.isneginf(k1[0, 2])
    with pytest.raises(ValueError, match="top_k"):
        _filter_logits(uniform, top_k=0, top_p=None)
    with pytest.raises(ValueError, match="top_p"):
        _filter_logits(uniform, top_k=None, top_p=1.5)


def test_filter_logits_properties():
    # Randomized property check: kept-count == min(k, nucleus size) and
    # kept mass >= top_p for every row.
    from multidisttorch_tpu.train.lm import _filter_logits

    rng = np.random.default_rng(11)
    logits = jnp.asarray(rng.normal(0, 2, (16, 33)).astype(np.float32))
    for top_k, top_p in ((1, None), (7, None), (None, 0.5),
                         (None, 0.99), (5, 0.7)):
        out = np.asarray(_filter_logits(logits, top_k, top_p))
        kept = np.isfinite(out)
        if top_k is not None:
            assert (kept.sum(-1) <= top_k).all()
            if top_p is None:
                assert (kept.sum(-1) == top_k).all()
        if top_p is not None and top_k is None:
            # kept set reaches the target mass
            probs = np.asarray(jax.nn.softmax(logits, axis=-1))
            mass = (probs * kept).sum(-1)
            assert (mass >= top_p - 1e-6).all()
        # filtering never changes surviving values
        np.testing.assert_array_equal(out[kept], np.asarray(logits)[kept])


def test_sampler_factories_validate_at_build_time():
    g, model, _ = _setup()
    with pytest.raises(ValueError, match="top_p"):
        make_cached_lm_sample(g, model, temperature=1.0, top_p=5.0)
    with pytest.raises(ValueError, match="temperature > 0"):
        make_cached_lm_sample(g, model, top_k=5)  # greedy would drop it
    with pytest.raises(ValueError, match="top_k"):
        make_lm_sample(g, model, temperature=1.0, top_k=0)
