"""Elastic multi-host survival layer: membership heartbeats, the wedge
watchdog's exit-code contract, supervised world-shrink restart, ledger
compaction, decorrelated retry jitter, and the SIGTERM graceful drain.

Fast tests run in-process (membership and supervisor logic are plain
files + subprocesses — no device runtime); the true multi-controller
drills (kill-one-of-N, wedge -> WedgedCollective, cross-host restore
agreement) are ``multihost``-marked subprocess worlds like
tests/test_multihost.py's.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(_REPO, "tools")


# --------------------------------------------------------------------
# membership: lease files, staleness, torn tails
# --------------------------------------------------------------------


def test_heartbeat_writes_and_stops_cleanly(tmp_path):
    from multidisttorch_tpu.parallel import membership as m

    hb = m.Heartbeat(str(tmp_path), 3, interval_s=0.02, world_epoch=1,
                     world_size=2).start()
    time.sleep(0.15)
    hb.stop()
    recs = m.read_lease(m.lease_path(str(tmp_path), 3))
    assert len(recs) >= 3  # immediate beat + interval beats + final
    assert recs[0]["status"] == "alive" and recs[-1]["status"] == "left"
    assert all(r["slot"] == 3 and r["world_epoch"] == 1 for r in recs)
    assert [r["seq"] for r in recs] == sorted(r["seq"] for r in recs)


def test_lease_read_tolerates_torn_tail(tmp_path):
    from multidisttorch_tpu.parallel import membership as m

    path = m.lease_path(str(tmp_path), 0)
    os.makedirs(os.path.dirname(path))
    with open(path, "w") as f:
        f.write(json.dumps({"slot": 0, "ts": 1.0, "status": "alive"}) + "\n")
        f.write('{"slot": 0, "ts": 2.0, "stat')  # torn mid-append
    recs = m.read_lease(path)
    assert len(recs) == 1 and recs[0]["ts"] == 1.0


def test_lost_hosts_stale_vs_fresh_vs_left(tmp_path):
    from multidisttorch_tpu.parallel import membership as m

    now = time.time()

    def write(slot, ts, status="alive"):
        path = m.lease_path(str(tmp_path), slot)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(
                {"slot": slot, "ts": ts, "status": status}) + "\n")

    write(0, now)            # fresh: alive
    write(1, now - 10.0)     # stale: lost
    write(2, now - 10.0, status="left")  # clean departure: not lost
    view = m.MembershipView(str(tmp_path))
    assert view.lost_hosts(3.0, now=now) == [1]
    assert view.lost_hosts(3.0, now=now, among=[0, 2]) == []
    assert set(view.hosts()) == {0, 1, 2}


def test_heartbeat_suspend_goes_stale(tmp_path):
    from multidisttorch_tpu.parallel import membership as m

    hb = m.start_heartbeat(str(tmp_path), 0, interval_s=0.02)
    try:
        time.sleep(0.1)
        assert m.suspend_heartbeat()
        rec = m.latest_lease(m.lease_path(str(tmp_path), 0))
        time.sleep(0.15)
        rec2 = m.latest_lease(m.lease_path(str(tmp_path), 0))
        # suspended: no new beats; the lease ages toward lost
        assert rec2["seq"] == rec["seq"]
    finally:
        m.stop_heartbeat()


def test_world_history_roundtrip(tmp_path):
    from multidisttorch_tpu.parallel import membership as m

    m.record_world(str(tmp_path), epoch=0, hosts=[0, 1, 2])
    m.record_world(str(tmp_path), epoch=1, hosts=[0, 2], lost=[1],
                   reason="host_lost")
    hist = m.world_history(str(tmp_path))
    assert [w["epoch"] for w in hist] == [0, 1]
    assert hist[1]["lost"] == [1] and hist[1]["hosts"] == [0, 2]


# --------------------------------------------------------------------
# watchdog: WedgedCollective, exit codes, daemon regression
# --------------------------------------------------------------------


def test_wedged_collective_is_preemption_class():
    from multidisttorch_tpu.hpo.supervision import (
        PREEMPTION,
        classify_failure,
        exit_code_for,
    )
    from multidisttorch_tpu.parallel.cluster import (
        PREEMPTION_EXIT_CODE,
        AgreementTimeout,
        WedgedCollective,
    )

    exc = WedgedCollective("epoch sync wedged")
    assert isinstance(exc, AgreementTimeout)  # back-compat catch sites
    assert classify_failure(exc) == PREEMPTION
    assert exit_code_for(exc) == PREEMPTION_EXIT_CODE
    assert exit_code_for(RuntimeError("boom")) == 1


def test_call_with_timeout_error_cls_and_daemon_leak_regression():
    from multidisttorch_tpu.parallel.cluster import (
        AgreementTimeout,
        WedgedCollective,
        call_with_timeout,
    )

    release = threading.Event()

    def blocked():
        release.wait(30)

    before = set(threading.enumerate())
    with pytest.raises(WedgedCollective):
        call_with_timeout(
            blocked, 0.05, "test sync", error_cls=WedgedCollective
        )
    # The abandoned runner thread MUST be a daemon: a non-daemon leak
    # would make interpreter shutdown join a blocked thread forever.
    leaked = [
        t for t in set(threading.enumerate()) - before
        if t.name.startswith("watchdog:")
    ]
    assert leaked, "watchdog runner not found"
    assert all(t.daemon for t in leaked)
    # default error type unchanged
    with pytest.raises(AgreementTimeout):
        call_with_timeout(blocked, 0.05, "test sync")
    release.set()


def test_group_min_scalar_on_mesh_single_process():
    # The on-mesh value-agreement sibling of group_all_ok (the
    # recovery path uses the sideband agree_min_int instead).
    from multidisttorch_tpu.parallel.collectives import group_min_scalar
    from multidisttorch_tpu.parallel.mesh import setup_groups

    g0, _g1 = setup_groups(2)
    assert group_min_scalar(g0, 7) == 7
    assert group_min_scalar(g0, 0, what="zero") == 0


def test_agree_min_int_single_process_identity():
    from multidisttorch_tpu.parallel.cluster import agree_min_int

    assert agree_min_int(
        "t", 5, [0], timeout_s=1.0, what="solo"
    ) == 5


# --------------------------------------------------------------------
# decorrelated retry jitter
# --------------------------------------------------------------------


def test_backoff_without_jitter_is_bitwise_stable():
    from multidisttorch_tpu.hpo.supervision import RetryPolicy

    p = RetryPolicy(backoff_base_s=0.05, backoff_factor=2.0,
                    backoff_max_s=30.0)
    assert p.backoff_s(1) == 0.05
    assert p.backoff_s(2) == 0.1
    assert p.backoff_s(3, key=17) == 0.2  # key ignored when jitter off


def test_jitter_deterministic_decorrelated_bounded():
    from multidisttorch_tpu.hpo.supervision import RetryPolicy

    p = RetryPolicy(
        max_retries=5, backoff_base_s=0.05, backoff_max_s=2.0,
        jitter=True, jitter_seed=42,
    )
    # deterministic under (seed, key, retry_number)
    for k in (1, 2, 3):
        assert p.backoff_s(k, key=7) == p.backoff_s(k, key=7)
    # decorrelated across keys: N lanes felled together back off apart
    delays = {key: p.backoff_s(1, key=key) for key in range(8)}
    assert len(set(delays.values())) > 4
    # bounded: [base, max] always
    for key in range(8):
        for k in (1, 2, 3, 4, 5):
            d = p.backoff_s(k, key=key)
            assert p.backoff_base_s <= d <= p.backoff_max_s
    # a different seed reshuffles the schedule
    q = RetryPolicy(
        max_retries=5, backoff_base_s=0.05, backoff_max_s=2.0,
        jitter=True, jitter_seed=43,
    )
    assert any(
        p.backoff_s(1, key=key) != q.backoff_s(1, key=key)
        for key in range(8)
    )


# --------------------------------------------------------------------
# ledger compaction
# --------------------------------------------------------------------


def _storm_ledger(tmp_path, hashes=3, rounds=7):
    """Synthesize a restart storm: per config hash, `rounds` attempts
    of preempted/retrying churn, the first hash settling at the end."""
    from multidisttorch_tpu.hpo.ledger import SweepLedger

    led = SweepLedger(str(tmp_path))
    for h_i in range(hashes):
        h = f"hash-{h_i:02d}"
        for a in range(1, rounds + 1):
            led.attempt_start(h_i, h, a)
            status = "retrying" if a % 2 else "preempted"
            led.attempt_end(
                h_i, h, a, status, error="storm",
                summary={"steps_at_failure": 4 * a,
                         "resumed_from_step": 0},
            )
        if h_i == 0:
            led.attempt_start(h_i, h, rounds + 1)
            led.attempt_end(
                h_i, h, rounds + 1, "completed",
                summary={"steps": 40, "resumed_from_step": 0},
            )
    return led


def test_compact_preserves_restart_folds_and_shrinks(tmp_path):
    led = _storm_ledger(tmp_path)
    finished0 = {h: r["status"] for h, r in led.finished().items()}
    attempts0 = led.attempts()
    infra0 = led.infra_failures()
    before = len(led.load())
    stats = led.compact()
    assert stats["lines_before"] == before
    assert stats["lines_after"] < before  # the storm actually shrank
    assert {h: r["status"] for h, r in led.finished().items()} == finished0
    assert led.attempts() == attempts0
    assert led.infra_failures() == infra0
    # compaction is stable: a second pass changes nothing semantic
    led.compact()
    assert led.attempts() == attempts0
    assert led.infra_failures() == infra0


def test_compact_tolerates_torn_tail_and_is_atomic(tmp_path):
    led = _storm_ledger(tmp_path)
    with open(led.path, "a") as f:
        f.write('{"event": "attempt_start", "config')  # torn
    attempts0 = led.attempts()
    led.compact()
    assert led.attempts() == attempts0
    # no stray tmp file left behind
    assert not os.path.exists(led.path + ".tmp")


def test_compact_respects_write_gate(tmp_path):
    from multidisttorch_tpu.hpo.ledger import SweepLedger

    led = _storm_ledger(tmp_path)
    n = len(led.load())
    reader = SweepLedger(str(tmp_path), write=False)
    assert reader.compact() == {
        "lines_before": 0, "lines_after": 0, "hashes": 0,
    }
    assert len(led.load()) == n  # untouched


def test_resumed_sweep_skips_settled_after_compaction(tmp_path):
    # End-to-end: settle a sweep, compact, resume — the compacted
    # ledger must still drive the skip.
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
    from multidisttorch_tpu.hpo.ledger import SweepLedger

    train = synthetic_mnist(64, seed=0)
    cfgs = [
        TrialConfig(i, epochs=1, batch_size=16, hidden_dim=16,
                    latent_dim=4, seed=i)
        for i in range(2)
    ]
    kw = dict(num_groups=2, out_dir=str(tmp_path), verbose=False,
              save_images=False, save_checkpoints=False)
    rs = run_hpo(cfgs, train, None, **kw)
    assert all(r.status == "completed" for r in rs)
    SweepLedger(str(tmp_path)).compact()
    rs2 = run_hpo(cfgs, train, None, resume=True, **kw)
    assert all(r.status == "resumed_complete" for r in rs2)


def test_ledger_view_compact_cli(tmp_path):
    _storm_ledger(tmp_path)
    sys.path.insert(0, _TOOLS)
    try:
        import ledger_view
    finally:
        sys.path.remove(_TOOLS)
    assert ledger_view.main(["--compact", str(tmp_path)]) == 0
    assert ledger_view.main(["--json", str(tmp_path)]) == 0


# --------------------------------------------------------------------
# host-scoped fault kinds
# --------------------------------------------------------------------


def test_fault_spec_host_kinds_validation():
    from multidisttorch_tpu.faults.plan import (
        HOST_LOST,
        WEDGE,
        FaultPlan,
        FaultSpec,
    )

    spec = FaultSpec(HOST_LOST, trial_id=-1, step=12, host=1)
    assert spec.host == 1
    with pytest.raises(ValueError, match="host"):
        FaultSpec(WEDGE, trial_id=-1, step=3)  # host missing
    with pytest.raises(ValueError, match="step"):
        FaultSpec(HOST_LOST, trial_id=-1, host=1)  # step missing
    # JSON round-trip carries the host field
    plan = FaultPlan(specs=(spec,), seed=3)
    assert FaultPlan.from_json(plan.to_json()).specs[0].host == 1


def test_injector_host_lost_fires_on_cumulative_clock(monkeypatch):
    from multidisttorch_tpu.faults import inject
    from multidisttorch_tpu.faults.plan import HOST_LOST, FaultPlan, FaultSpec

    exits = []
    monkeypatch.setattr(inject.os, "_exit", lambda code: exits.append(code))
    plan = FaultPlan(
        specs=(FaultSpec(HOST_LOST, trial_id=-1, step=10, host=2),)
    )
    inj = inject.FaultInjector(plan, host_slot=2)
    # trial steps don't matter; the HOST clock does (any trial's hook)
    inj.step_hook(0, 0, 4)   # host steps 0..4
    inj.step_hook(1, 0, 4)   # 4..8
    assert not exits
    inj.step_hook(0, 4, 4)   # 8..12 covers step 10 -> fires
    assert exits == [inject.HOST_LOST_EXIT_CODE]
    # wrong slot never fires
    inj2 = inject.FaultInjector(plan, host_slot=0)
    inj2.step_hook(0, 0, 100)
    assert len(exits) == 1
    # no slot (single-controller) never fires
    inj3 = inject.FaultInjector(plan)
    inj3.step_hook(0, 0, 100)
    assert len(exits) == 1


def test_injector_wedge_suspends_heartbeat_then_preempts(tmp_path):
    from multidisttorch_tpu.faults import inject
    from multidisttorch_tpu.faults.plan import WEDGE, FaultPlan, FaultSpec
    from multidisttorch_tpu.parallel import membership as m

    hb = m.start_heartbeat(str(tmp_path), 1, interval_s=0.02)
    try:
        plan = FaultPlan(
            specs=(
                FaultSpec(WEDGE, trial_id=-1, step=0, host=1,
                          delay_s=0.05),
            )
        )
        inj = inject.FaultInjector(plan, host_slot=1)
        with pytest.raises(inject.HostPreemption, match="wedge"):
            inj.step_hook(0, 0, 1)
        assert hb._suspended.is_set()
        assert inj.fired and inj.fired[0]["kind"] == WEDGE
    finally:
        m.stop_heartbeat()


def test_injector_fired_log_survives_restart(tmp_path):
    from multidisttorch_tpu.faults import inject
    from multidisttorch_tpu.faults.plan import CRASH, FaultPlan, FaultSpec

    log = str(tmp_path / "fired.jsonl")
    plan = FaultPlan(specs=(FaultSpec(CRASH, trial_id=0, step=5),))
    inj = inject.FaultInjector(plan, fired_log=log)
    with pytest.raises(inject.InjectedCrash):
        inj.step_hook(0, 5, 1)
    # a "restarted host" builds a fresh injector over the same log:
    # the one-shot fault must stay fired
    inj2 = inject.FaultInjector(plan, fired_log=log)
    inj2.step_hook(0, 5, 1)  # no raise
    assert inj2.fired == []  # nothing new fired


# --------------------------------------------------------------------
# supervisor (fast: fake no-device workers)
# --------------------------------------------------------------------

_FAKE_WORKER = textwrap.dedent(
    """
    import os, signal, sys, time
    sys.path.insert(0, {repo!r})
    from multidisttorch_tpu.parallel import membership

    slot = int(os.environ["MDT_HOST_SLOT"])
    epoch = int(os.environ["MDT_WORLD_EPOCH"])
    run_dir = os.environ["MDT_ELASTIC_RUN_DIR"]
    membership.start_heartbeat(
        run_dir, slot, interval_s=0.05, world_epoch=epoch,
        world_size=int(os.environ["OMPI_COMM_WORLD_SIZE"]),
    )

    def on_term(sig, frame):
        membership.stop_heartbeat()
        sys.exit(75)  # the drain contract: healthy host, lost world

    signal.signal(signal.SIGTERM, on_term)

    if epoch == 0:
        if slot == 1:
            time.sleep(0.6)
            os._exit(86)  # hard host loss (SIGKILL semantics)
        while True:
            time.sleep(0.05)  # train forever; supervisor drains us
    else:
        time.sleep(0.4)  # the shrunken world finishes the sweep
        membership.stop_heartbeat()
        sys.exit(0)
    """
)


def test_supervisor_shrinks_world_on_hard_host_loss(tmp_path):
    sys.path.insert(0, _TOOLS)
    try:
        from sweep_supervisor import ElasticSupervisor
    finally:
        sys.path.remove(_TOOLS)

    worker = tmp_path / "fake_worker.py"
    worker.write_text(_FAKE_WORKER.format(repo=_REPO))
    run_dir = str(tmp_path / "run")
    os.makedirs(run_dir)
    sup = ElasticSupervisor(
        [sys.executable, str(worker)],
        run_dir,
        3,
        heartbeat_deadline_s=1.0,
        poll_s=0.05,
        boot_grace_s=10.0,
        drain_grace_s=5.0,
        world_timeout_s=60.0,
        compact_ledger=False,  # no ledger in the fake sweep
    )
    report = sup.run()
    assert report["success"]
    assert report["worlds_formed"] == 2
    assert report["hosts_lost"] == [1]
    assert report["worlds"][0]["outcome"] == "host_lost"
    assert report["worlds"][1]["outcome"] == "complete"
    assert report["worlds"][1]["hosts"] == [0, 2]
    # survivors were drained, not blamed: their exits are 75/terms
    w0 = report["worlds"][0]["exits"]
    assert w0[1] not in (0, 75)
    # the durable world history matches the report
    from multidisttorch_tpu.parallel.membership import world_history

    hist = world_history(run_dir)
    assert [w["epoch"] for w in hist] == [0, 1]
    assert hist[1]["lost"] == [1]


# --------------------------------------------------------------------
# SIGTERM graceful drain (subprocess; single-host, so tier-1-fast)
# --------------------------------------------------------------------

_DRAIN_WORKER = os.path.join(os.path.dirname(__file__), "drain_worker.py")


@pytest.mark.chaos
def test_sigterm_drain_preemption_exit_and_bounded_loss(tmp_path):
    from multidisttorch_tpu.hpo.ledger import SweepLedger
    from multidisttorch_tpu.parallel.cluster import PREEMPTION_EXIT_CODE

    out_dir = str(tmp_path / "sweep")
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    p = subprocess.Popen(
        [sys.executable, _DRAIN_WORKER, out_dir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    # Wait for epoch >= 2 to be durably checkpointed, then SIGTERM.
    meta_path = os.path.join(out_dir, "trial-0", "state.msgpack.json")
    deadline = time.time() + 180
    killed = False
    while time.time() < deadline and p.poll() is None:
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            if int(meta.get("completed_epochs", 0)) >= 2:
                p.send_signal(signal.SIGTERM)
                killed = True
                break
        except (OSError, ValueError):
            pass
        time.sleep(0.01)
    assert killed, "sweep finished before the drain could be exercised"
    out = p.communicate(timeout=120)[0]
    assert p.returncode == PREEMPTION_EXIT_CODE, out[-2000:]
    assert "HostPreemption" in out and "graceful drain" in out, out[-2000:]

    # The drain recorded the in-flight attempt (fsync'd ledger).
    led = SweepLedger(out_dir)
    pre = [
        ev for ev in led.load()
        if ev.get("event") == "attempt_end"
        and ev.get("status") == "preempted"
    ]
    assert pre and "graceful drain" in pre[-1]["error"]
    steps_at_kill = int(pre[-1]["summary"]["steps_at_failure"])

    # Resume: completes, and lost work <= one checkpoint cadence.
    p2 = subprocess.run(
        [sys.executable, _DRAIN_WORKER, out_dir, "resume"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=300,
    )
    assert p2.returncode == 0, p2.stdout[-2000:]
    line = [l for l in p2.stdout.splitlines() if l.startswith("RESULT ")]
    res = json.loads(line[-1][len("RESULT "):])
    assert res["status"] == "completed"
    steps_per_epoch = 1024 // 32
    assert res["resumed_from_step"] >= steps_per_epoch  # real resume
    assert steps_at_kill - res["resumed_from_step"] <= steps_per_epoch


# --------------------------------------------------------------------
# true multi-controller elastic drills (subprocess worlds; slow tier)
# --------------------------------------------------------------------


def _launch_mh(mode, tmp_path, *, nprocs=2, devs_per_proc=4, timeout=420,
               extra_env=None):
    import test_multihost  # same-directory import (pytest rootdir path)

    return test_multihost._launch(
        mode, tmp_path, nprocs=nprocs, devs_per_proc=devs_per_proc,
        timeout=timeout, extra_env=extra_env,
    )


@pytest.mark.multihost
def test_cross_host_restore_agreement_min_step(tmp_path):
    # A real 2-process world over a real keep-last checkpoint lineage
    # (steps 4 and 8). With process 1's VIEW of the newest candidate
    # torn, BOTH processes must agree on the earlier step 4 — without
    # the agreement, process 0 would restore step 8 and desync SPMD.
    # Healthy views agree on 8; a host seeing nothing valid degrades
    # both to scratch; and a participant that never joins produces a
    # NAMED WedgedCollective within the deadline (no hang).
    r0, r1 = _launch_mh("elastic_restore_agree", tmp_path)
    assert r0["torn_agreed"] == r1["torn_agreed"] == 4
    assert r0["healthy_agreed"] == r1["healthy_agreed"] == 8
    assert r0["none_agreed"] is None and r1["none_agreed"] is None
    assert r0["wedge"] == "WedgedCollective"
    assert r0["wedge_wait_s"] < 10  # bounded by the 2s deadline + slop


@pytest.mark.multihost
def test_elastic_drill_host_lost_three_hosts(tmp_path):
    # The kill-one-of-3 drill end-to-end through the real harness:
    # host 1 dies mid-sweep (os._exit, heartbeat and all), the
    # supervisor re-forms a 2-host world, the survivors finish every
    # trial, recovered results bit-match the fault-free reference.
    from multidisttorch_tpu.faults.harness import run_chaos_mh_bench

    report = run_chaos_mh_bench(
        str(tmp_path),
        hosts=3,
        devs_per_host=2,
        trials=4,
        epochs=2,
        kind="host_lost",
        victim=1,
        heartbeat_deadline_s=2.0,
        agree_timeout_s=10.0,
        boot_grace_s=90.0,
        world_timeout_s=300.0,
    )
    assert report["worlds_formed"] >= 2, report["supervisor"]
    assert report["hosts_lost"] == [1]
    assert report["hosts_final"] == 2
    assert report["all_trials_settled"], report["statuses"]
    assert report["recovered_bit_identical"], report["parity"]
    assert report["goodput"] > 0.5
    assert report["membership"]["host_lost_traced"]
    assert report["membership"]["world_shrunk_traced"]


@pytest.mark.multihost
def test_wedge_exits_with_named_wedged_collective(tmp_path):
    # A wedged host (stalled, heartbeat suspended) on a SPANNING group:
    # the survivor's sync watchdog must exit with a NAMED
    # WedgedCollective within the deadline (never a test timeout), the
    # supervisor must classify the wedged host as lost via its stale
    # lease, and the shrunken world must finish the sweep.
    from multidisttorch_tpu.faults.harness import run_chaos_mh_bench

    report = run_chaos_mh_bench(
        str(tmp_path),
        hosts=2,
        devs_per_host=2,
        trials=3,
        epochs=2,
        kind="wedge",
        victim=1,
        # The survivor must hit its bounded end-of-sweep barrier (8s)
        # BEFORE the supervisor's staleness verdict fires, so the
        # WedgedCollective exit path is what gets exercised — hence a
        # deliberately lazy heartbeat deadline.
        heartbeat_deadline_s=45.0,
        agree_timeout_s=8.0,
        boot_grace_s=90.0,
        world_timeout_s=300.0,
    )
    assert report["wedged_collective_exits"] >= 1, report["supervisor"]
    assert report["hosts_lost"] == [1]
    assert report["worlds_formed"] >= 2
    assert report["all_trials_settled"], report["statuses"]
    assert report["membership"]["host_lost_traced"]
