"""Checkpoint data plane v2 (docs/RESILIENCE.md "Checkpoint format
v2"): content-addressed chunk store, incremental manifests, refcounted
GC + orphan sweep, chunk-complete verification/scan-back, the
cross-host restore agreement over chunked checkpoints, and the
snapshot-fast preemption drain (ledger honesty + RAM re-place)."""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train import checkpoint as ck
from multidisttorch_tpu.train import ckpt_store as cs
from multidisttorch_tpu.train.steps import build_train_state

pytestmark = pytest.mark.ckpt


def _state(step=0, seed=0, hidden=16):
    s = build_train_state(
        VAE(hidden_dim=hidden, latent_dim=4),
        optax.adam(1e-3),
        jax.random.key(seed),
    )
    return s.replace(step=jnp.asarray(step, jnp.int32))


def _tree_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(jax.device_get(a)), jax.tree.leaves(
        jax.device_get(b)
    )
    return len(la) == len(lb) and all(
        np.asarray(x).dtype == np.asarray(y).dtype
        and np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def _save_v2(state, path, step, *, keep_last=1, chunk=4096, stats=None):
    return ck.save_state(
        state,
        path,
        metadata={"step": step, "completed_epochs": max(1, step // 8)},
        keep_last=keep_last,
        format="v2",
        chunk_bytes=chunk,
        stats_out=stats,
    )


# -- chunk store ------------------------------------------------------


def test_chunk_store_roundtrip_dedup_crc(tmp_path):
    store = cs.ChunkStore(str(tmp_path / "chunks"))
    blob = os.urandom(10_000)
    digest, written = store.put(blob)
    assert written == len(blob)
    # Content-addressed dedup: the second landing writes nothing.
    digest2, written2 = store.put(blob)
    assert digest2 == digest and written2 == 0
    assert store.read(digest) == blob
    ok, reason = store.verify(digest, nbytes=len(blob))
    assert ok, reason
    # Bit-rot: payload garbled under a valid sidecar.
    with open(store.chunk_path(digest), "r+b") as f:
        f.seek(100)
        f.write(b"\xff" * 8)
    ok, reason = store.verify(digest)
    assert not ok and "crc32 mismatch" in reason
    with pytest.raises(IOError):
        store.read(digest)


def test_v2_save_restore_bitwise_and_sidecar(tmp_path):
    path = str(tmp_path / "state.msgpack")
    s = _state(3, seed=1)
    stats = {}
    _save_v2(s, path, 3, stats=stats)
    assert stats["format"] == "v2" and stats["total_bytes"] > 0
    # The primary file is a tiny manifest, not the full state.
    assert os.path.getsize(path) < stats["total_bytes"] // 10
    assert cs.is_manifest_file(path)
    ok, meta, reason = ck.verify_checkpoint(path)
    assert ok, reason
    assert meta["_format"] == "v2"
    restored = ck.restore_state(_state(), path)
    assert _tree_equal(restored, s)


def test_incremental_resave_references_unchanged_chunks(tmp_path):
    path = str(tmp_path / "state.msgpack")
    s = _state(8, seed=2)
    _save_v2(s, path, 8)
    stats = {}
    _save_v2(s, path, 8, stats=stats)
    # Bit-identical state: every chunk referenced, none rewritten.
    assert stats["new_bytes"] == 0
    assert stats["reused_bytes"] == stats["total_bytes"]
    # Touch ONE leaf: only its chunks cost bytes.
    s2 = s.replace(
        params={
            **dict(s.params),
            "fc21": jax.tree.map(lambda x: x + 1, dict(s.params)["fc21"]),
        }
    )
    stats2 = {}
    _save_v2(s2, path, 9, stats=stats2)
    fc21_bytes = sum(
        np.asarray(x).nbytes
        for x in jax.tree.leaves(dict(jax.device_get(s2.params))["fc21"])
    )
    assert 0 < stats2["new_bytes"] <= fc21_bytes + 2 * 4096
    restored = ck.restore_state(_state(), path)
    assert _tree_equal(restored, s2)


def test_torn_manifest_scans_back(tmp_path):
    path = str(tmp_path / "state.msgpack")
    (g,) = setup_groups(1)
    s8, s16 = _state(8, seed=1), _state(16, seed=2)
    _save_v2(s8, path, 8, keep_last=2)
    _save_v2(s16, path, 16, keep_last=2)
    # Torn manifest: truncated mid-write.
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    ok, _, reason = ck.verify_checkpoint(path)
    assert not ok and "size mismatch" in reason
    got = ck.restore_latest_valid(_state(), path, g)
    assert got is not None
    restored, meta, used = got
    assert int(meta["step"]) == 16 and used.endswith(".v0000000016")
    assert _tree_equal(restored, s16)


def test_missing_chunk_scans_back_to_previous_step(tmp_path):
    path = str(tmp_path / "state.msgpack")
    (g,) = setup_groups(1)
    s8, s16 = _state(8, seed=1), _state(16, seed=2)
    _save_v2(s8, path, 8, keep_last=2)
    _save_v2(s16, path, 16, keep_last=2)
    store = cs.ChunkStore(cs.chunk_dir_for(path))
    newest = cs.read_manifest_file(path)
    prev = cs.read_manifest_file(path + ".v0000000008")
    unique = cs.manifest_digests(newest) - cs.manifest_digests(prev)
    assert unique  # different seeds -> different params
    os.remove(store.chunk_path(next(iter(unique))))
    ok, _, reason = ck.verify_checkpoint(path)
    assert not ok and "chunk-incomplete" in reason
    # The .v16 version references the SAME missing chunk — the scan
    # must fall all the way back to step 8, which is chunk-complete.
    got = ck.restore_latest_valid(_state(), path, g)
    assert got is not None
    restored, meta, used = got
    assert int(meta["step"]) == 8
    assert _tree_equal(restored, s8)


# -- retention + GC ---------------------------------------------------


def _stable_and_moving(step, seed_moving):
    """A state whose encoder subtree is bitwise-stable across saves
    while the decoder moves — the chunk-sharing fixture."""
    s = _state(step, seed=0)
    p = dict(jax.device_get(s.params))
    p["fc4"] = jax.tree.map(
        lambda x: np.asarray(x) + np.float32(seed_moving), p["fc4"]
    )
    return s.replace(params=p)


def test_retention_shares_chunks_and_never_drops_referenced(tmp_path):
    path = str(tmp_path / "state.msgpack")
    store = cs.ChunkStore(cs.chunk_dir_for(path))
    for i, step in enumerate((8, 16, 24)):
        _save_v2(_stable_and_moving(step, i), path, step, keep_last=2)
    # keep_last=2: step 8's version pruned; its UNIQUE chunks are gone,
    # the shared (stable-subtree) chunks survive for 16/24.
    assert not os.path.exists(path + ".v0000000008")
    m24 = cs.read_manifest_file(path)
    m16 = cs.read_manifest_file(path + ".v0000000016")
    shared = cs.manifest_digests(m24) & cs.manifest_digests(m16)
    assert shared  # the stable encoder dedups across versions
    # The eviction-never-drops-a-referenced-chunk regression: every
    # RETAINED manifest stays chunk-complete after pruning.
    for cand in ck.checkpoint_candidates(path):
        ok, _, reason = ck.verify_checkpoint(cand)
        assert ok, (cand, reason)
    # Refcounts: shared chunks counted once per referencing manifest.
    refs = store.refcounts()
    for d in shared:
        assert refs.get(d, 0) >= 2
    # Disk holds no chunk that zero retained manifests reference
    # (the primary-replace + prune decrements fired).
    live = cs.manifest_digests(m24) | cs.manifest_digests(m16)
    on_disk = set(store.all_chunks())
    assert on_disk == live


def test_gc_reconciles_and_sweeps_orphans(tmp_path):
    path = str(tmp_path / "state.msgpack")
    s = _state(8, seed=3)
    _save_v2(s, path, 8)
    store = cs.ChunkStore(cs.chunk_dir_for(path))
    # A crashed save's leak: chunks landed, no manifest references
    # them, refcounts never updated.
    orphan, _ = store.put(os.urandom(5000))
    # And a leaked COUNT: refs claim a manifest that does not exist.
    store.incr({orphan})
    rep = cs.sweep_ckpt_dir(str(tmp_path), grace_s=3600.0)
    assert rep["orphans_removed"] == 0 and rep["kept_in_grace"] == 1
    assert rep["leaked_refs_reconciled"] >= 1  # the bogus count dropped
    rep = cs.sweep_ckpt_dir(str(tmp_path), grace_s=0.0)
    assert rep["orphans_removed"] == 1
    assert not os.path.exists(store.chunk_path(orphan))
    # The referenced manifest stays restorable — even with refs.json
    # deleted entirely (the sweep rebuilds it from the manifests).
    os.remove(store.refs_path())
    rep = cs.sweep_ckpt_dir(str(tmp_path), grace_s=0.0)
    assert rep["orphans_removed"] == 0
    ok, _, reason = ck.verify_checkpoint(path)
    assert ok, reason
    assert _tree_equal(ck.restore_state(_state(), path), s)


def test_ckpt_gc_cli(tmp_path, capsys):
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import ckpt_gc

    d = tmp_path / "run" / "trial-0"
    d.mkdir(parents=True)
    path = str(d / "state.msgpack")
    _save_v2(_state(8), path, 8)
    store = cs.ChunkStore(cs.chunk_dir_for(path))
    orphan, _ = store.put(os.urandom(1000))
    # Dry run: reports, removes nothing.
    rc = ckpt_gc.main([str(tmp_path / "run"), "--dry-run", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["totals"]["dirs"] == 1
    assert out["reports"][0]["orphans_found"] == 1
    assert os.path.exists(store.chunk_path(orphan))
    # Real sweep.
    rc = ckpt_gc.main([str(tmp_path / "run"), "--grace", "0", "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["totals"]["orphans_removed"] == 1
    assert not os.path.exists(store.chunk_path(orphan))
    ok, _, reason = ck.verify_checkpoint(path)
    assert ok, reason


_KILL_CHILD = r"""
import os, sys
sys.path.insert(0, sys.argv[2])
os.environ["MDT_CKPT_PERSIST_DELAY_S"] = "0.15"
import jax, optax
import jax.numpy as jnp
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.train import checkpoint as ck
from multidisttorch_tpu.train.steps import build_train_state

s = build_train_state(VAE(hidden_dim=16, latent_dim=4),
                      optax.adam(1e-3), jax.random.key(0))
path = sys.argv[1]
step = 0
while True:
    step += 8
    ck.save_state(
        s.replace(step=jnp.asarray(step, jnp.int32)), path,
        metadata={"step": step, "completed_epochs": step // 8},
        keep_last=2, format="v2", chunk_bytes=2048,
    )
    print("SAVED %d" % step, flush=True)
"""


@pytest.mark.ckpt
def test_kill_mid_save_leaves_previous_step_restorable(tmp_path):
    """SIGKILL DURING a v2 persist (the delay env holds every save
    open for 150ms): the previous step stays restorable, the wreckage
    is leaked chunks at worst, and the orphan sweep reclaims them
    without touching the survivors."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    path = str(tmp_path / "state.msgpack")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [
            sys.executable,
            "-c",
            _KILL_CHILD,
            path,
            os.path.abspath(repo),
        ],
        stdout=subprocess.PIPE,
        text=True,
        env=env,
    )
    saved = 0
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("SAVED"):
                saved = int(line.split()[1])
                if saved >= 16:
                    break
        assert saved >= 16, "child never reached two durable saves"
        # Kill mid-save: the delay guarantees the NEXT save is open
        # for a long window; give it time to enter it.
        time.sleep(0.05)
        os.kill(proc.pid, signal.SIGKILL)
    finally:
        proc.wait(timeout=30)
    (g,) = setup_groups(1)
    got = ck.restore_latest_valid(_state(), path, g)
    assert got is not None
    restored, meta, used = got
    # A step the child durably reported (or one the kill let finish).
    assert int(meta["step"]) >= saved - 8
    assert int(jax.device_get(restored.step)) == int(meta["step"])
    # Crash wreckage never corrupts: sweep reclaims leaks, survivors
    # stay chunk-complete and restorable.
    cs.sweep_ckpt_dir(str(tmp_path), grace_s=0.0)
    got2 = ck.restore_latest_valid(_state(), path, g)
    assert got2 is not None and int(got2[1]["step"]) == int(meta["step"])
    # And the directory keeps working: a fresh save on top is clean.
    _save_v2(_state(99), path, 99)
    ok, _, reason = ck.verify_checkpoint(path)
    assert ok, reason


# -- agreement / cache ------------------------------------------------


def test_agreed_restore_step_over_chunked_checkpoints(tmp_path):
    """The cross-host restore agreement's read side over v2: local
    candidate verification is chunk-complete, so a host whose newest
    manifest lost a chunk votes the previous step."""
    path = str(tmp_path / "state.msgpack")
    _save_v2(_state(8, seed=1), path, 8, keep_last=2)
    _save_v2(_state(16, seed=2), path, 16, keep_last=2)
    got = ck.agreed_restore_step(
        path, name="t0:a1", participants=[0], timeout_s=5.0
    )
    assert got is not None and got[0] == 16
    # Lose a chunk unique to step 16 on "this host": the vote drops.
    store = cs.ChunkStore(cs.chunk_dir_for(path))
    uniq = cs.manifest_digests(cs.read_manifest_file(path)) - (
        cs.manifest_digests(
            cs.read_manifest_file(path + ".v0000000008")
        )
    )
    os.remove(store.chunk_path(next(iter(uniq))))
    got = ck.agreed_restore_step(
        path, name="t0:a2", participants=[0], timeout_s=5.0
    )
    assert got is not None and got[0] == 8


def test_snapshot_cache_semantics():
    cache = ck._SnapshotCache(max_entries=2)
    cache.put("/a/t1/s.msgpack", {"x": 1}, {"step": 1})
    cache.put("/a/t2/s.msgpack", {"x": 2}, {"step": 2})
    got = cache.get("/a/t1/s.msgpack")
    assert got is not None and got[0] == {"x": 1}
    # LRU bound: t1 was just touched, so t2 evicts.
    cache.put("/a/t3/s.msgpack", {"x": 3}, {"step": 3})
    assert cache.get("/a/t2/s.msgpack") is None
    assert cache.get("/a/t1/s.msgpack") is not None
    # Ownership-change invalidation: everything under a dir drops.
    assert cache.drop_under("/a") == 2
    assert len(cache) == 0


def test_driver_v2_skips_gather_for_sharded_state():
    """The sharded-native save path: under v2 a single-controller
    ZeRO state checkpoints WITHOUT the gather-to-replicated dispatch;
    v1 keeps it (serialization needs one blob)."""
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.driver import TrialConfig, _TrialRun

    import tempfile

    (g,) = setup_groups(1)
    data = synthetic_mnist(64, seed=0)
    out = tempfile.mkdtemp()
    base = dict(
        epochs=1, batch_size=32, hidden_dim=16, latent_dim=4,
        zero_update=True,
    )
    run_v2 = _TrialRun(
        g, TrialConfig(trial_id=0, **base), data, None,
        out, save_images=False, verbose=False,
        ckpt_format="v2",
    )
    assert run_v2._gather_state is None
    run_v1 = _TrialRun(
        g, TrialConfig(trial_id=1, **base), data, None,
        out, save_images=False, verbose=False,
        ckpt_format="v1",
    )
    assert run_v1._gather_state is not None


def test_pipeline_stage_manifests_share_one_store(tmp_path):
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.driver import TrialConfig
    from multidisttorch_tpu.hpo.pipeline_run import run_pipeline_trial

    groups = setup_groups(2)
    cfg = TrialConfig(
        trial_id=0, epochs=1, batch_size=32, latent_dim=4,
        pipeline_stages=2, grad_accum=2,
    )
    os.environ["MDT_CKPT_FORMAT"] = "v2"
    try:
        run_pipeline_trial(
            cfg, synthetic_mnist(64, seed=0),
            stage_meshes=groups, out_dir=str(tmp_path),
        )
    finally:
        os.environ.pop("MDT_CKPT_FORMAT", None)
    d = tmp_path / "trial-0"
    stage_paths = [str(d / f"stage{s}.msgpack") for s in range(2)]
    for p in stage_paths:
        assert cs.is_manifest_file(p)
        ok, meta, reason = ck.verify_checkpoint(p)
        assert ok, reason
        assert meta["pipeline_stage"] is True
    # One chunk store per trial dir, shared by both stage families.
    assert cs.chunk_dir_for(stage_paths[0]) == cs.chunk_dir_for(
        stage_paths[1]
    )
    assert len(cs.live_manifest_files(str(d))) == 2


# -- snapshot-fast drain (service) ------------------------------------


@pytest.mark.service
def test_snapshot_drain_honesty_and_ram_replace(tmp_path):
    """The drain contract end to end: slices free at the snapshot, the
    ledger records `preempted` only after the background persist lands,
    the victim re-places from the RAM snapshot, and the trace renders
    the snapshot/persist split inside the attempt."""
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.service import queue as squeue
    from multidisttorch_tpu.service.runtime import SweepService
    from multidisttorch_tpu.telemetry import trace as ttrace

    d = str(tmp_path / "svc")
    os.makedirs(d)
    telemetry.configure(os.path.join(d, "telemetry"))
    os.environ[
        "MDT_CKPT_PERSIST_DELAY_S"
    ] = "0.4"
    ram0 = ck.ckpt_counters()["restores_ram"]
    try:
        client = squeue.SweepClient(d, tenant="t")
        sub = client.submit(
            {
                "epochs": 4,
                "batch_size": 32,
                "latent_dim": 4,
                "hidden_dim": 16,
                "log_interval": 1000,
            }
        )
        svc = SweepService(
            d, n_slices=1, max_lanes=1, data_rows=128,
            defrag_enabled=False, snapshot_drain=True, ckpt_format="v2",
        )
        t0 = time.time()
        ap = None
        while time.time() - t0 < 60:
            svc.tick()
            actives = list(svc.active.values())
            if actives and bool(
                actives[0].run.result.checkpoint
            ) and not actives[0].run._ckpt_idle():
                ap = actives[0]
                break
        assert ap is not None, "no in-flight checkpoint write observed"
        tid = next(iter(ap.entries)).__int__()

        svc._checkpoint_drain(ap, reason="test preemption")
        # Snapshot phase: slices free NOW, persist still in flight,
        # and the ledger does NOT say preempted yet.
        assert svc.pool.free_total == 1
        assert len(svc._pending_persists) == 1
        with open(svc.ledger.path) as f:
            assert '"preempted"' not in f.read()
        # Persist lands -> honest record + requeue.
        t0 = time.time()
        while svc._pending_persists and time.time() - t0 < 30:
            svc.tick()
        assert not svc._pending_persists
        with open(svc.ledger.path) as f:
            led = f.read()
        assert led.count('"preempted"') == 1
        # The victim re-places in THIS process: RAM-snapshot restore.
        t0 = time.time()
        while not svc.settled.get(sub) and time.time() - t0 < 120:
            svc.tick()
        assert svc.settled.get(sub) == "completed"
        assert ck.ckpt_counters()["restores_ram"] > ram0
        books = svc.books()
        ckb = books["checkpoint"]
        assert ckb["drain_snapshot"]["count"] == 1
        assert ckb["drain_persist"]["count"] == 1
        # Snapshot freed the slices faster than the persist landed.
        assert (
            ckb["drain_snapshot"]["max_s"]
            < ckb["drain_persist"]["max_s"]
        )
        assert ckb["restores_ram"] >= 1
        svc._drain(reason="test end")
        svc.store.shutdown()
    finally:
        os.environ.pop("MDT_CKPT_PERSIST_DELAY_S", None)
        telemetry.disable()
    # The offline trace renders the split: a ckpt_persist SPAN (not
    # instant) with real duration inside the submission's tree.
    traces = ttrace.build_submission_traces(d)
    tr = traces[sub]
    names = {
        s["name"]: s for s in tr["spans"]
    }
    assert "ckpt_persist" in names
    persist = names["ckpt_persist"]
    assert persist["kind"] == "span"
    assert persist["end"] - persist["start"] > 0.05
    assert any(
        s["name"] == "ckpt_snapshot" for s in tr["spans"]
    )
    assert tid is not None  # silence unused warnings


@pytest.mark.service
def test_legacy_join_drain_mode_still_blocks(tmp_path):
    """MDT_SNAPSHOT_DRAIN=0 / snapshot_drain=False keeps the v1-era
    semantics: the drain joins the persist inline, records preempted
    immediately, and requeues before returning — the bench's
    comparison arm, and the conservative operator escape hatch."""
    from multidisttorch_tpu.service import queue as squeue
    from multidisttorch_tpu.service.runtime import SweepService

    d = str(tmp_path / "svc")
    os.makedirs(d)
    client = squeue.SweepClient(d, tenant="t")
    client.submit(
        {
            "epochs": 3,
            "batch_size": 32,
            "latent_dim": 4,
            "hidden_dim": 16,
            "log_interval": 1000,
        }
    )
    svc = SweepService(
        d, n_slices=1, max_lanes=1, data_rows=128,
        defrag_enabled=False, snapshot_drain=False, ckpt_format="v1",
    )
    t0 = time.time()
    ap = None
    while time.time() - t0 < 60:
        svc.tick()
        actives = list(svc.active.values())
        if actives and bool(actives[0].run.result.checkpoint):
            ap = actives[0]
            break
    assert ap is not None
    svc._checkpoint_drain(ap, reason="test preemption")
    # Everything happened inline: no pending persist, ledger already
    # has the record, pool already free.
    assert not svc._pending_persists
    assert svc.pool.free_total == 1
    with open(svc.ledger.path) as f:
        assert '"preempted"' in f.read()
    svc._drain(reason="test end")
    svc.store.shutdown()


def test_sweep_top_renders_ckpt_books():
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    import sweep_top

    from types import SimpleNamespace

    books = {
        "checkpoint": {
            "format": "v2",
            "snapshot_drain": True,
            "pending_persists": 1,
            "saves": 12,
            "bytes_total": 10_000_000,
            "bytes_written": 2_500_000,
            "bytes_reused": 7_500_000,
            "delta_ratio": 0.25,
            "restores": 3,
            "restores_ram": 2,
            "drain_snapshot": {
                "count": 2, "p50_s": 0.001, "p99_s": 0.002,
                "max_s": 0.002,
            },
            "drain_persist": {
                "count": 2, "p50_s": 0.3, "p99_s": 0.5, "max_s": 0.5,
            },
        },
    }
    out = sweep_top.render_service(
        {}, books, SimpleNamespace(trials={}), "/tmp/svc"
    )
    assert "ckpt" in out and "fmt v2" in out
    assert "delta 0.25" in out
    assert "ram-restores 2" in out
    assert "drain-snapshot" in out and "drain-persist" in out
