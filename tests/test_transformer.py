"""TransformerLM with ring-parallel attention: dense-vs-ring parity and
sequence-parallel training. The reference has no attention at all
(SURVEY.md §5); this is the model that makes the long-context op a
usable capability. 8 virtual CPU devices."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from multidisttorch_tpu.models.transformer import TransformerLM
from multidisttorch_tpu.ops.ring_attention import make_ring_attention
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, setup_groups
from multidisttorch_tpu.train.lm import (
    create_lm_state,
    lm_loss_mean,
    make_lm_train_step,
)

VOCAB = 17


_COMMON = dict(
    vocab_size=VOCAB, d_model=32, num_heads=2, num_layers=2, max_len=64
)


def _models(trial):
    dense = TransformerLM(**_COMMON)
    ring = TransformerLM(
        attention=make_ring_attention(trial, causal=True), **_COMMON
    )
    return dense, ring


def _tokens(b=2, t=32, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, VOCAB, (b, t)).astype(np.int32)
    )


def test_ring_lm_forward_matches_dense():
    (g,) = setup_groups(1)  # 8-device ring over the sequence
    dense, ring = _models(g)
    tokens = _tokens()
    params = dense.init({"params": jax.random.key(0)}, tokens)["params"]
    logits_dense = dense.apply({"params": params}, tokens)
    logits_ring = jax.jit(
        lambda p, tk: ring.apply({"params": p}, tk)
    )(params, jax.device_put(tokens, g.sharding(None, DATA_AXIS)))
    np.testing.assert_allclose(
        np.asarray(logits_ring), np.asarray(logits_dense),
        rtol=2e-4, atol=2e-5,
    )


def test_lm_multi_step_matches_sequential_steps():
    # The scan-fused LM dispatch (make_lm_multi_step — the bench's TPU
    # timing path, docs/DISPATCH.md) must be a pure fusion: K chained
    # steps in one program produce the same losses and params as K
    # single-step dispatches. Checked for plain DP and for sequence
    # parallelism (tokens sharded over T).
    from multidisttorch_tpu.train.lm import make_lm_multi_step

    for sp in (False, True):
        (g,) = setup_groups(1)
        model = TransformerLM(**_COMMON)
        tx = optax.adam(1e-3)
        tokens = np.random.default_rng(7).integers(
            0, VOCAB, (3, 8, 32), dtype=np.int32
        )
        tok_sh = (
            g.sharding(None, DATA_AXIS) if sp else g.batch_sharding
        )

        state_a = create_lm_state(
            g, model, tx, jax.random.key(0), example_len=32
        )
        step = make_lm_train_step(g, model, tx, sequence_parallel=sp)
        seq_losses = []
        for i in range(3):
            state_a, m = step(
                state_a, jax.device_put(jnp.asarray(tokens[i]), tok_sh)
            )
            seq_losses.append(float(m["loss"]))

        state_b = create_lm_state(
            g, model, tx, jax.random.key(0), example_len=32
        )
        multi = make_lm_multi_step(g, model, tx, sequence_parallel=sp)
        chunks = jax.device_put(
            jnp.asarray(tokens),
            g.sharding(*((None, None, DATA_AXIS) if sp
                         else (None, DATA_AXIS, None))),
        )
        state_b, m = multi(state_b, chunks)
        assert m["loss"].shape == (3,)
        assert int(state_b.step) == int(state_a.step) == 3
        np.testing.assert_allclose(
            np.asarray(m["loss"]), seq_losses, rtol=1e-5, atol=1e-6
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            ),
            jax.device_get(state_b.params),
            jax.device_get(state_a.params),
        )


def test_ring_lm_grads_match_dense():
    (g,) = setup_groups(1)
    dense, ring = _models(g)
    tokens = _tokens(seed=1)
    params = dense.init({"params": jax.random.key(0)}, tokens)["params"]

    g_dense = jax.grad(
        lambda p: lm_loss_mean(dense.apply({"params": p}, tokens), tokens)
    )(params)
    tokens_sp = jax.device_put(tokens, g.sharding(None, DATA_AXIS))
    g_ring = jax.jit(
        jax.grad(
            lambda p: lm_loss_mean(
                ring.apply({"params": p}, tokens_sp), tokens_sp
            )
        )
    )(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5
        ),
        jax.device_get(g_ring),
        jax.device_get(g_dense),
    )


def test_sequence_parallel_training_learns_pattern():
    # T=64 sharded over 8 devices (8 tokens per chip): a periodic token
    # stream is perfectly predictable; SP training must drive the
    # next-token loss well below random (ln 17 ≈ 2.83).
    (g,) = setup_groups(1)
    _, ring = _models(g)
    tx = optax.adam(3e-3)
    state = create_lm_state(g, ring, tx, jax.random.key(0), example_len=64)
    step = make_lm_train_step(g, ring, tx, sequence_parallel=True)

    base = np.tile(np.arange(8), 16)[:64]  # period-8 pattern
    tokens = jax.device_put(
        jnp.asarray(np.stack([base, (base + 3) % 8]).astype(np.int32)),
        g.sharding(None, DATA_AXIS),
    )
    losses = []
    for _ in range(60):
        state, m = step(state, tokens)
        losses.append(float(m["loss"]))
    assert losses[0] > 2.0  # near-random at init
    assert losses[-1] < 0.7, losses[-1]


def test_trial_parallel_sequence_parallel_lms():
    # The composition examples/lm_hpo.py demonstrates: TWO concurrent
    # LM trials, each sequence-parallel on its own 4-device submesh
    # ring. Both must train independently (different lrs -> different
    # losses) and both must learn.
    groups = setup_groups(2)
    trials = []
    for g, lr in zip(groups, (1e-3, 3e-3)):
        model = TransformerLM(
            vocab_size=16, d_model=32, num_heads=2, num_layers=1,
            max_len=32, attention=make_ring_attention(g, causal=True),
        )
        tx = optax.adam(lr)
        base = np.tile(np.arange(8), 4)[:32]
        trials.append({
            "state": create_lm_state(g, model, tx, jax.random.key(0),
                                     example_len=32),
            "step": make_lm_train_step(g, model, tx,
                                       sequence_parallel=True),
            "tokens": jax.device_put(
                jnp.asarray(np.stack([base, (base + g.group_id) % 8])
                            .astype(np.int32)),
                g.sharding(None, DATA_AXIS),
            ),
        })
    first = []
    for i in range(40):
        for t in trials:  # cooperative round-robin, no barriers
            t["state"], t["m"] = t["step"](t["state"], t["tokens"])
        if i == 0:
            first = [float(t["m"]["loss"]) for t in trials]
    last = [float(t["m"]["loss"]) for t in trials]
    assert all(f > 1.5 for f in first)
    assert all(l < 1.0 for l in last), last
    assert last[0] != last[1]  # distinct hyperparameters, distinct runs


def test_lm_state_checkpoint_roundtrip(tmp_path):
    # The LM's TrainState rides the same msgpack checkpoint path as the
    # VAE/classifier states: save mid-training, restore, and the next
    # step must match the uninterrupted run bitwise.
    from multidisttorch_tpu.train.checkpoint import restore_state, save_state

    (g,) = setup_groups(1)
    _, ring = _models(g)
    tx = optax.adam(1e-3)
    state = create_lm_state(g, ring, tx, jax.random.key(0), example_len=64)
    step = make_lm_train_step(g, ring, tx, sequence_parallel=True)
    base = np.tile(np.arange(8), 8)[:64]
    tokens = jax.device_put(
        jnp.asarray(np.stack([base, (base + 3) % 8]).astype(np.int32)),
        g.sharding(None, DATA_AXIS),
    )
    for _ in range(3):
        state, _ = step(state, tokens)
    path = str(tmp_path / "lm.msgpack")
    save_state(state, path)
    cont, m_cont = step(state, tokens)

    template = create_lm_state(g, ring, tx, jax.random.key(1),
                               example_len=64)
    restored = restore_state(template, path, g)
    resumed, m_res = step(restored, tokens)
    assert float(m_cont["loss"]) == float(m_res["loss"])
    assert int(resumed.step) == int(cont.step) == 4


def test_lm_loss_masks_final_position():
    # A wrong prediction ONLY at the rolled-around final target must not
    # change the loss.
    logits = jnp.zeros((1, 4, VOCAB))
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    spiked = logits.at[0, 3, 5].set(100.0)  # affects only position T-1
    assert float(lm_loss_mean(logits, tokens)) == float(
        lm_loss_mean(spiked, tokens)
    )


def test_lm_eval_step_matches_train_objective():
    from multidisttorch_tpu.train.lm import make_lm_eval_step

    (g,) = setup_groups(1)
    _, ring = _models(g)
    tx = optax.adam(1e-3)
    state = create_lm_state(g, ring, tx, jax.random.key(0), example_len=32)
    tokens = jax.device_put(_tokens(), g.sharding(None, DATA_AXIS))
    ev = make_lm_eval_step(g, ring, sequence_parallel=True)
    out = ev(state, tokens)
    manual = float(
        lm_loss_mean(ring.apply({"params": state.params}, tokens), tokens)
    )
    np.testing.assert_allclose(float(out["loss"]), manual, rtol=1e-6)
    np.testing.assert_allclose(
        float(out["perplexity"]), np.exp(manual), rtol=1e-5
    )


def test_lm_per_block_remat_gradients_and_losses_match():
    # TransformerLM(remat=True): per-BLOCK nn.remat through the
    # ring-attention stack. Same params (remat changes no init), and
    # the precise equivalence is at the GRADIENT level (the backward
    # re-runs each block's forward, so reductions reassociate only at
    # ULP scale); post-Adam params are deliberately not compared —
    # Adam's rsqrt amplifies ULP gradient noise at near-eps moments.
    (g,) = setup_groups(1)
    _, plain = _models(g)
    remat = TransformerLM(
        remat=True,
        attention=make_ring_attention(g, causal=True),
        **_COMMON,
    )
    tokens = jax.device_put(_tokens(seed=2), g.sharding(None, DATA_AXIS))
    params = plain.init({"params": jax.random.key(0)}, _tokens(seed=2))[
        "params"
    ]
    # identical param structure: remat is purely a backward-schedule knob
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a.shape, b.shape),
        params,
        remat.init({"params": jax.random.key(0)}, _tokens(seed=2))["params"],
    )

    def grad_of(model):
        return jax.jit(
            jax.grad(
                lambda p: lm_loss_mean(
                    model.apply({"params": p}, tokens), tokens
                )
            )
        )(params)

    # atol floor sits at a few f32 ULPs of the typical grad magnitude:
    # XLA:CPU on the pinned jaxlib reassociates the recomputed-forward
    # reductions up to ~2 ulp (observed max 1.9e-8 on 0.4.36), which the
    # old 1e-8 floor flagged as a failure.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=5e-8
        ),
        jax.device_get(grad_of(plain)),
        jax.device_get(grad_of(remat)),
    )

    # And the training trajectory's losses agree tightly step for step.
    def run(model):
        tx = optax.adam(1e-3)
        state = create_lm_state(g, model, tx, jax.random.key(0),
                                example_len=32)
        step = make_lm_train_step(g, model, tx, sequence_parallel=True)
        losses = []
        for _ in range(3):
            state, m = step(state, tokens)
            losses.append(float(m["loss"]))
        return losses

    np.testing.assert_allclose(run(plain), run(remat), rtol=1e-5)


def _tp_losses(cfg, tokens_np, model_parallel, shard_asserts=None):
    """Train 3 deterministic steps of a dense-attention LM, replicated
    (``model_parallel=1``) or TP-sharded; shared by both TP parity
    tests so the harness can't drift between them."""
    from multidisttorch_tpu.models.transformer import transformer_tp_shardings
    from multidisttorch_tpu.train.steps import state_shardings

    sh = None
    if model_parallel == 1:
        (g,) = setup_groups(1)
    else:
        (g,) = setup_groups(1, model_parallel=model_parallel)
    model = TransformerLM(**cfg)
    tx = optax.adam(1e-3)
    if model_parallel == 1:
        state = create_lm_state(g, model, tx, jax.random.key(0),
                                example_len=16)
    else:
        state = create_lm_state(
            g, model, tx, jax.random.key(0), example_len=16,
            param_shardings=transformer_tp_shardings(g, model),
        )
        sh = state_shardings(state)
        if shard_asserts is not None:
            shard_asserts(state)
    step = make_lm_train_step(g, model, tx, shardings=sh)
    toks = jax.device_put(jnp.asarray(tokens_np), g.batch_sharding)
    out = []
    for _ in range(3):
        state, m = step(state, toks)
        out.append(float(m["loss"]))
    return out


def test_transformer_mlp_tp_matches_replicated():
    # Megatron MLP pair sharded over a (data x model) submesh: identical
    # training to the replicated LM (deterministic model — exact).
    # num_heads=2 doesn't divide the model axis, so auto keeps the
    # attention replicated and this covers the MLP-only configuration.
    tokens_np = np.asarray(_tokens(b=8, t=16, seed=5))

    def check(state):
        # MLP pair physically sharded: (32, 128) -> (32, 32) shards
        k = state.params["block_0"]["up"]["kernel"]
        assert k.addressable_shards[0].data.shape == (32, 128 // 4)

    np.testing.assert_allclose(
        _tp_losses(_COMMON, tokens_np, 1),
        _tp_losses(_COMMON, tokens_np, 4, check),
        rtol=2e-4,
    )


def test_transformer_attention_head_tp_matches_replicated():
    # Full Megatron decomposition: q/k/v column-parallel (the column
    # shard IS a head shard after the [head, head_dim] reshape), proj
    # row-parallel, plus the MLP pair — vs the replicated LM.
    tokens_np = np.asarray(_tokens(b=8, t=16, seed=6))
    cfg = dict(_COMMON, num_heads=4)  # heads divide the model axis

    def check(state):
        # auto mode sharded the attention: q columns = heads split
        k = state.params["block_0"]["q"]["kernel"]
        assert k.addressable_shards[0].data.shape == (32, 32 // 4)
        p = state.params["block_0"]["proj"]["kernel"]
        assert p.addressable_shards[0].data.shape == (32 // 4, 32)

    np.testing.assert_allclose(
        _tp_losses(cfg, tokens_np, 1),
        _tp_losses(cfg, tokens_np, 4, check),
        rtol=2e-4,
    )


def test_sp_x_tp_lm_matches_replicated():
    # The full 2-D composition on one (data=4 x model=2) trial mesh:
    # tokens sequence-sharded over the ring, heads + q/k/v/proj + MLP
    # pair sharded over the model axis. Three deterministic training
    # steps must match the fully-replicated dense-attention LM.
    from multidisttorch_tpu.models.transformer import transformer_tp_shardings
    from multidisttorch_tpu.train.steps import state_shardings

    cfg = dict(_COMMON, num_heads=4, max_len=16)
    tokens_np = np.asarray(_tokens(b=8, t=16, seed=9))  # b div 8 devices

    def replicated():
        (g,) = setup_groups(1)
        model = TransformerLM(**cfg)
        tx = optax.adam(1e-3)
        state = create_lm_state(g, model, tx, jax.random.key(0),
                                example_len=16)
        step = make_lm_train_step(g, model, tx)  # plain DP over batch
        toks = jax.device_put(jnp.asarray(tokens_np), g.batch_sharding)
        out = []
        for _ in range(3):
            state, m = step(state, toks)
            out.append(float(m["loss"]))
        return out

    def composed():
        (g,) = setup_groups(1, model_parallel=2)  # data 4 x model 2
        ring = make_ring_attention(g, causal=True)
        assert ring.head_sharded
        model = TransformerLM(attention=ring, **cfg)
        tx = optax.adam(1e-3)
        psh = transformer_tp_shardings(g, model)
        state = create_lm_state(
            g, model, tx, jax.random.key(0), example_len=16,
            param_shardings=psh,
        )
        step = make_lm_train_step(
            g, model, tx, sequence_parallel=True,
            shardings=state_shardings(state),
        )
        toks = jax.device_put(jnp.asarray(tokens_np),
                              g.sharding(None, DATA_AXIS))
        out = []
        for _ in range(3):
            state, m = step(state, toks)
            out.append(float(m["loss"]))
        return out

    np.testing.assert_allclose(replicated(), composed(), rtol=2e-4)


def test_tp_auto_follows_ring_head_sharding():
    # "auto" follows the attention callable: a head-sharded ring (2-D
    # mesh, shard_heads default) gets sharded q/k/v projections; a
    # replicated-head ring (shard_heads=False) keeps them replicated.
    # The MLP pair shards either way.
    from multidisttorch_tpu.models.transformer import transformer_tp_shardings
    from multidisttorch_tpu.parallel.mesh import MODEL_AXIS

    (g,) = setup_groups(1, model_parallel=4)
    cfg = dict(_COMMON, num_heads=4)

    sharded_ring = make_ring_attention(g, causal=True)
    assert sharded_ring.head_sharded
    sh = transformer_tp_shardings(g, TransformerLM(attention=sharded_ring,
                                                   **cfg))
    assert MODEL_AXIS in tuple(sh["block_0"]["q"]["kernel"].spec)

    flat_ring = make_ring_attention(g, causal=True, shard_heads=False)
    assert not flat_ring.head_sharded
    sh = transformer_tp_shardings(g, TransformerLM(attention=flat_ring,
                                                   **cfg))
    assert MODEL_AXIS not in tuple(sh["block_0"]["q"]["kernel"].spec)
    assert MODEL_AXIS in tuple(sh["block_0"]["up"]["kernel"].spec)

    # A plain flash callable signals head_sharded=False EXPLICITLY (its
    # single unsharded pallas_call can't be split by GSPMD), so "auto"
    # deliberately keeps the attention projections replicated while the
    # MLP still shards (ADVICE r4).
    from multidisttorch_tpu.ops.pallas_attention import make_flash_attention

    flash = make_flash_attention(causal=True)
    assert flash.head_sharded is False
    assert flash.carries_collectives is False  # stageable in a pipeline
    sh = transformer_tp_shardings(g, TransformerLM(attention=flash, **cfg))
    assert MODEL_AXIS not in tuple(sh["block_0"]["q"]["kernel"].spec)
    assert MODEL_AXIS in tuple(sh["block_0"]["up"]["kernel"].spec)


def test_lm_sampling_reproduces_learned_pattern():
    # Train on the deterministic periodic corpus, then greedy-decode
    # from a short prompt: the model must continue the pattern exactly
    # — the LM analog of the reference's prior-sample check.
    from multidisttorch_tpu.data import synthetic_corpus
    from multidisttorch_tpu.train.lm import make_lm_sample

    (g,) = setup_groups(1)
    corpus = synthetic_corpus(n=4096, vocab_size=16, period=16)
    model = TransformerLM(
        vocab_size=16, d_model=32, num_heads=2, num_layers=2, max_len=32
    )
    tx = optax.adam(5e-3)
    state = create_lm_state(g, model, tx, jax.random.key(0), example_len=32)
    step = make_lm_train_step(g, model, tx)
    rng = np.random.default_rng(0)
    for i in range(400):
        toks = jax.device_put(
            jnp.asarray(corpus.batch(rng, 8, 32)), g.batch_sharding
        )
        state, m = step(state, toks)
    # Loss floor is not zero for randomly-aligned windows: the first
    # block boundary's position is unknowable from a short prefix. The
    # continuation from a 20-token prompt IS deterministic (some
    # boundary has always been revealed by then), which is what the
    # decode assertions below check exactly.
    assert float(m["loss"]) < 0.3, float(m["loss"])

    sample = make_lm_sample(g, model)  # greedy
    window = corpus.batch(np.random.default_rng(99), 1, 32)
    prompt_len = 20
    buf = np.tile(window, (8, 1))  # B=8 identical prompts
    # positions >= prompt_len are GARBAGE: the decode must ignore them
    # (causality contract) and still reproduce the true continuation
    buf[:, prompt_len:] = np.random.default_rng(5).integers(
        0, 16, size=buf[:, prompt_len:].shape
    )
    buf = jnp.asarray(buf)
    out = np.asarray(sample(state, buf, prompt_len, jax.random.key(1)))
    # prompt preserved, continuation matches the true stream
    np.testing.assert_array_equal(
        out[:, :prompt_len], np.tile(window[:, :prompt_len], (8, 1))
    )
    np.testing.assert_array_equal(out, np.tile(window, (8, 1)))
    # temperature sampling runs and stays in-vocab
    hot = make_lm_sample(g, model, temperature=1.0)
    out_t = np.asarray(hot(state, buf, prompt_len, jax.random.key(2)))
    assert out_t.min() >= 0 and out_t.max() < 16
    # prompt_len=0 clamps to 1: position 0 is the seed, never garbage
    out0 = np.asarray(sample(state, buf, 0, jax.random.key(3)))
    np.testing.assert_array_equal(out0[:, 0], np.asarray(buf)[:, 0])
