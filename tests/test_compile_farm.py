"""The compile subsystem (multidisttorch_tpu/compile/): executable
registry coalescing, the background AOT precompile farm, the
quarantined persistent cache, and the driver's admission path.

The safety property under test everywhere: **no deserialized
executable ever executes in the trial process without a passed
canary** — a corrupt entry is quarantined by its sidecar, a failed
canary evicts the whole cache dir, and the process's jax config points
at the cache only on the one verdict (``enabled``) that requires a
passed canary. Scripted canary runners stand in for real broken
jaxlibs so every failure mode is drilled deterministically in-process
(the real subprocess protocol is exercised by the coldstart bench and
the CI canary job).
"""

import json
import os
import threading
import time
import zlib

import jax
import numpy as np
import pytest

from multidisttorch_tpu.compile import programs as cprog
from multidisttorch_tpu.compile.cache import (
    CANARY_CRASHED,
    CANARY_MISMATCH,
    QUARANTINE_DIR,
    SIDECAR_SUFFIX,
    cache_probe,
    canary_quarantine,
    enable_quarantined_cache,
    scan_cache,
    seal_cache,
)
from multidisttorch_tpu.compile.farm import PrecompilePool
from multidisttorch_tpu.compile.registry import (
    CLAIMED,
    COMPILING,
    FAILED,
    PENDING,
    READY,
    ExecutableRegistry,
    get_executable_registry,
)
from multidisttorch_tpu.hpo.driver import TrialConfig, stack_bucket_key
from multidisttorch_tpu.parallel.mesh import setup_groups


@pytest.fixture(autouse=True)
def _fresh_registry():
    # The registry is process-lifetime by design; tests must not leak
    # programs into (or depend on) each other's tables.
    get_executable_registry().reset()
    yield
    get_executable_registry().reset()


def _cfg(**kw):
    base = dict(
        trial_id=0, epochs=1, batch_size=16, lr=1e-3, seed=7,
        hidden_dim=16, latent_dim=4,
    )
    base.update(kw)
    return TrialConfig(**base)


# -- program vocabulary ----------------------------------------------


def test_single_keys_bake_hypers_but_init_does_not():
    g = setup_groups(1)[0]
    a, b = _cfg(lr=1e-3), _cfg(lr=2e-3)
    bucket = stack_bucket_key(a)
    assert stack_bucket_key(b) == bucket  # lr is not a shape
    # lr twins are DIFFERENT train programs (lr is an XLA constant)...
    assert cprog.single_train_key(g, a, bucket) != cprog.single_train_key(
        g, b, bucket
    )
    # ...but share ONE init program (init never reads the hypers).
    assert cprog.single_init_key(g, a, bucket) == cprog.single_init_key(
        g, b, bucket
    )
    for key in (
        cprog.single_train_key(g, a, bucket),
        cprog.single_init_key(g, a, bucket),
        cprog.stacked_train_key(g, bucket, 4),
    ):
        assert isinstance(cprog.program_label(key), str)


def test_mesh_fingerprint_distinguishes_groups():
    g0, g1 = setup_groups(2)[:2]
    cfg = _cfg()
    bucket = stack_bucket_key(cfg)
    # An executable is loaded onto concrete devices: bucket twins on
    # different submeshes must never share a registry slot.
    assert cprog.single_train_key(g0, cfg, bucket) != cprog.single_train_key(
        g1, cfg, bucket
    )
    # EXCEPT init: it is jitted with no device pinning (the driver
    # device_puts its output), so every group shares ONE compile —
    # N-group sweeps must not pay N bit-identical init lowerings.
    assert cprog.single_init_key(g0, cfg, bucket) == cprog.single_init_key(
        g1, cfg, bucket
    )
    assert cprog.program_label(
        cprog.single_init_key(g0, cfg, bucket)
    ).endswith("@shared")


def test_avals_match_guards_shape_drift():
    cfg = _cfg()
    avals = cprog.single_avals(cfg)
    state_aval = avals["train"][0]
    assert cprog.avals_match(state_aval, state_aval)
    other = cprog.single_avals(_cfg(hidden_dim=32))["train"][0]
    assert not cprog.avals_match(state_aval, other)
    assert not cprog.avals_match(state_aval, object())  # never raises


def test_registry_init_state_bit_identical_to_eager():
    import optax

    from multidisttorch_tpu.train.steps import build_train_state

    cfg = _cfg()
    model = cprog.default_model(cfg)
    eager = build_train_state(model, optax.adam(cfg.lr), jax.random.key(7))
    compiled = (
        cprog.build_init_fn(cfg, model)
        .lower(*cprog.init_avals())
        .compile()
    )(jax.random.key(7))
    for a, b in zip(jax.tree.leaves(eager), jax.tree.leaves(compiled)):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# -- registry: coalescing, ownership, torn shutdown -------------------


def test_compile_now_coalesces_duplicate_signatures():
    reg = ExecutableRegistry()
    key = ("train", ("k",), (1e-3, 1.0), (0,))
    n_compiles = [0]
    gate = threading.Event()

    def fn_factory():
        def body(x):
            return x + 1
        return jax.jit(body)

    fn = fn_factory()
    aval = (jax.ShapeDtypeStruct((4,), np.float32),)

    class SlowFn:
        def lower(self, *avals):
            n_compiles[0] += 1
            gate.wait(timeout=5)
            return fn.lower(*avals)

    results = []

    def worker():
        results.append(reg.compile_now(key, SlowFn(), aval))

    threads = [threading.Thread(target=worker) for _ in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    gate.set()
    for t in threads:
        t.join(timeout=10)
    # Exactly ONE thread lowered; the other two coalesced onto the
    # same entry and saw it READY.
    assert n_compiles[0] == 1
    assert all(e.status == READY for e in results)
    assert len({id(e) for e in results}) == 1
    # A later taker gets the executable and hit accounting.
    assert reg.take(key) is not None
    assert reg.entry(key).hits == 1


def test_registry_failed_is_terminal_and_sticky():
    reg = ExecutableRegistry()
    key = ("train", ("bad",), (1e-3, 1.0), (0,))

    class Broken:
        def lower(self, *a):
            raise RuntimeError("no lowering for you")

    e = reg.compile_now(key, Broken(), ())
    assert e.status == FAILED and "no lowering" in e.error
    assert reg.take(key) is None
    # A retry does NOT re-attempt a known-bad lowering.
    e2 = reg.compile_now(key, Broken(), ())
    assert e2 is e and e2.status == FAILED


def test_claim_vs_farm_ownership():
    reg = ExecutableRegistry()
    key = ("train", ("x",), (1e-3, 1.0), (0,))
    assert reg.schedule(key) is True
    assert reg.schedule(key) is False  # one farm job per program
    assert reg.status(key) == PENDING
    assert reg.claim(key) is True  # driver takes the queued job
    assert reg.status(key) == CLAIMED
    # The farm worker's check: CLAIMED is not PENDING, so it skips.
    assert reg.status(key) != PENDING


def test_pool_torn_shutdown_releases_queued_jobs():
    reg = ExecutableRegistry()
    pool = PrecompilePool(registry=reg, workers=1)
    release = threading.Event()
    started = threading.Event()

    def slow_builder():
        started.set()
        release.wait(timeout=10)
        return jax.jit(lambda x: x * 2), (
            jax.ShapeDtypeStruct((2,), np.float32),
        )

    k_inflight = ("train", ("a",), (1e-3, 1.0), (0,))
    k_queued = ("train", ("b",), (1e-3, 1.0), (0,))
    assert pool.submit(k_inflight, slow_builder)
    assert pool.submit(
        k_queued,
        lambda: (jax.jit(lambda x: x), (
            jax.ShapeDtypeStruct((2,), np.float32),
        )),
    )
    assert started.wait(timeout=10)
    pool.shutdown()  # torn: one in flight, one still queued
    # The queued job's PENDING entry is RELEASED — the next admission
    # claims and compiles it inline instead of waiting forever on a
    # worker that will never come.
    assert reg.status(k_queued) is None
    assert reg.claim(k_queued) is True
    # The in-flight compile finishes into the registry harmlessly.
    release.set()
    deadline = time.monotonic() + 10
    while reg.status(k_inflight) not in (READY, FAILED):
        assert time.monotonic() < deadline
        time.sleep(0.01)
    assert reg.status(k_inflight) == READY
    # Post-shutdown submits are refused AND leave no orphan PENDING
    # entry behind — one would stall a later admission on this key for
    # the full cooperative wait.
    k_late = ("train", ("c",), (1e-3, 1.0), (0,))
    assert not pool.submit(
        k_late, lambda: (jax.jit(lambda x: x), ()),
    )
    assert reg.status(k_late) is None


def test_pool_plan_sweep_dedups_duplicate_signatures():
    reg = ExecutableRegistry()
    pool = PrecompilePool(registry=reg, workers=1)
    g = setup_groups(1)
    # Four trials, ONE program signature (same bucket, same lr): the
    # farm must submit one train job + one init job, not four.
    items = [("single", [(i, _cfg(trial_id=i))]) for i in range(4)]
    n = pool.plan_sweep(items, g)
    assert n == 2  # init + train
    assert pool.drain(timeout_s=120)
    pool.shutdown(wait=True)
    cfg = _cfg()
    bucket = stack_bucket_key(cfg)
    assert reg.status(cprog.single_train_key(g[0], cfg, bucket)) == READY
    assert reg.status(cprog.single_init_key(g[0], cfg, bucket)) == READY


def test_admission_waits_cooperatively_never_blocks():
    # While a farm worker is mid-compile, the driver's admission
    # generator must YIELD (other submeshes keep stepping), not block —
    # and take the executable when the worker lands it.
    from multidisttorch_tpu.hpo.driver import _aot_admit

    reg = get_executable_registry()
    g = setup_groups(1)[0]
    cfg = _cfg()
    bucket = stack_bucket_key(cfg)
    key = cprog.single_train_key(g, cfg, bucket)
    avals = cprog.single_avals(cfg)
    steps = cprog.build_single_steps(g, cfg)

    release = threading.Event()

    class GatedFn:
        def lower(self, *a):
            release.wait(timeout=30)
            return steps["train"].lower(*a)

    worker = threading.Thread(
        target=lambda: reg.compile_now(key, GatedFn(), avals["train"])
    )
    worker.start()
    deadline = time.monotonic() + 10
    while reg.status(key) != COMPILING:
        assert time.monotonic() < deadline
        time.sleep(0.005)

    state_aval = avals["train"][0]
    gen = _aot_admit(
        {"train": key}, {"train": steps["train"], "multi": None},
        lambda: avals, state_aval, "train",
    )
    yields = 0
    taken = admission = None
    t0 = time.monotonic()
    while True:
        try:
            next(gen)
            yields += 1
            if yields == 3:
                release.set()  # the farm finishes while we cooperate
        except StopIteration as stop:
            taken, admission = stop.value
            break
        assert time.monotonic() - t0 < 30
    assert yields >= 3  # it yielded instead of blocking the host loop
    assert "train" in taken
    assert admission["outcome"] == "wait"
    worker.join(timeout=10)


def test_admission_claims_pending_job_inline():
    from multidisttorch_tpu.hpo.driver import _aot_admit

    reg = get_executable_registry()
    pool = PrecompilePool(registry=reg, workers=1)
    g = setup_groups(1)[0]
    cfg = _cfg(hidden_dim=32)
    bucket = stack_bucket_key(cfg)
    key = cprog.single_train_key(g, cfg, bucket)
    avals = cprog.single_avals(cfg)
    steps = cprog.build_single_steps(g, cfg)
    # A torn farm left the building: entry released, program unknown.
    assert reg.schedule(key)
    pool.shutdown()
    reg.release(key)
    gen = _aot_admit(
        {"train": key}, {"train": steps["train"], "multi": None},
        lambda: avals, avals["train"][0], "train",
    )
    while True:
        try:
            next(gen)
        except StopIteration as stop:
            taken, admission = stop.value
            break
    assert admission["outcome"] == "inline"
    assert "train" in taken
    assert reg.status(key) == READY


# -- sidecars + scan --------------------------------------------------


def _plant_entry(cache_dir, name, blob=b"x" * 64):
    os.makedirs(cache_dir, exist_ok=True)
    with open(os.path.join(cache_dir, name), "wb") as f:
        f.write(blob)


def test_scan_rejects_corrupt_truncated_and_unsealed(tmp_path):
    d = str(tmp_path / "cache")
    _plant_entry(d, "good", b"a" * 100)
    _plant_entry(d, "bitrot", b"b" * 100)
    _plant_entry(d, "torn", b"c" * 100)
    seal_cache(d)
    # bit rot: same length, different bytes -> crc_mismatch
    _plant_entry(d, "bitrot", b"B" + b"b" * 99)
    # torn write: truncated after sealing -> size_mismatch
    _plant_entry(d, "torn", b"c" * 10)
    # unknown provenance: never sealed -> unsealed
    _plant_entry(d, "stranger", b"s" * 20)
    report = scan_cache(d)
    reasons = {r["entry"]: r["reason"] for r in report["rejected"]}
    assert reasons == {
        "bitrot": "crc_mismatch",
        "torn": "size_mismatch",
        "stranger": "unsealed",
    }
    assert report["ok"] == 1 and report["quarantined"] == 3
    # Rejected entries MOVED aside: jax sees a miss, never a garbled
    # blob; the good entry stays.
    left = sorted(
        n for n in os.listdir(d)
        if not n.endswith(SIDECAR_SUFFIX) and n != QUARANTINE_DIR
    )
    assert left == ["good"]
    qdir = os.path.join(d, QUARANTINE_DIR)
    assert sorted(
        n for n in os.listdir(qdir) if not n.endswith(SIDECAR_SUFFIX)
    ) == ["bitrot", "stranger", "torn"]


def test_scan_classifies_malformed_but_parseable_sidecars(tmp_path):
    # Bit rot can produce a sidecar that parses as VALID JSON of the
    # wrong shape ([], 0, {"nbytes": null}) — the scanner must
    # classify it sidecar_unreadable and quarantine, never crash: it
    # runs inside the corruption-containment path itself.
    d = str(tmp_path / "cache")
    for name, side in (
        ("e_list", "[]"),
        ("e_zero", "0"),
        ("e_null", '{"crc32": 1, "nbytes": null}'),
        ("e_str", '{"crc32": "xx", "nbytes": 2}'),
    ):
        _plant_entry(d, name, b"xy")
        with open(os.path.join(d, name + SIDECAR_SUFFIX), "w") as f:
            f.write(side)
    report = scan_cache(d)
    assert report["ok"] == 0
    assert {r["reason"] for r in report["rejected"]} == {
        "sidecar_unreadable"
    }
    assert report["quarantined"] == 4


def test_seal_is_idempotent_and_refreshes(tmp_path):
    d = str(tmp_path / "cache")
    _plant_entry(d, "e1", b"v1")
    r1 = seal_cache(d)
    assert r1["sealed"] == 1
    assert seal_cache(d)["sealed"] == 0  # unchanged -> no churn
    _plant_entry(d, "e1", b"v2")  # legit rewrite by a writer
    r3 = seal_cache(d)
    assert r3["refreshed"] == 1
    assert scan_cache(d)["ok"] == 1


# -- the canary quarantine -------------------------------------------


def _scripted_runner(script):
    """A canary-child stand-in: script maps mode -> result dict."""
    calls = []

    def run(mode, cache_dir, platform, timeout_s):
        calls.append(mode)
        out = script[mode]
        return dict(out() if callable(out) else out)

    run.calls = calls
    return run


def test_canary_mismatch_evicts_and_leaves_cold_path(tmp_path):
    d = str(tmp_path / "cache")
    _plant_entry(d, "entry", b"deadbeef" * 8)
    seal_cache(d)
    runner = _scripted_runner({
        "cold": {"ok": True, "bits": "aa"},
        "warmup": {"ok": True, "bits": "aa"},
        "warm": {"ok": True, "bits": "bb"},  # deserialize drifted
    })
    out = canary_quarantine(d, runner=runner)
    assert out["verdict"] == CANARY_MISMATCH and not out["passed"]
    assert out["evicted"] >= 1
    # Every entry quarantined: nothing left for jax to load — the next
    # compile is COLD, which is the fallback the protocol promises.
    assert [
        n for n in os.listdir(d)
        if not n.endswith(SIDECAR_SUFFIX) and n != QUARANTINE_DIR
    ] == []


def test_heap_corrupting_entry_never_loads_in_trial_process(tmp_path):
    # THE acceptance property (ISSUE 7): plant a stand-in for a
    # heap-corrupting executable — an entry whose sidecar is VALID (the
    # scan alone cannot catch it: PR 1's corruption was bit-exact on
    # disk) and whose deserialize-and-run CRASHES the canary child. The
    # trial process must end with its jax config NOT pointing at the
    # cache, the entries evicted, and a classified verdict — the
    # corrupt executable never gets a chance to execute here.
    d = str(tmp_path / "cache")
    _plant_entry(d, "heapbomb", b"\x7fELF-corrupting-thunks" * 4)
    seal_cache(d)
    assert scan_cache(d, quarantine=False)["ok"] == 1  # scan trusts it

    runner = _scripted_runner({
        "cold": {"ok": True, "bits": "aa"},
        "warmup": {"ok": True, "bits": "aa"},
        "warm": {  # the sacrificial child dies the PR 1 death
            "ok": False, "timeout": False, "rc": -11,
            "error": "canary warm child died rc=-11 "
                     "(deserialized-executable crash class)",
        },
    })
    prev = jax.config.jax_compilation_cache_dir
    out = enable_quarantined_cache(d, platform="cpu", runner=runner)
    assert out["enabled"] is False
    assert out["verdict"] == CANARY_CRASHED
    assert jax.config.jax_compilation_cache_dir == prev  # untouched
    assert out["canary"]["evicted"] >= 1
    qdir = os.path.join(d, QUARANTINE_DIR)
    assert "heapbomb" in os.listdir(qdir)


def test_passed_canary_on_cpu_stays_quarantined_only(tmp_path, monkeypatch):
    # XLA:CPU policy: even a PASSED canary licenses only sacrificial
    # processes — the known corruption class fails late, so the trial
    # process keeps cold-compiling.
    monkeypatch.delenv("MDT_CACHE_SACRIFICIAL", raising=False)
    monkeypatch.delenv("MDT_FORCE_COMPILE_CACHE", raising=False)
    d = str(tmp_path / "cache")
    ok_runner = _scripted_runner({
        "cold": {"ok": True, "bits": "aa"},
        "warmup": {"ok": True, "bits": "aa"},
        "warm": {"ok": True, "bits": "aa"},
    })
    prev = jax.config.jax_compilation_cache_dir
    out = enable_quarantined_cache(d, platform="cpu", runner=ok_runner)
    assert out["verdict"] == "quarantined_only" and not out["enabled"]
    assert jax.config.jax_compilation_cache_dir == prev


def test_passed_canary_enables_for_tpu_and_sacrificial(tmp_path):
    ok_runner = _scripted_runner({
        "cold": {"ok": True, "bits": "aa"},
        "warmup": {"ok": True, "bits": "aa"},
        "warm": {"ok": True, "bits": "aa"},
    })
    prev = jax.config.jax_compilation_cache_dir
    try:
        d = str(tmp_path / "tpu_cache")
        out = enable_quarantined_cache(d, platform="tpu", runner=ok_runner)
        assert out["enabled"] and out["verdict"] == "enabled"
        assert jax.config.jax_compilation_cache_dir == d
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    try:
        d2 = str(tmp_path / "sac_cache")
        out = enable_quarantined_cache(
            d2, platform="cpu", runner=ok_runner, sacrificial=True
        )
        assert out["enabled"] and out["verdict"] == "enabled"
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_cache_probe_reports_scan_and_canary(tmp_path):
    d = str(tmp_path / "cache")
    _plant_entry(d, "sealed_ok", b"fine")
    seal_cache(d)
    _plant_entry(d, "stranger", b"who")
    out = cache_probe(d, runner=_scripted_runner({
        "cold": {"ok": True, "bits": "aa"},
        "warmup": {"ok": True, "bits": "aa"},
        "warm": {"ok": True, "bits": "aa"},
    }))
    # The probe REPORTS the unsealed stranger without quarantining it
    # (read-only contract: mutation belongs to the enable path).
    assert out["scan"]["quarantined"] == 0
    assert [r["reason"] for r in out["scan"]["rejected"]] == ["unsealed"]
    assert "stranger" in os.listdir(d)
    assert out["canary"]["passed"] and out["usable"]
    # And it did not vouch for the stranger: still no sidecar.
    assert not os.path.exists(
        os.path.join(d, "stranger" + SIDECAR_SUFFIX)
    )


def test_cache_probe_failure_is_nondestructive(tmp_path):
    # A transient canary failure (e.g. a loaded host timing out the
    # child) during a PROBE must not throw away the production cache:
    # entries stay in place, nothing is evicted.
    d = str(tmp_path / "cache")
    _plant_entry(d, "precious", b"hours of TPU compiles")
    seal_cache(d)
    out = cache_probe(d, runner=_scripted_runner({
        "cold": {"ok": True, "bits": "aa"},
        "warmup": {"ok": True, "bits": "aa"},
        "warm": {
            "ok": False, "timeout": True,
            "error": "canary warm child blocked past 120s",
        },
    }))
    assert not out["usable"]
    assert out["canary"]["verdict"] == "canary_timeout"
    assert out["canary"]["evicted"] == 0
    assert "precious" in os.listdir(d)
    assert not os.path.isdir(os.path.join(d, QUARANTINE_DIR)) or (
        os.listdir(os.path.join(d, QUARANTINE_DIR)) == []
    )


def test_canary_child_env_never_inherits_cache_dir(monkeypatch):
    # The cold reference child must compile with NO cache — an
    # inherited JAX_COMPILATION_CACHE_DIR would make it deserialize
    # the same suspect entry as the warm child and bit-match it.
    import subprocess as _sp

    from multidisttorch_tpu.compile.cache import _run_canary_child

    captured = {}

    class _P:
        returncode = 0
        stdout = "CANARYBITS|00\n"
        stderr = ""

    def fake_run(cmd, **kw):
        captured["env"] = kw["env"]
        return _P()

    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/tmp/suspect")
    monkeypatch.setenv("MDT_FORCE_COMPILE_CACHE", "1")
    monkeypatch.setattr(_sp, "run", fake_run)
    for mode in ("cold", "warmup", "warm"):
        r = _run_canary_child(mode, "/tmp/x", None, 5.0)
        assert r["ok"]
        assert "JAX_COMPILATION_CACHE_DIR" not in captured["env"]
        assert "MDT_FORCE_COMPILE_CACHE" not in captured["env"]


def test_registry_lru_bound_evicts_terminal_only():
    # The service-lifetime memory bound: terminal entries beyond
    # max_programs are dropped LRU-first; in-flight ownership states
    # always survive. An evicted program just recompiles next time.
    reg = ExecutableRegistry(max_programs=2)
    fn = jax.jit(lambda x: x + 1)
    aval = (jax.ShapeDtypeStruct((2,), np.float32),)

    def k(i):
        return ("train", (f"p{i}",), (1e-3 * (i + 1), 1.0), (0,))

    assert reg.compile_now(k(0), fn, aval).status == READY
    assert reg.compile_now(k(1), fn, aval).status == READY
    reg.take(k(0))  # k0 is now more recently used than k1
    assert reg.compile_now(k(2), fn, aval).status == READY
    # k1 (LRU terminal) was evicted to admit k2; k0 survived.
    assert reg.status(k(1)) is None
    assert reg.status(k(0)) == READY and reg.status(k(2)) == READY
    assert reg.evicted == 1
    # A PENDING farm job is never evicted, even under cap pressure.
    assert reg.schedule(k(3))
    assert reg.compile_now(k(4), fn, aval).status == READY
    assert reg.status(k(3)) == PENDING


# -- end-to-end: the farm under run_hpo + cold-start books ------------


@pytest.mark.slow
def test_precompiled_sweep_never_blocks_and_matches_jit(tmp_path):
    # The tentpole contract end-to-end on a real sweep: with the farm
    # on, every trial's program arrives by registry hit or cooperative
    # wait (never an inline/jit compile on the host loop), the books
    # record it, and results are bit-identical to the plain-jit sweep.
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.driver import run_hpo
    from multidisttorch_tpu.telemetry.events import EVENTS_NAME, read_events
    from multidisttorch_tpu.telemetry.export import SweepFold

    train, test = synthetic_mnist(256), synthetic_mnist(64)
    cfgs = [
        _cfg(trial_id=i, hidden_dim=16 + 8 * i, epochs=1)
        for i in range(3)
    ]
    tel = str(tmp_path / "tel")
    telemetry.configure(tel)
    try:
        r_farm = run_hpo(
            cfgs, train, test, num_groups=1,
            out_dir=str(tmp_path / "farm"), save_images=False,
            verbose=False, precompile=True,
        )
    finally:
        telemetry.disable()
    fold = SweepFold()
    for ev in read_events(os.path.join(tel, EVENTS_NAME)):
        fold.feed(ev)
    assert len(fold.admissions) == 3
    for a in fold.admissions:
        assert a["outcome"] in ("hit", "wait"), a
        assert a["admission_s"] is not None
    assert fold.precompile.get("plan") == 1
    assert fold.compiles >= 3 and fold.compile_s_total > 0
    # Parity: farm-admitted executables are the driver's programs.
    get_executable_registry().reset()
    os.environ["MDT_AOT_ADMISSION"] = "0"
    try:
        r_jit = run_hpo(
            cfgs, train, test, num_groups=1,
            out_dir=str(tmp_path / "jit"), save_images=False,
            verbose=False,
        )
    finally:
        del os.environ["MDT_AOT_ADMISSION"]
    for a, b in zip(r_farm, r_jit):
        assert float(a.final_train_loss).hex() == float(
            b.final_train_loss
        ).hex()
        assert float(a.final_test_loss).hex() == float(
            b.final_test_loss
        ).hex()


def test_sweepfold_compile_books_fold():
    from multidisttorch_tpu.telemetry.export import SweepFold

    fold = SweepFold()
    mk = lambda kind, **data: {  # noqa: E731
        "kind": kind, "ts": data.pop("ts", 1.0), "data": data,
        "trial_id": data.pop("trial_id", None),
    }
    fold.feed(mk("compile_end", program="p1", program_kind="train",
                 source="precompile", compile_s=1.5, ok=True))
    fold.feed(mk("compile_end", program="p2", program_kind="init",
                 source="inline", compile_s=0.5, ok=False, error="boom"))
    fold.feed(mk("cache_hit", program="p1", source="precompile"))
    fold.feed(mk("precompile_scheduled", program="p1"))
    ev_start = {"kind": "attempt_start", "ts": 10.0, "trial_id": 3,
                "attempt": 1, "data": {}}
    ev_disp = {"kind": "first_dispatch", "ts": 12.5, "trial_id": 3,
               "data": {"outcome": "hit", "wait_s": 0.0, "program": "p1"}}
    fold.feed(ev_start)
    fold.feed(ev_disp)
    assert fold.compile_books["p1"]["compile_s"] == 1.5
    assert fold.compile_books["p1"]["hits"] == 1
    assert fold.compile_books["p2"]["ok"] is False
    assert fold.compiles == 2 and fold.cache_hits == 1
    assert fold.precompile == {"scheduled": 1}
    (adm,) = fold.admissions
    assert adm["trial_id"] == 3 and adm["outcome"] == "hit"
    assert adm["admission_s"] == 2.5
    assert fold.trials[3]["compile_outcome"] == "hit"


def test_crc_sidecar_format_is_plain_json(tmp_path):
    # The sidecar is the checkpoint layer's pattern: inspectable JSON,
    # not a pickle — a corrupted sidecar must never execute anything.
    d = str(tmp_path / "cache")
    _plant_entry(d, "e", b"payload")
    seal_cache(d)
    with open(os.path.join(d, "e" + SIDECAR_SUFFIX)) as f:
        rec = json.load(f)
    assert rec == {"crc32": zlib.crc32(b"payload"), "nbytes": 7}
