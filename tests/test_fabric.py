"""Service-fabric invariants (ISSUE 13, docs/SERVICE.md "Service
fabric"): shard routing, lease-fenced ownership, stale-replica write
rejection, torn-journal adoption replay, EDF ordering, the anti-thrash
preemption budget, the submit fsync discipline, the daemon_lost chaos
kind, and the discrete-event loadgen's contracts."""

from __future__ import annotations

import json
import os
import signal
import time

import numpy as np
import pytest

from multidisttorch_tpu.service import fabric, queue as squeue
from multidisttorch_tpu.service.scheduler import (
    FairShareScheduler,
    PendingTrial,
    PreemptionPolicy,
    SlicePool,
    TenantPolicy,
)

pytestmark = pytest.mark.fabric


# -- shard routing ----------------------------------------------------


def test_shard_of_stable_and_in_range():
    for n in (1, 2, 3, 8):
        for t in ("alpha", "beta", "x", "a-very-long-tenant-name"):
            k = fabric.shard_of(t, n)
            assert 0 <= k < n
            assert k == fabric.shard_of(t, n)  # deterministic
    with pytest.raises(ValueError):
        fabric.shard_of("t", 0)


def test_fabric_config_first_writer_pins(tmp_path):
    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 4)
    assert fabric.read_fabric_config(d) == {"n_shards": 4}
    fabric.ensure_fabric_config(d, 4)  # idempotent
    with pytest.raises(ValueError):
        fabric.ensure_fabric_config(d, 2)  # disagreeing routing


# -- leases + fencing -------------------------------------------------


def test_claim_renew_steal_fence(tmp_path):
    d = str(tmp_path)
    f0 = fabric.try_claim(d, 0, replica=0)
    assert f0 is not None and f0.epoch == 1
    f0.renew()
    assert f0.holds(force=True)
    # Replica 1 takes over at a higher epoch: the old fence is dead.
    f1 = fabric.try_claim(d, 0, replica=1)
    assert f1 is not None and f1.epoch == 2
    assert not f0.holds(force=True)
    with pytest.raises(fabric.FenceLost):
        f0.check()
    with pytest.raises(fabric.FenceLost):
        f0.renew()
    # The winner is unaffected.
    f1.renew()
    assert f1.holds(force=True)


def test_claim_race_first_append_wins(tmp_path, monkeypatch):
    d = str(tmp_path)
    path = fabric.lease_file(d, 3)
    # Replica 5's claim at epoch 1 lands FIRST; replica 6 raced the
    # same epoch (its max-epoch read happened before 5's append).
    fabric._append_lease(
        path,
        {"shard": 3, "replica": 5, "epoch": 1, "status": fabric.CLAIM,
         "ts": time.time()},
    )
    monkeypatch.setattr(fabric, "_max_epoch_tail", lambda p: 0)
    assert fabric.try_claim(d, 3, replica=6) is None
    monkeypatch.undo()
    # And 5's fence, constructed from its own winning claim, holds.
    f5 = fabric.ShardFence(shard=3, replica=5, epoch=1, path=path)
    assert f5.holds(force=True)


def test_shard_orphaned_verdicts(tmp_path):
    d = str(tmp_path)
    assert fabric.shard_orphaned(d, 0, lease_deadline_s=1.0)  # unclaimed
    f = fabric.try_claim(d, 0, replica=0)
    assert not fabric.shard_orphaned(d, 0, lease_deadline_s=5.0)
    # Stale: no renewal past the deadline.
    assert fabric.shard_orphaned(
        d, 0, lease_deadline_s=0.5, now=time.time() + 2.0
    )
    # Released: immediately claimable.
    f.release()
    assert fabric.shard_orphaned(d, 0, lease_deadline_s=5.0)


def test_fenced_queue_rejects_stale_appends(tmp_path):
    d = str(tmp_path)
    fence = fabric.try_claim(d, 0, replica=0)
    sd = fabric.shard_dir(d, 0)
    q = squeue.SubmissionQueue(sd, fence=fence.check)
    q.append({"event": "submitted", "sub": {"submission_id": "s1"}})
    n_before = len(squeue.load_queue(sd))
    # Takeover: every further append by the stale writer must raise
    # BEFORE touching the journal. (The fence's holds() verdict is
    # cached for check_interval_s — wait it out, as a real replica's
    # next append would.)
    assert fabric.try_claim(d, 0, replica=1) is not None
    time.sleep(fence.check_interval_s + 0.02)
    with pytest.raises(fabric.FenceLost):
        q.append({"event": "settled", "submission_id": "s1"})
    assert len(squeue.load_queue(sd)) == n_before


# -- EDF --------------------------------------------------------------


def _entry(tenant, i, *, deadline_ts=None, size=1, bucket="b"):
    return PendingTrial(
        sub_id=f"{tenant}-{i}",
        tenant=tenant,
        priority=1,
        cfg=None,
        bucket=bucket,
        size=size,
        cost=1.0,
        submit_ts=float(i),
        trial_id=i,
        deadline_ts=deadline_ts,
    )


def test_edf_never_inverts_same_tenant_deadlines():
    rng = np.random.default_rng(0)
    for trial in range(5):
        sched = FairShareScheduler({"t": TenantPolicy()})
        pool = SlicePool(1)
        n = 40
        deadlines = {}
        for i in range(n):
            dl = (
                float(rng.uniform(0, 1000))
                if rng.random() < 0.6
                else None
            )
            deadlines[i] = dl
            sched.push(_entry("t", i, deadline_ts=dl), now=float(i))
        order = []
        while sched.pending_count():
            placed = sched.schedule(pool, max_lanes=1, now=0.0)
            assert len(placed) == 1
            e = placed[0].members[0]
            order.append(e.trial_id)
            pool.free(placed[0].start, placed[0].size)
        # Every deadline-tagged entry precedes every best-effort one,
        # deadlines place in ascending order, best-effort stays FIFO.
        tagged = [i for i in order if deadlines[i] is not None]
        untagged = [i for i in order if deadlines[i] is None]
        assert order == tagged + untagged
        ds = [deadlines[i] for i in tagged]
        assert ds == sorted(ds)
        assert untagged == sorted(untagged)


def test_edf_never_jumps_a_front_pushed_entry():
    """A defrag victim (or recovered trial) pushed front=True keeps
    its head-of-queue position: a later deadline-tagged push may sort
    within the tail but never ahead of the barrier — the pinned
    victim must reclaim its relocation target first."""
    sched = FairShareScheduler({"t": TenantPolicy()})
    pool = SlicePool(1)
    victim = _entry("t", 0)  # best-effort, e.g. a migrated victim
    victim.pinned_start = 0
    sched.push(victim, front=True, now=0.0)
    sched.push(_entry("t", 1, deadline_ts=1.0), now=0.0)  # tight
    order = []
    while sched.pending_count():
        (p,) = sched.schedule(pool, max_lanes=1, now=0.0)
        order.append(p.members[0].trial_id)
        pool.free(p.start, p.size)
    assert order == [0, 1]


def test_edf_late_arrival_jumps_queue_but_fifo_stays():
    sched = FairShareScheduler({"t": TenantPolicy()})
    pool = SlicePool(1)
    sched.push(_entry("t", 0, deadline_ts=100.0), now=0.0)
    sched.push(_entry("t", 1), now=0.0)  # best-effort
    sched.push(_entry("t", 2, deadline_ts=50.0), now=0.0)  # later, tighter
    order = []
    while sched.pending_count():
        (p,) = sched.schedule(pool, max_lanes=1, now=0.0)
        order.append(p.members[0].trial_id)
        pool.free(p.start, p.size)
    assert order == [2, 0, 1]


# -- anti-thrash budget ----------------------------------------------


def test_preemption_policy_budget_and_cooldowns():
    pol = PreemptionPolicy(
        max_preemptions_per_trial=2,
        trial_cooldown_s=10.0,
        global_cooldown_s=5.0,
    )
    assert pol.event_allowed(0.0)
    assert pol.victim_allowed(1, 0, 0.0)
    pol.note_eviction(1, 0.0)
    # Trial cooldown: not evictable again until 10s pass.
    assert not pol.victim_allowed(1, 1, 5.0)
    assert pol.victim_allowed(1, 1, 10.0)
    # Per-trial cap: at the cap, immune forever.
    pol.note_eviction(1, 10.0)
    assert not pol.victim_allowed(1, 2, 1e9)
    # Global event cooldown.
    assert not pol.event_allowed(12.0)
    assert pol.event_allowed(15.0)
    # Disabled policy never evicts.
    off = PreemptionPolicy(enabled=False)
    assert not off.victim_allowed(9, 0, 0.0)
    assert not off.event_allowed(0.0)
    # Settled-trial bookkeeping is dropped (bounded RSS).
    pol.forget(1)
    assert 1 not in pol.last_evict


# -- the durability satellite ----------------------------------------


def test_submit_fsync_call_sequence(tmp_path, monkeypatch):
    """The commit discipline: spool-file fsync BEFORE the rename,
    directory fsync AFTER it — on ext4-ordered a missing dir fsync can
    vanish the commit point (the rename) on crash."""
    ops = []
    real_fsync, real_replace = os.fsync, os.replace

    def rec_fsync(fd):
        ops.append(("fsync", fd))
        return real_fsync(fd)

    def rec_replace(src, dst):
        ops.append(("replace", dst))
        return real_replace(src, dst)

    monkeypatch.setattr(os, "fsync", rec_fsync)
    monkeypatch.setattr(os, "replace", rec_replace)
    client = squeue.SweepClient(str(tmp_path), tenant="t")
    sid = client.submit({"epochs": 1})
    replaces = [i for i, (k, _) in enumerate(ops) if k == "replace"]
    assert len(replaces) == 1, ops
    r = replaces[0]
    # At least one fsync strictly before the rename (the payload) and
    # at least one strictly after it (the directory).
    assert any(k == "fsync" for k, _ in ops[:r]), ops
    assert any(k == "fsync" for k, _ in ops[r + 1:]), ops
    assert ops[-1][0] == "fsync", ops  # the dir fsync IS the last op
    assert os.path.exists(
        os.path.join(squeue.intake_dir(str(tmp_path)), sid + ".json")
    )


def test_journal_first_append_fsyncs_dir(tmp_path, monkeypatch):
    calls = []
    monkeypatch.setattr(
        squeue, "fsync_dir", lambda p: calls.append(p)
    )
    q = squeue.SubmissionQueue(str(tmp_path))
    q.append({"event": "submitted", "sub": {"submission_id": "a"}})
    assert calls == [str(tmp_path)]  # creation made the entry durable
    q.append({"event": "settled", "submission_id": "a"})
    assert calls == [str(tmp_path)]  # later appends: file fsync only


# -- daemon_lost ------------------------------------------------------


def test_daemon_lost_spec_validation():
    from multidisttorch_tpu.faults.plan import (
        DAEMON_LOST,
        HOST_KINDS,
        FaultPlan,
        FaultSpec,
    )

    assert DAEMON_LOST in HOST_KINDS
    spec = FaultSpec(DAEMON_LOST, trial_id=-1, step=5, host=1)
    plan = FaultPlan(specs=(spec,), seed=3)
    assert FaultPlan.from_json(plan.to_json()) == plan
    with pytest.raises(ValueError):
        FaultSpec(DAEMON_LOST, trial_id=-1, step=5)  # host required
    with pytest.raises(ValueError):
        FaultSpec(DAEMON_LOST, trial_id=-1, host=1)  # step required


def test_daemon_lost_fires_sigkill_on_dispatch_clock(
    tmp_path, monkeypatch
):
    from multidisttorch_tpu.faults.inject import FaultInjector
    from multidisttorch_tpu.faults.plan import (
        DAEMON_LOST,
        FaultPlan,
        FaultSpec,
    )

    kills = []
    monkeypatch.setattr(
        os, "kill", lambda pid, sig: kills.append((pid, sig))
    )
    log = str(tmp_path / "fired.jsonl")
    plan = FaultPlan(
        specs=(FaultSpec(DAEMON_LOST, trial_id=-1, step=10, host=1),)
    )
    # Wrong replica: never fires.
    other = FaultInjector(plan, host_slot=0)
    other.host_step(100)
    assert kills == []
    inj = FaultInjector(plan, host_slot=1, fired_log=log)
    inj.host_step(5)
    assert kills == []
    inj.host_step(6)  # window [5, 11) covers dispatch index 10
    assert kills == [(os.getpid(), signal.SIGKILL)]
    with open(log) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    assert recs and recs[0]["kind"] == DAEMON_LOST
    # One-shot across restarts: a new injector replaying the fired log
    # does not fire again.
    kills.clear()
    inj2 = FaultInjector(plan, host_slot=1, fired_log=log)
    inj2.host_step(100)
    assert kills == []


# -- adoption replay --------------------------------------------------


def _journal_line(rec):
    return json.dumps({**rec, "ts": time.time()}) + "\n"


def _write_orphan_shard(sd):
    """A dead replica's shard journal: C settled, A placed (work
    orphaned mid-flight), B submitted-not-admitted, plus a TORN tail
    (the crash landed mid-append)."""
    os.makedirs(sd, exist_ok=True)
    cfg = {"epochs": 1, "batch_size": 32, "latent_dim": 4,
           "hidden_dim": 16, "log_interval": 1000}
    with open(squeue.queue_path(sd), "w") as f:
        for sid, tid in (("beta-C", 0), ("beta-A", 1)):
            f.write(_journal_line({
                "event": "submitted",
                "sub": {"submission_id": sid, "tenant": "beta",
                        "config": {**cfg, "seed": tid},
                        "priority": 1, "size": 1,
                        "submit_ts": time.time()},
            }))
            f.write(_journal_line({
                "event": "admitted", "submission_id": sid,
                "trial_id": tid, "config_hash": f"h{tid}",
                "bucket": "b",
            }))
            f.write(_journal_line({
                "event": "placed", "submission_id": sid,
                "trial_id": tid, "start": 0, "size": 1, "lanes": 1,
                "stacked": False, "resumed": False,
            }))
        f.write(_journal_line({
            "event": "settled", "submission_id": "beta-C",
            "trial_id": 0, "status": "completed", "error": "",
        }))
        f.write(_journal_line({
            "event": "submitted",
            "sub": {"submission_id": "beta-B", "tenant": "beta",
                    "config": {**cfg, "seed": 9}, "priority": 1,
                    "size": 1, "submit_ts": time.time()},
        }))
        f.write('{"event": "settled", "submission_id": "beta-A", "st')


def test_adoption_replays_torn_journal_no_dup_no_drop(tmp_path):
    """The adopter's journal replay: the torn final transition costs
    only itself — every submission id survives exactly once, settled
    stays settled, ever-placed re-enters resume_scan, and the pending
    one re-admits WITHOUT colliding trial ids."""
    from multidisttorch_tpu.service.runtime import SweepService

    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 1)
    sd = fabric.shard_dir(d, 0)
    _write_orphan_shard(sd)
    fence = fabric.try_claim(d, 0, replica=0)
    svc = SweepService(
        sd, fence=fence.check, n_slices=2, max_lanes=2, data_rows=64
    )
    try:
        # C stays settled; A and B are live again.
        assert svc.settled == {"beta-C": "completed"}
        by_sub = {e.sub_id: e for e in svc.entries.values()}
        assert set(by_sub) == {"beta-A", "beta-B"}
        # A was placed when the owner died: it must re-place from its
        # checkpoints, and its interrupted placement is journaled.
        assert by_sub["beta-A"].resume_scan
        assert not by_sub["beta-B"].resume_scan
        folded = squeue.fold_queue(squeue.load_queue(sd))
        assert folded["beta-A"]["state"] == squeue.ADMITTED
        assert folded["beta-A"]["unplaced_reason"] == (
            "daemon restart recovery"
        )
        # No id collision: B's fresh trial id is above A's journaled 1.
        assert by_sub["beta-B"].trial_id >= 2
        # No duplicates anywhere.
        ids = [e.trial_id for e in svc.entries.values()]
        assert len(ids) == len(set(ids))
    finally:
        svc.store.shutdown()


def test_stale_replica_tick_rejected_after_takeover(tmp_path):
    """The paused-and-resumed replica: its service raises FenceLost at
    the next tick (before any journal write) once another replica
    claimed the shard."""
    from multidisttorch_tpu.service.runtime import SweepService

    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 1)
    sd = fabric.shard_dir(d, 0)
    os.makedirs(sd, exist_ok=True)
    fence = fabric.try_claim(d, 0, replica=0)
    svc = SweepService(
        sd, fence=fence.check, n_slices=2, max_lanes=2, data_rows=64
    )
    try:
        client = squeue.SweepClient(sd, tenant="t")
        client.submit({"epochs": 1, "batch_size": 32, "latent_dim": 4,
                       "log_interval": 1000})
        svc.tick()
        assert svc.sched.pending_count() + len(svc.active) >= 1
        n_before = len(squeue.load_queue(sd))
        # Replica 1 takes the shard (the pause happened here).
        assert fabric.try_claim(d, 0, replica=1) is not None
        client.submit({"epochs": 1, "batch_size": 32, "latent_dim": 4,
                       "log_interval": 1000, "seed": 2})
        with pytest.raises(fabric.FenceLost):
            svc.tick()
        # Nothing was appended by the stale incarnation: the new
        # spool file is still spooled, the journal untouched.
        assert len(squeue.load_queue(sd)) == n_before
    finally:
        svc.store.shutdown()


def test_fabric_replica_failover_inprocess(tmp_path):
    """Two in-process replicas: each claims its home shard; freezing
    one (no ticks = no renewals) makes the survivor adopt its shard
    and finish its work; unfreezing the stale replica drops the shard
    through the fence instead of double-placing."""
    from multidisttorch_tpu.service.fabric import FabricReplica

    d = str(tmp_path)
    cfg = {"epochs": 1, "batch_size": 32, "latent_dim": 4,
           "log_interval": 1000}
    kw = dict(
        n_shards=2,
        lease_deadline_s=0.6,
        renew_every_s=0.1,
        adopt_scan_every_s=0.1,
        nonpreferred_grace_s=0.3,
        n_slices=2,
        max_lanes=2,
        data_rows=64,
    )
    r0 = FabricReplica(d, replica=0, **kw)
    r1 = FabricReplica(d, replica=1, **kw)
    client = fabric.FabricClient(d, n_shards=2)
    ids = [
        client.submit({**cfg, "seed": 1}, tenant="alpha"),  # shard 0
        client.submit({**cfg, "seed": 2}, tenant="beta"),   # shard 1
        client.submit({**cfg, "seed": 3}, tenant="beta"),
    ]
    t0 = time.time()
    while time.time() - t0 < 30:
        r0.tick()
        r1.tick()
        if 0 in r0.services and 1 in r1.services:
            break
    assert 0 in r0.services and 1 in r1.services
    # Freeze replica 1 mid-service: its lease decays; replica 0 adopts
    # shard 1 and finishes everything.
    t0 = time.time()
    while time.time() - t0 < 60:
        r0.tick()
        if all(
            (client.status(s) or {}).get("state") == squeue.SETTLED
            for s in ids
        ):
            break
        time.sleep(0.02)
    final = client.wait(ids, timeout_s=1.0)
    assert all(r["state"] == squeue.SETTLED for r in final.values())
    assert r0.adoptions >= 1 and 1 in r0.services
    # The frozen replica resumes: fence check drops the shard, no
    # journal write, no double placement. Refresh r0's leases first —
    # the settle/wait asserts above ran without ticks, and on a loaded
    # machine that gap can exceed the 0.6 s lease deadline, making
    # shard 1 GENUINELY orphaned at r1's tick (re-adopting it would be
    # correct behavior, but not the scenario under test).
    r0.tick()
    n_before = len(squeue.load_queue(fabric.shard_dir(d, 1)))
    r1.tick()
    assert 1 not in r1.services
    assert r1.fences_lost >= 1
    assert len(squeue.load_queue(fabric.shard_dir(d, 1))) == n_before
    r0.drain(reason="test end")


def test_fabric_client_routes_by_tenant(tmp_path):
    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 2)
    client = fabric.FabricClient(d)
    sid_a = client.submit({"epochs": 1}, tenant="alpha")
    sid_b = client.submit({"epochs": 1}, tenant="beta")
    assert os.path.exists(os.path.join(
        squeue.intake_dir(fabric.shard_dir(d, fabric.shard_of("alpha", 2))),
        sid_a + ".json",
    ))
    assert os.path.exists(os.path.join(
        squeue.intake_dir(fabric.shard_dir(d, fabric.shard_of("beta", 2))),
        sid_b + ".json",
    ))
    assert client.status(sid_a)["state"] == squeue.PENDING
    assert client.status("nope") is None


# -- console ----------------------------------------------------------


def test_sweep_top_fabric_panel_and_json(tmp_path, capsys):
    import importlib
    import sys

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        ),
    )
    sweep_top = importlib.import_module("sweep_top")

    d = str(tmp_path)
    fabric.ensure_fabric_config(d, 2)
    f0 = fabric.try_claim(d, 0, replica=0)
    assert f0 is not None
    # Shard 0 alive under replica 0; shard 1 unclaimed; one submission
    # with a deadline sits journaled on shard 0.
    sd = fabric.shard_dir(d, 0)
    q = squeue.SubmissionQueue(sd)
    q.append({
        "event": "submitted",
        "sub": {"submission_id": "alpha-1", "tenant": "alpha",
                "config": {}, "priority": 1, "size": 1,
                "submit_ts": time.time(), "deadline_s": 120.0},
    })
    rc = sweep_top.main([d, "--service"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "service fabric" in out
    assert "shard-0" in out and "shard-1" in out
    assert "UNCLAIMED" in out
    assert "deadline" in out  # the live-table column
    rc = sweep_top.main([d, "--service", "--json"])
    assert rc == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["fabric"]["shards"]["0"]["replica"] == 0
    assert "alpha-1" in snap["shards"]["0"]["queue"]


# -- loadgen ----------------------------------------------------------


def test_loadgen_contracts_and_budget():
    from multidisttorch_tpu.service.loadgen import LoadSpec, _Sim

    sim = _Sim(LoadSpec(n_submissions=6000, seed=1))
    r = sim.run()
    assert r["zero_lost"]
    assert r["completed"] == r["admitted"]
    assert r["submitted"] == 6000
    # Small-n fairness is noisy; the 10% gate belongs to the 100k/1M
    # runs — here we assert it is broadly weight-shaped.
    assert r["fairness"]["max_abs_ratio_error"] is not None
    assert r["fairness"]["max_abs_ratio_error"] < 0.25
    assert r["placement_latency_s"]["count"] == r["admitted"]
    assert 0.0 <= r["deadline"]["hit_rate"] <= 1.0
    # The anti-thrash budget holds for EVERY simulated trial.
    cap = sim.preempt.max_preemptions_per_trial
    assert all(
        st.entry.preempt_count <= cap for st in sim.trials.values()
    )
    # Determinism: same spec, same story.
    r2 = _Sim(LoadSpec(n_submissions=6000, seed=1)).run()
    assert r2["placement_latency_s"] == r["placement_latency_s"]
    assert r2["churn"] == r["churn"]


def test_loadgen_preemption_improves_whale_deadline_hits():
    """Preemption earns its churn where it matters: a whale-heavy,
    tight-slack workload (large deadline trials that cannot wait for a
    natural slot) hits MORE deadlines with bounded preemption than
    without, on the identical seeded workload."""
    from multidisttorch_tpu.service.loadgen import LoadSpec, run_loadgen

    base = dict(
        n_submissions=2500,
        seed=3,
        deadline_frac=0.25,
        sizes=((1, 0.3), (2, 0.3), (4, 0.4)),
        slack_lo=1.5,
        slack_hi=3.0,
        utilization=2.0,
    )
    with_p = run_loadgen(LoadSpec(**base))
    without = run_loadgen(
        LoadSpec(**base, preempt=PreemptionPolicy(enabled=False))
    )
    assert with_p["churn"]["preempt_evictions"] >= 1
    assert without["churn"]["preempt_evictions"] == 0
    assert (
        with_p["deadline"]["hit_rate"]
        > without["deadline"]["hit_rate"]
    )
