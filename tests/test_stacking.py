"""Trial-stacking tests: vmapped stacked steps, the stacked data
gatherer, mask-and-refill lane surgery, and the driver's bucket
scheduling — including the ISSUE 1 acceptance contract: a stacked
trial's final params match the unstacked path bit-for-bit (same seed,
same data order, same submesh shape)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.data.sampler import (
    StackedTrialDataIterator,
    TrialDataIterator,
)
from multidisttorch_tpu.hpo.driver import (
    TrialConfig,
    config_is_stackable,
    run_hpo,
    stack_bucket_key,
)
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.steps import (
    TrialHypers,
    build_lane_state,
    create_stacked_train_state,
    create_train_state,
    make_lane_ops,
    make_stacked_eval_step,
    make_stacked_multi_step,
    make_stacked_train_step,
    make_train_step,
)


def _params_equal(a, b) -> bool:
    diffs = jax.tree.map(
        lambda x, y: bool(jnp.all(jnp.asarray(x) == jnp.asarray(y))), a, b
    )
    return all(jax.tree.leaves(diffs))


@pytest.fixture(scope="module")
def trial():
    return setup_groups(1)[0]  # all 8 virtual devices


@pytest.fixture(scope="module")
def model():
    return VAE(hidden_dim=32, latent_dim=8)


def test_stacked_step_bitwise_parity_with_unstacked(trial, model):
    # THE acceptance contract: K trials advanced by the vmapped stacked
    # step produce final params BIT-IDENTICAL to the same configs run
    # through make_train_step one at a time — same seeds, same batches,
    # same per-step RNG stream (fold_in(key(seed+1), step)), same
    # submesh. Different lrs, betas, and seeds per lane on purpose.
    K, B, steps = 3, 16, 3
    seeds, lrs, betas = [0, 5, 9], [1e-3, 3e-3, 2e-3], [1.0, 4.0, 1.0]
    hypers = TrialHypers.stack(lrs, betas)
    sstep = make_stacked_train_step(trial, model)
    state = create_stacked_train_state(trial, model, seeds)
    base = jnp.stack([jax.random.key(s + 1) for s in seeds])
    batches = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (steps, K, B, 784)),
        jnp.float32,
    )
    for i in range(steps):
        state, metrics = sstep(
            state, hypers, batches[i], base, jnp.full((K,), i, jnp.int32)
        )
    assert metrics["loss_sum"].shape == (K,)

    for k in range(K):
        su = create_train_state(
            trial, model, optax.adam(lrs[k]), jax.random.key(seeds[k])
        )
        ustep = make_train_step(
            trial, model, optax.adam(lrs[k]), beta=betas[k]
        )
        for i in range(steps):
            su, _ = ustep(
                su, batches[i, k],
                jax.random.fold_in(jax.random.key(seeds[k] + 1), i),
            )
        lane_params = jax.tree.map(lambda x: x[k], state.params)
        assert _params_equal(lane_params, su.params), f"lane {k} diverged"
        lane_opt = jax.tree.map(lambda x: x[k], state.opt_state)
        assert _params_equal(lane_opt, su.opt_state), f"lane {k} opt state"


def test_stacked_multi_step_matches_per_step(trial, model):
    # Scan-chunked stacked steps use the SAME per-step fold_in stream,
    # so chunked == per-step bitwise (stronger than make_multi_step,
    # whose split-based stream is its own).
    K, B, S = 2, 16, 4
    seeds = [1, 2]
    hypers = TrialHypers.stack([1e-3] * K, [1.0] * K)
    base = jnp.stack([jax.random.key(s + 1) for s in seeds])
    batches = jnp.asarray(
        np.random.default_rng(1).uniform(0, 1, (S, K, B, 784)), jnp.float32
    )
    s_multi = create_stacked_train_state(trial, model, seeds)
    multi = make_stacked_multi_step(trial, model)
    s_multi, m = multi(
        s_multi, hypers, batches, base, jnp.zeros((K,), jnp.int32)
    )
    assert m["loss_sum"].shape == (S, K)

    s_step = create_stacked_train_state(trial, model, seeds)
    sstep = make_stacked_train_step(trial, model)
    for i in range(S):
        s_step, _ = sstep(
            s_step, hypers, batches[i], base, jnp.full((K,), i, jnp.int32)
        )
    assert _params_equal(s_multi.params, s_step.params)


def test_active_mask_freezes_lane(trial, model):
    # active=0.0 freezes a lane exactly (params AND opt state), while
    # live lanes continue; the compiled program is the same either way.
    K, B = 2, 16
    hypers_live = TrialHypers.stack([1e-3] * K, [1.0] * K)
    hypers_mask = TrialHypers.stack([1e-3] * K, [1.0] * K, active=[1.0, 0.0])
    sstep = make_stacked_train_step(trial, model)
    state = create_stacked_train_state(trial, model, [3, 4])
    base = jnp.stack([jax.random.key(s + 1) for s in (3, 4)])
    batch = jnp.asarray(
        np.random.default_rng(2).uniform(0, 1, (K, B, 784)), jnp.float32
    )
    frozen_before = jax.device_get(
        jax.tree.map(lambda x: x[1], state.params)
    )
    state, _ = sstep(
        state, hypers_live, batch, base, jnp.zeros((K,), jnp.int32)
    )
    live_after_one = jax.device_get(jax.tree.map(lambda x: x[1], state.params))
    state, _ = sstep(
        state, hypers_mask, batch, base, jnp.ones((K,), jnp.int32)
    )
    lane1 = jax.tree.map(lambda x: x[1], state.params)
    assert _params_equal(lane1, live_after_one)  # frozen at step-1 values
    assert not _params_equal(lane1, frozen_before)  # did train before mask
    # the one compiled program served both hypers values
    assert sstep._cache_size() == 1


def test_lane_ops_read_write_single_compile(trial, model):
    K = 4
    read, write = make_lane_ops(trial)
    state = create_stacked_train_state(trial, model, list(range(K)))
    fresh = trial.device_put(build_lane_state(model, 99))
    fresh_host = jax.device_get(fresh.params)
    before_lane0 = jax.device_get(jax.tree.map(lambda x: x[0], state.params))

    state2 = write(state, fresh, np.int32(2))
    # lane 2 replaced, lane 0 untouched
    assert _params_equal(
        jax.tree.map(lambda x: x[2], state2.params), fresh_host
    )
    assert _params_equal(
        jax.tree.map(lambda x: x[0], state2.params), before_lane0
    )
    # read slices what write wrote
    lane = read(state2, np.int32(2))
    assert _params_equal(lane.params, fresh_host)
    # traced lane index: every k reuses ONE executable each way
    for k in (0, 1, 3):
        _ = read(state2, np.int32(k))
        state2 = write(
            state2, trial.device_put(build_lane_state(model, 50 + k)),
            np.int32(k),
        )
    assert read._cache_size() == 1
    assert write._cache_size() == 1


def test_stacked_eval_step_matches_unstacked(trial, model):
    from multidisttorch_tpu.train.steps import make_eval_step

    K, B = 2, 16
    betas = [1.0, 4.0]
    hypers = TrialHypers.stack([1e-3] * K, betas)
    state = create_stacked_train_state(trial, model, [0, 7])
    seval = make_stacked_eval_step(trial, model)
    batch = jnp.asarray(
        np.random.default_rng(3).uniform(0, 1, (B, 784)), jnp.float32
    )
    weights = jnp.asarray(
        np.r_[np.ones(10), np.zeros(6)].astype(np.float32)
    )
    out = seval(state, hypers, batch, weights)
    assert out["loss_sum"].shape == (K,)
    for k in range(K):
        su = create_train_state(
            trial, model, optax.adam(1e-3), jax.random.key([0, 7][k])
        )
        ev = make_eval_step(
            trial, model, beta=betas[k], with_recon=False, masked=True
        )
        ref = ev(su, batch, weights)
        assert float(out["loss_sum"][k]) == float(ref["loss_sum"])


def test_stacked_iterator_matches_trial_iterator(trial):
    data = synthetic_mnist(96, seed=0)
    seeds = [0, 11, 5]
    B = 16
    stacked = StackedTrialDataIterator(data, trial, B, seeds)
    singles = [
        TrialDataIterator(data, trial, B, seed=s, use_native=False)
        for s in seeds
    ]
    # two lockstep rounds == each lane's epochs 1 and 2, bit-identical
    for epoch in (1, 2):
        per_lane = [list(it.epoch(epoch)) for it in singles]
        for b, stacked_batch in enumerate(stacked.round_batches()):
            got = np.asarray(stacked_batch)
            assert got.shape == (len(seeds), B, 784)
            for k in range(len(seeds)):
                np.testing.assert_array_equal(
                    got[k], np.asarray(per_lane[k][b])
                )


def test_stacked_iterator_set_lane_refill_stream(trial):
    data = synthetic_mnist(64, seed=0)
    B = 16
    stacked = StackedTrialDataIterator(data, trial, B, [0, 3])
    list(stacked.round_batches())  # both lanes consume epoch 1
    stacked.set_lane(1, seed=42)  # refill lane 1 mid-sweep
    fresh = TrialDataIterator(data, trial, B, seed=42, use_native=False)
    lane0 = TrialDataIterator(data, trial, B, seed=0, use_native=False)
    fresh_batches = list(fresh.epoch(1))  # refilled lane restarts epoch 1
    lane0_batches = list(lane0.epoch(2))  # neighbor continues at epoch 2
    for b, stacked_batch in enumerate(stacked.round_batches()):
        got = np.asarray(stacked_batch)
        np.testing.assert_array_equal(got[0], np.asarray(lane0_batches[b]))
        np.testing.assert_array_equal(got[1], np.asarray(fresh_batches[b]))


def test_stacked_iterator_round_chunks_tail(trial):
    data = synthetic_mnist(80, seed=1)  # 5 batches of 16 -> chunks 2+2+1
    stacked = StackedTrialDataIterator(data, trial, 16, [0, 1])
    chunks = list(stacked.round_chunks(2))
    assert [c[0] for c in chunks] == [0, 2, 4]
    assert [c[1].shape[0] for c in chunks] == [2, 2, 1]
    assert chunks[0][1].shape[1:] == (2, 16, 784)
    # chunked rows == the per-step rows, same round
    stacked2 = StackedTrialDataIterator(data, trial, 16, [0, 1])
    flat = np.concatenate([np.asarray(c[1]) for c in chunks])
    steps = np.stack([np.asarray(b) for b in stacked2.round_batches()])
    np.testing.assert_array_equal(flat, steps)


def test_bucket_key_and_stackability():
    base = dict(trial_id=0, epochs=1, batch_size=16, hidden_dim=32,
                latent_dim=8)
    a = TrialConfig(**base)
    assert stack_bucket_key(a) == stack_bucket_key(
        TrialConfig(**{**base, "trial_id": 1, "lr": 9e-3, "beta": 7.0,
                       "seed": 4, "epochs": 5, "log_interval": 3})
    )
    assert stack_bucket_key(a) != stack_bucket_key(
        TrialConfig(**{**base, "hidden_dim": 64})
    )
    assert stack_bucket_key(a) != stack_bucket_key(
        TrialConfig(**{**base, "batch_size": 32})
    )
    assert config_is_stackable(a)
    assert not config_is_stackable(
        TrialConfig(**{**base, "eval_sampled": True})
    )


def _small_cfg(i, **kw):
    d = dict(trial_id=i, epochs=1, batch_size=16, hidden_dim=32,
             latent_dim=8, log_interval=100)
    d.update(kw)
    return TrialConfig(**d)


@pytest.fixture(scope="module")
def data():
    return synthetic_mnist(128, seed=0), synthetic_mnist(32, seed=1)


def test_run_hpo_stacked_end_to_end(tmp_path, data):
    # 5 same-shape configs on 2 groups: trials outnumber groups, so the
    # driver buckets and stacks; unequal epoch targets exercise
    # mask-and-refill retirement mid-bucket.
    train, test = data
    configs = [
        _small_cfg(0),
        _small_cfg(1, lr=3e-3),
        _small_cfg(2, epochs=2, beta=4.0),
        _small_cfg(3, seed=7),
        _small_cfg(4, epochs=3),
    ]
    results = run_hpo(
        configs, train, test, num_groups=2, out_dir=str(tmp_path),
        verbose=False, save_images=False, stack_trials=True,
    )
    assert [r.trial_id for r in results] == [0, 1, 2, 3, 4]
    for r in results:
        assert r.status == "completed"
        assert r.stacked
        assert r.steps == 8 * r.config.epochs
        assert len(r.history) == r.config.epochs
        assert np.isfinite(r.final_train_loss)
        assert np.isfinite(r.final_test_loss)
        assert r.checkpoint and os.path.exists(r.checkpoint)
        with open(os.path.join(r.out_dir, "metrics.json")) as f:
            metrics = json.load(f)
        assert metrics["trial_id"] == r.trial_id
        assert metrics["stacked"] is True
        assert metrics["dataset"] == "synthetic-mnist"
    # per-trial hypers took effect within the shared program
    assert results[0].final_train_loss != results[1].final_train_loss


def test_run_hpo_stacked_parity_with_unstacked(tmp_path, data):
    # Driver-level acceptance: every stacked trial's losses equal the
    # same config run unstacked on the same submesh shape, bitwise —
    # the stacked per-step RNG stream matches fused_steps=1 exactly.
    train, test = data
    configs = [_small_cfg(0, epochs=2), _small_cfg(1, lr=3e-3, epochs=2),
               _small_cfg(2, beta=2.0, seed=5, epochs=2)]
    stacked = run_hpo(
        configs, train, test, num_groups=1, out_dir=str(tmp_path / "s"),
        verbose=False, save_images=False, stack_trials=True,
    )
    assert all(r.stacked for r in stacked)
    for i, cfg in enumerate(configs):
        (un,) = run_hpo(
            [cfg], train, test, num_groups=1,
            out_dir=str(tmp_path / f"u{i}"),
            verbose=False, save_images=False,
        )
        assert stacked[i].final_train_loss == un.final_train_loss
        assert stacked[i].final_test_loss == un.final_test_loss


def test_run_hpo_stacked_checkpoint_resumes_unstacked(tmp_path, data):
    # A stacked lane's retirement checkpoint carries the same metadata
    # contract as the classic path: a later unstacked resume recognizes
    # the trial as complete and skips it.
    train, _ = data
    cfgs = [_small_cfg(0), _small_cfg(1, lr=2e-3)]
    run_hpo(
        cfgs, train, None, num_groups=1, out_dir=str(tmp_path),
        verbose=False, save_images=False, stack_trials=True,
    )
    (r,) = run_hpo(
        [cfgs[0]], train, None, num_groups=1, out_dir=str(tmp_path),
        verbose=False, save_images=False, resume=True,
    )
    assert r.status == "resumed_complete"
    assert r.steps == 8


def test_run_hpo_stacked_mixed_with_unstackable(tmp_path, data):
    # An eval_sampled config can't stack; it runs the classic path in
    # the same sweep while the rest bucket together.
    train, test = data
    configs = [
        _small_cfg(0), _small_cfg(1, lr=3e-3), _small_cfg(2, seed=2),
        _small_cfg(3, eval_sampled=True),
    ]
    results = run_hpo(
        configs, train, test, num_groups=2, out_dir=str(tmp_path),
        verbose=False, save_images=False, stack_trials=True,
    )
    assert [r.trial_id for r in results] == [0, 1, 2, 3]
    assert all(r.status == "completed" for r in results)
    assert [r.stacked for r in results] == [True, True, True, False]


def test_run_hpo_stacked_falls_back_when_groups_suffice(tmp_path, data):
    # Trials do NOT outnumber groups -> classic path, stacked=False.
    train, _ = data
    results = run_hpo(
        [_small_cfg(0), _small_cfg(1)], train, None, num_groups=2,
        out_dir=str(tmp_path), verbose=False, save_images=False,
        save_checkpoints=False, stack_trials=True,
    )
    assert all(not r.stacked for r in results)
    assert all(r.status == "completed" for r in results)


def test_run_hpo_stacked_rejects_contradictory_modes(tmp_path, data):
    train, _ = data
    cfgs = [_small_cfg(0), _small_cfg(1)]
    with pytest.raises(ValueError, match="resume"):
        run_hpo(cfgs, train, None, num_groups=1, out_dir=str(tmp_path),
                stack_trials=True, resume=True)
    with pytest.raises(ValueError, match="shard_across_trials"):
        run_hpo(cfgs, train, None, num_groups=1, out_dir=str(tmp_path),
                stack_trials=True, shard_across_trials=True)
    with pytest.raises(ValueError, match="model_builder"):
        run_hpo(cfgs, train, None, num_groups=1, out_dir=str(tmp_path),
                stack_trials=True, model_builder=lambda cfg: VAE())


def test_run_hpo_stacked_fused_steps_bucket(tmp_path, data):
    # fused_steps>1 buckets use the scan-chunked stacked multi-step
    # (with the per-step tail); counts and history match the contract.
    train, _ = data
    configs = [_small_cfg(i, fused_steps=3, epochs=2) for i in range(3)]
    results = run_hpo(
        configs, train, None, num_groups=1, out_dir=str(tmp_path),
        verbose=False, save_images=False, stack_trials=True,
    )
    assert all(r.stacked for r in results)
    assert all(r.steps == 16 for r in results)
    assert all(len(r.history) == 2 for r in results)


def test_run_hpo_stacked_host_syncs_o1(tmp_path, data):
    # The bucket pays O(1) fetches per ROUND for all lanes together: 2
    # per epoch (train avg + test avg) regardless of lane count.
    train, test = data
    configs = [_small_cfg(i, epochs=2) for i in range(4)]
    results = run_hpo(
        configs, train, test, num_groups=1, out_dir=str(tmp_path),
        verbose=False, save_images=False, stack_trials=True,
    )
    for r in results:
        assert r.host_syncs == 2 * 2
