"""Test harness: 8 virtual CPU devices in one process.

The reference has no tests at all (SURVEY.md §4); its de-facto smoke test
requires an 8-process mpirun/srun launch (``example-subgroup.py:39``).
The JAX-native analog needs no launcher: force the host platform to
expose 8 fake CPU devices so submesh carving, per-trial collectives, and
full HPO runs execute in plain pytest.

Must run before any JAX backend initialization. The environment's
sitecustomize may pre-import jax with a TPU plugin pinned via
JAX_PLATFORMS, so we override through jax.config (effective until the
backend is first used) rather than os.environ alone.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

# Persistent XLA compilation cache: DISABLED here (and everywhere, by
# default — utils/compile_cache.py) on this toolchain. The pinned
# jaxlib's XLA:CPU executable deserialization corrupts the heap: with a
# warm cache, the first suite run to rebuild an already-cached program
# (test_hpo.py's resume tests rebuild the train step in-process) takes
# the cache-READ path and dies with SIGSEGV / `malloc:
# chunk_main_arena`, killing every test after test_hpo.py. A full cold
# suite costs minutes of recompiles; a corrupted interpreter costs the
# entire run. Opt back in with MDT_FORCE_COMPILE_CACHE=1 on a jaxlib
# whose CPU thunk serialization is sound (the env var is honored by
# enable_persistent_compile_cache, which this harness deliberately no
# longer calls unconditionally).

import jax

jax.config.update("jax_platforms", "cpu")

from multidisttorch_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_compile_cache,
)

enable_persistent_compile_cache()  # no-op unless MDT_FORCE_COMPILE_CACHE=1

import pytest  # noqa: E402


def pytest_collection_modifyitems(items):
    # Two-tier gate (VERDICT r4 weak #6): every subprocess-spawning
    # test (multi-process worlds, example-CLI smokes) is also `slow`,
    # so `pytest -m "not slow"` is the fast in-process core suite and
    # the full run stays the complete gate. Done here rather than
    # per-file so a new multihost/examples test can't forget the tier.
    for item in items:
        if "multihost" in item.keywords or "examples" in item.keywords:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session", autouse=True)
def _assert_eight_devices():
    assert len(jax.devices()) == 8, (
        "test harness expected 8 virtual CPU devices, got "
        f"{jax.devices()} — conftest ran too late relative to backend init"
    )
