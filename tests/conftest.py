"""Test harness: 8 virtual CPU devices in one process.

The reference has no tests at all (SURVEY.md §4); its de-facto smoke test
requires an 8-process mpirun/srun launch (``example-subgroup.py:39``).
The JAX-native analog needs no launcher: force the host platform to
expose 8 fake CPU devices so submesh carving, per-trial collectives, and
full HPO runs execute in plain pytest.

Must run before any JAX backend initialization. The environment's
sitecustomize may pre-import jax with a TPU plugin pinned via
JAX_PLATFORMS, so we override through jax.config (effective until the
backend is first used) rather than os.environ alone.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_eight_devices():
    assert len(jax.devices()) == 8, (
        "test harness expected 8 virtual CPU devices, got "
        f"{jax.devices()} — conftest ran too late relative to backend init"
    )
