"""Test harness: 8 virtual CPU devices in one process.

The reference has no tests at all (SURVEY.md §4); its de-facto smoke test
requires an 8-process mpirun/srun launch (``example-subgroup.py:39``).
The JAX-native analog needs no launcher: force the host platform to
expose 8 fake CPU devices so submesh carving, per-trial collectives, and
full HPO runs execute in plain pytest.

Must run before any JAX backend initialization. The environment's
sitecustomize may pre-import jax with a TPU plugin pinned via
JAX_PLATFORMS, so we override through jax.config (effective until the
backend is first used) rather than os.environ alone.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

# Persistent XLA compilation cache: the suite is dominated by jit
# compiles of the same programs run-over-run (measured 4.5x on the
# heaviest file), and cache keys are HLO hashes so staleness is
# impossible by construction. The env vars alone are NOT enough here —
# sitecustomize pre-imports jax, which freezes env-derived config
# before this file runs — so mirror them through jax.config.update
# (same trick as the platform pin below). The env vars still matter:
# subprocess-spawning tests (multihost worlds, example smokes) inherit
# them, and those children have no sitecustomize-pre-import problem
# at the point their conftest-less interpreters start jax fresh.
_CACHE_DIR = os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), ".jax_cache"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

import jax

jax.config.update("jax_platforms", "cpu")

from multidisttorch_tpu.utils.compile_cache import (  # noqa: E402
    enable_persistent_compile_cache,
)

enable_persistent_compile_cache(_CACHE_DIR)

import pytest  # noqa: E402


def pytest_collection_modifyitems(items):
    # Two-tier gate (VERDICT r4 weak #6): every subprocess-spawning
    # test (multi-process worlds, example-CLI smokes) is also `slow`,
    # so `pytest -m "not slow"` is the fast in-process core suite and
    # the full run stays the complete gate. Done here rather than
    # per-file so a new multihost/examples test can't forget the tier.
    for item in items:
        if "multihost" in item.keywords or "examples" in item.keywords:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session", autouse=True)
def _assert_eight_devices():
    assert len(jax.devices()) == 8, (
        "test harness expected 8 virtual CPU devices, got "
        f"{jax.devices()} — conftest ran too late relative to backend init"
    )
