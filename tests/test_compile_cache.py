"""The shared persistent-compile-cache switch (utils/compile_cache.py)
— the one policy behind the test harness, the multichip dryrun, and
bench's CPU fallback."""

import os

import jax

from multidisttorch_tpu.utils.compile_cache import (
    default_cache_dir,
    enable_persistent_compile_cache,
)


def test_default_dir_honors_env_override(monkeypatch):
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/some/shared/disk")
    assert default_cache_dir() == "/some/shared/disk"


def test_default_dir_anchors_at_checkout_root(monkeypatch):
    # cwd-independent: the fallback is .jax_cache NEXT TO the package,
    # so every entry point shares one cache no matter where it runs.
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    monkeypatch.chdir("/tmp")
    d = default_cache_dir()
    assert d.endswith(".jax_cache")
    import multidisttorch_tpu

    pkg_root = os.path.dirname(
        os.path.dirname(os.path.abspath(multidisttorch_tpu.__file__))
    )
    assert d == os.path.join(pkg_root, ".jax_cache")


def test_enable_is_noop_without_optin(tmp_path, monkeypatch):
    # Default-off on this toolchain: deserialized XLA:CPU executables
    # corrupt the heap on the pinned jaxlib (module docstring — the
    # seed suite's test_hpo resume segfault), so without the explicit
    # opt-in the switch must change NOTHING.
    monkeypatch.delenv("MDT_FORCE_COMPILE_CACHE", raising=False)
    target = str(tmp_path / "cache")
    prev = jax.config.jax_compilation_cache_dir
    assert enable_persistent_compile_cache(target) is False
    assert not os.path.exists(target)
    assert jax.config.jax_compilation_cache_dir == prev


def test_enable_sets_config_and_creates_dir(tmp_path, monkeypatch):
    # Opt-in path (a jaxlib whose CPU executable serialization is
    # sound): the original behavior, behind MDT_FORCE_COMPILE_CACHE=1.
    monkeypatch.setenv("MDT_FORCE_COMPILE_CACHE", "1")
    target = str(tmp_path / "cache")
    prev = jax.config.jax_compilation_cache_dir
    try:
        assert enable_persistent_compile_cache(target) is True
        assert os.path.isdir(target)
        assert jax.config.jax_compilation_cache_dir == target
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_enable_is_best_effort_on_bad_dir(tmp_path, monkeypatch):
    # A path that cannot be a directory must return False and leave the
    # config untouched — the cache is an optimization, never a failure.
    monkeypatch.setenv("MDT_FORCE_COMPILE_CACHE", "1")
    blocker = tmp_path / "file"
    blocker.write_text("x")
    prev = jax.config.jax_compilation_cache_dir
    assert enable_persistent_compile_cache(str(blocker / "sub")) is False
    assert jax.config.jax_compilation_cache_dir == prev
