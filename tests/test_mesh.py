"""Submesh carving tests — parity with /root/reference/utils.py:146-163."""

import jax
import numpy as np
import pytest

from multidisttorch_tpu.parallel.mesh import (
    DATA_AXIS,
    device_world,
    global_mesh,
    setup_groups,
)


def test_device_world():
    n, first_local = device_world()
    assert n == 8
    assert first_local == 0


def test_global_mesh_covers_all_devices():
    mesh = global_mesh()
    assert mesh.devices.size == 8
    assert mesh.axis_names == (DATA_AXIS,)


class TestSetupGroups:
    def test_two_groups_contiguous(self):
        # Reference carving: contiguous blocks [g*k .. g*k+k-1]
        # (utils.py:156); with world 8 and 2 groups -> [0-3], [4-7],
        # matching example-subgroup.py:20-23.
        groups = setup_groups(2)
        assert [g.global_ranks for g in groups] == [(0, 1, 2, 3), (4, 5, 6, 7)]

    def test_disjoint_and_complete(self):
        groups = setup_groups(4)
        all_ranks = [r for g in groups for r in g.global_ranks]
        assert sorted(all_ranks) == list(range(8))
        assert len(set(all_ranks)) == 8
        seen_devices = set()
        for g in groups:
            for d in g.devices:
                assert d not in seen_devices
                seen_devices.add(d)

    def test_group_size_and_mesh_axis(self):
        groups = setup_groups(2)
        for g in groups:
            assert g.size == 4
            assert g.mesh.axis_names == (DATA_AXIS,)

    def test_eight_groups_of_one(self):
        groups = setup_groups(8)
        assert all(g.size == 1 for g in groups)

    def test_one_group_is_whole_world(self):
        (g,) = setup_groups(1)
        assert g.global_ranks == tuple(range(8))

    def test_too_many_groups_raises(self):
        # Reference asserts world_size >= num_groups (utils.py:150).
        with pytest.raises(ValueError, match="exceeds number of total"):
            setup_groups(9)

    def test_non_divisible_raises(self):
        # Fix of quirk Q5: the reference silently orphans remainder ranks
        # and the job hangs (utils.py:152, vae-hpo.py:201).
        with pytest.raises(ValueError, match="orphaned"):
            setup_groups(3)

    def test_allow_uneven_drops_remainder(self):
        groups = setup_groups(3, allow_uneven=True)
        assert all(g.size == 2 for g in groups)
        covered = {r for g in groups for r in g.global_ranks}
        assert covered == set(range(6))  # devices 6, 7 deliberately dropped

    def test_membership_single_controller(self):
        # Every process holds handles to ALL groups (reference contract,
        # utils.py:163) and tests membership per group (vae-hpo.py:201).
        groups = setup_groups(2)
        for g in groups:
            assert g.is_local_member  # single-controller: owns everything
            assert g.local_rank == 0
            assert g.rank_of(g.devices[0]) == 0
            assert g.rank_of(g.devices[-1]) == g.size - 1
            # Non-member device has rank -1, like dist.get_rank -> -1.
            other = groups[1 - g.group_id].devices[0]
            assert g.rank_of(other) == -1

    def test_zero_groups_raises(self):
        with pytest.raises(ValueError, match=">= 1"):
            setup_groups(0)

    def test_carving_is_metadata_only_fast(self):
        # Q2: no collective handshake — carving 8 groups must be
        # instantaneous (no compilation, no device sync).
        import time

        t0 = time.perf_counter()
        for _ in range(50):
            setup_groups(8)
        assert time.perf_counter() - t0 < 2.0

    def test_shardings(self):
        g0, _ = setup_groups(2)
        x = np.arange(8, dtype=np.float32).reshape(4, 2)
        xs = jax.device_put(x, g0.batch_sharding)
        assert xs.sharding.mesh == g0.mesh
        params = g0.device_put({"w": np.ones((3,), np.float32)})
        np.testing.assert_array_equal(np.asarray(params["w"]), np.ones(3))
