"""Non-finite-loss detection (train/guards.py): a NaN loss surfaces as
a structured DivergenceError naming the step — in the HPO driver's
epoch boundary and, via guard_finite, in the non-HPO classifier/LM
loops — never as a silent garbage metric."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.guards import (
    DivergenceError,
    check_finite,
    guard_finite,
)


def test_check_finite_passes_and_names_step_on_nan():
    assert check_finite(1.25, "loss", step=7) == 1.25
    with pytest.raises(DivergenceError, match=r"step 41"):
        check_finite(float("nan"), "loss", step=41, trial_id=3)
    with pytest.raises(DivergenceError, match="trial 3"):
        check_finite(float("inf"), "loss", step=41, trial_id=3)
    # Structured fields, not just message text: supervisors classify on
    # the type and act on the step.
    try:
        check_finite(float("nan"), "epoch avg", step=8, trial_id=0)
    except DivergenceError as e:
        assert e.step == 8 and e.trial_id == 0 and e.what == "epoch avg"


def test_guard_finite_validates_every():
    with pytest.raises(ValueError, match="every"):
        guard_finite(lambda s: s, every=0)


def test_classifier_nan_loss_raises_divergence_error_naming_step():
    # Satellite contract: the classifier loop's structured divergence
    # surface. NaN images drive the real compiled step's loss to NaN;
    # the guard names the optimizer step.
    from multidisttorch_tpu.models.resnet import ResNet
    from multidisttorch_tpu.train.classifier import (
        create_classifier_state,
        make_classifier_train_step,
    )

    model = ResNet(stage_sizes=(1,), base_channels=8, image_hw=16)
    (trial,) = setup_groups(1)
    tx = optax.adam(1e-3)
    state = create_classifier_state(trial, model, tx, jax.random.key(0))
    step = guard_finite(
        make_classifier_train_step(trial, model, tx),
        key="loss",
        what="classifier train loss",
    )

    rng = np.random.default_rng(0)
    good = jnp.asarray(
        rng.uniform(0, 1, (16, 16 * 16 * 3)).astype(np.float32)
    )
    labels = jnp.asarray(rng.integers(0, 10, (16,)).astype(np.int32))
    state, m = step(state, good, labels)  # healthy step passes through
    assert np.isfinite(float(m["loss"]))

    bad = jnp.full_like(good, jnp.nan)
    with pytest.raises(DivergenceError, match=r"step 2") as ei:
        step(state, bad, labels)
    assert ei.value.step == 2  # step 1 was the healthy one


def test_lm_nan_loss_raises_divergence_error():
    # Satellite contract: the LM loop's surface. Tokens are ints (can't
    # carry NaN), so poison the params — the realistic LM divergence
    # shape (weights blow up, loss follows).
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.train.lm import create_lm_state, make_lm_train_step

    (g,) = setup_groups(1)
    model = TransformerLM(
        vocab_size=17, d_model=32, num_heads=2, num_layers=1, max_len=32
    )
    tx = optax.adam(1e-3)
    state = create_lm_state(g, model, tx, jax.random.key(0), example_len=32)
    step = guard_finite(
        make_lm_train_step(g, model, tx), key="loss", what="lm train loss"
    )
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 17, (8, 32)).astype(np.int32)
    )
    state, m = step(state, tokens)
    assert np.isfinite(float(m["loss"]))

    from multidisttorch_tpu.train.steps import TrainState

    poisoned = TrainState(
        params=jax.tree.map(lambda a: jnp.full_like(a, jnp.nan), state.params),
        opt_state=state.opt_state,
        step=state.step,
    )
    with pytest.raises(DivergenceError, match="lm train loss"):
        step(poisoned, tokens)


def test_guard_finite_every_n_checks_at_cadence():
    # every=2: the NaN introduced on call 1 is only *checked* on call 2
    # — the documented detection-lag/sync trade.
    calls = []

    class FakeState:
        def __init__(self, step):
            self.step = step

    def fake_step(state, loss):
        calls.append(loss)
        return FakeState(state.step + 1), {"loss": np.float32(loss)}

    g = guard_finite(fake_step, key="loss", every=2)
    s = FakeState(0)
    s, _ = g(s, float("nan"))  # call 1: unchecked by design
    with pytest.raises(DivergenceError):
        g(s, float("nan"))  # call 2: checked

def test_guard_finite_fused_metric_names_inner_step():
    # A scan-fused (K,) loss vector: the first bad entry names the
    # exact inner optimizer step, not just the dispatch.
    class FakeState:
        def __init__(self, step):
            self.step = step

    def fused_step(state, losses):
        return FakeState(state.step + len(losses)), {
            "loss": np.asarray(losses, np.float32)
        }

    g = guard_finite(fused_step, key="loss")
    with pytest.raises(DivergenceError) as ei:
        g(FakeState(10), [1.0, 2.0, float("nan"), 4.0])
    # steps 11,12,13,14 — the NaN is step 13
    assert ei.value.step == 13
