"""Telemetry tests: event bus (bounded queue, torn-tail JSONL, ordering
across retry boundaries), exporters (Perfetto trace loads + monotonic,
Prometheus dump parses), stacked step-time attribution, and the
zero-cost-when-off contract (no event objects constructed on hot paths
with telemetry disabled — the CI tier-1 guard of ISSUE 3)."""

import importlib.util
import json
import logging
import os
import re

import numpy as np
import pytest

from multidisttorch_tpu import telemetry
from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.faults.plan import CRASH, FaultPlan, FaultSpec
from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
from multidisttorch_tpu.hpo.supervision import RetryPolicy
from multidisttorch_tpu.telemetry import anomaly as tele_anomaly
from multidisttorch_tpu.telemetry import device as tele_device
from multidisttorch_tpu.telemetry import events as tele_events
from multidisttorch_tpu.telemetry import export as tele_export
from multidisttorch_tpu.telemetry import metrics as tele_metrics
from multidisttorch_tpu.utils.profiling import StepTimer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_off_after():
    """Every test leaves telemetry globally OFF (the default state the
    rest of the suite assumes)."""
    yield
    telemetry.disable()


def small_configs(n, epochs=1, **kw):
    return [
        TrialConfig(
            trial_id=i, epochs=epochs, batch_size=16, hidden_dim=16,
            latent_dim=4, seed=i, log_interval=10_000, **kw,
        )
        for i in range(n)
    ]


# -- event bus ---------------------------------------------------------


def test_bounded_queue_drops_oldest():
    bus = tele_events.Bus(queue_max=4)
    for i in range(10):
        bus.emit("tick", step=i)
    recent = bus.recent()
    assert len(recent) == 4
    assert [e.step for e in recent] == [6, 7, 8, 9]  # newest kept
    assert bus.dropped == 6
    assert bus.emitted == 10


def test_jsonl_sink_and_torn_tail(tmp_path):
    path = str(tmp_path / "events.jsonl")
    bus = tele_events.Bus(path=path)
    for i in range(3):
        bus.emit("tick", step=i, trial_id=1)
    bus.close()
    # A crash mid-append tears the final line; the reader must skip it
    # (same contract as the sweep ledger).
    with open(path, "a") as f:
        f.write('{"kind": "torn", "ts": 1.0, "da')
    got = tele_events.read_events(path)
    assert [e["step"] for e in got] == [0, 1, 2]
    assert all(e["kind"] == "tick" for e in got)
    # Event fields round-trip; identity tags ride at the top level.
    assert got[0]["trial_id"] == 1


def test_bus_survives_sink_failure(tmp_path):
    path = str(tmp_path / "events.jsonl")
    bus = tele_events.Bus(path=path)
    bus.emit("a")
    bus._sink.close()  # simulate the fd dying under the bus
    bus.emit("b")  # must not raise; degrades to in-memory only
    assert [e.kind for e in bus.recent()] == ["a", "b"]
    assert bus._sink is None


# -- event ordering across a retry boundary (driver integration) -------


def test_event_ordering_across_retry(tmp_path):
    tdir = str(tmp_path / "tele")
    cfgs = small_configs(2, epochs=2)
    data = synthetic_mnist(64, seed=0)
    plan = FaultPlan(specs=(FaultSpec(CRASH, 0, step=5),))
    with telemetry.telemetry_run(tdir):
        results = run_hpo(
            cfgs, data, None, num_groups=2,
            out_dir=str(tmp_path / "out"),
            save_images=False, verbose=False,
            resilient=True, retry=RetryPolicy(max_retries=2,
                                              backoff_base_s=0.01),
            fault_plan=plan,
        )
    assert all(
        r.status in ("completed", "resumed_complete") for r in results
    )
    events = tele_events.read_events(os.path.join(tdir, "events.jsonl"))
    # Timestamps are monotone non-decreasing in append order.
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)
    # Trial 0's lifecycle reads in causal order across the retry
    # boundary: start(1) .. fault .. end(retrying) .. start(2) ..
    # end(completed).
    seq = [
        (e["kind"], (e.get("data") or {}).get("status"))
        for e in events
        if e.get("trial_id") == 0
        and e["kind"] in ("attempt_start", "attempt_end",
                          "fault_injected", "retry_scheduled")
    ]
    kinds = [k for k, _ in seq]
    assert kinds.index("fault_injected") > kinds.index("attempt_start")
    assert ("attempt_end", "retrying") in seq
    assert ("attempt_end", "completed") in seq
    assert seq.index(("attempt_end", "retrying")) < seq.index(
        ("attempt_end", "completed")
    )
    # The second attempt_start lands after the retrying end.
    starts = [i for i, (k, _) in enumerate(seq) if k == "attempt_start"]
    assert len(starts) == 2
    assert starts[1] > seq.index(("attempt_end", "retrying"))
    # The scheduled retry itself is an event.
    assert "retry_scheduled" in kinds


def test_stacked_sweep_emits_bucket_and_lane_events(tmp_path):
    tdir = str(tmp_path / "tele")
    cfgs = small_configs(3, epochs=1)
    data = synthetic_mnist(64, seed=0)
    with telemetry.telemetry_run(tdir):
        results = run_hpo(
            cfgs, data, None, num_groups=1,
            out_dir=str(tmp_path / "out"),
            save_images=False, verbose=False,
            stack_trials=True, stack_max_lanes=2,
        )
    assert [r.status for r in results] == ["completed"] * 3
    events = tele_events.read_events(os.path.join(tdir, "events.jsonl"))
    kinds = [e["kind"] for e in events]
    assert "stack_bucket" in kinds
    # 3 trials over 2 lanes: every retirement frees a lane; one refill
    # (the queued third trial) and two terminal maskings.
    assert kinds.count("lane_retire") == 3
    assert kinds.count("lane_refill") == 1
    assert kinds.count("lane_masked") == 2
    # Stacked epochs are lane-tagged.
    lanes = {e.get("lane") for e in events if e["kind"] == "epoch"}
    assert lanes <= {0, 1} and lanes


# -- exporters ---------------------------------------------------------


def _demo_events(tmp_path):
    tdir = str(tmp_path / "tele")
    cfgs = small_configs(2, epochs=1)
    data = synthetic_mnist(64, seed=0)
    plan = FaultPlan(specs=(FaultSpec(CRASH, 0, step=1),))
    with telemetry.telemetry_run(tdir):
        run_hpo(
            cfgs, data, None, num_groups=2,
            out_dir=str(tmp_path / "out"),
            save_images=False, verbose=False,
            resilient=True,
            retry=RetryPolicy(max_retries=1, backoff_base_s=0.01),
            fault_plan=plan,
        )
        reg = telemetry.get_registry()
        paths = tele_export.export_all(tdir, registry=reg)
    return tdir, paths


def test_trace_export_loads_and_is_monotonic(tmp_path):
    _tdir, paths = _demo_events(tmp_path)
    with open(paths["trace"]) as f:
        trace = json.loads(f.read())  # loads == Perfetto-parseable JSON
    evs = trace["traceEvents"]
    assert evs, "trace must not be empty"
    ts = [e["ts"] for e in evs if "ts" in e]
    assert ts == sorted(ts), "trace timestamps must be monotonic"
    assert all(t >= 0 for t in ts)
    # One track per trial: thread_name metadata for both trials, and
    # the attempt spans ride their trial's tid.
    names = {
        e["args"]["name"] for e in evs if e.get("name") == "thread_name"
    }
    assert {"driver", "trial 0", "trial 1"} <= names
    spans = [e for e in evs if e.get("ph") == "X"]
    assert spans and all(e["dur"] >= 0 for e in spans)
    # The injected fault appears as a tagged instant on trial 0's track.
    faults = [e for e in evs if e.get("name") == "fault_injected"]
    assert faults and faults[0]["tid"] == 1  # tid = trial_id + 1
    assert faults[0]["args"]["fault_kind"] == "crash"


_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+informna]+$"
)
_PROM_TYPE = re.compile(
    r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* (counter|gauge|histogram)$"
)


def test_prometheus_dump_parses(tmp_path):
    _tdir, paths = _demo_events(tmp_path)
    with open(paths["prometheus"]) as f:
        text = f.read()
    assert text.strip(), "dump must not be empty"
    seen_samples = 0
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert _PROM_TYPE.match(line), f"bad TYPE line: {line!r}"
        else:
            assert _PROM_SAMPLE.match(line), f"bad sample line: {line!r}"
            seen_samples += 1
    assert seen_samples >= 3
    # Histogram buckets are cumulative (monotone in le order as dumped).
    for name in {
        line.split("{")[0]
        for line in text.splitlines()
        if "_bucket{" in line
    }:
        series = [
            float(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith(name + "{")
        ]
        assert series == sorted(series)


def test_run_summary_accounting(tmp_path):
    tdir, paths = _demo_events(tmp_path)
    with open(paths["summary"]) as f:
        summary = json.load(f)
    assert summary["events"] == len(
        tele_events.read_events(os.path.join(tdir, "events.jsonl"))
    )
    # Trial 0 crashed once and retried: 2 attempts, 1 retry; goodput
    # counts its replayed work in the denominator only.
    t0 = summary["trials"]["0"]
    assert t0["attempts"] == 2
    assert t0["retries"] == 1
    assert t0["status"] == "completed"
    assert summary["executed_steps"] >= summary["useful_steps"] > 0
    assert 0 < summary["goodput"] <= 1.0
    assert "metrics" in summary  # registry snapshot embedded


# -- zero-cost-when-off (the CI tier-1 guard) --------------------------


class _Boom:
    def __init__(self, *a, **kw):
        raise AssertionError(
            "telemetry Event constructed with telemetry OFF — the "
            "zero-cost contract is broken"
        )


def _boom_fn(*a, **kw):
    raise AssertionError(
        "telemetry device/anomaly seam reached with telemetry OFF — the "
        "zero-cost contract is broken"
    )


def test_telemetry_off_constructs_no_events(tmp_path, monkeypatch):
    assert telemetry.get_bus() is None and telemetry.get_registry() is None
    assert telemetry.get_monitor() is None
    # Any Event construction anywhere in the sweep now explodes.
    monkeypatch.setattr(tele_events, "Event", _Boom)
    monkeypatch.setattr(
        tele_metrics, "StepSeries", _Boom
    )  # and no step series either
    # ...and no device-book or anomaly objects either (ISSUE 4): the
    # cost/memory/straggler seams must all sit behind the same guards.
    monkeypatch.setattr(tele_device, "record_step_cost", _boom_fn)
    monkeypatch.setattr(tele_device, "sample_memory", _boom_fn)
    monkeypatch.setattr(tele_device, "compiled_cost_analysis", _boom_fn)
    monkeypatch.setattr(tele_anomaly, "RollingRobustZ", _Boom)
    monkeypatch.setattr(tele_anomaly, "AnomalyMonitor", _Boom)
    cfgs = small_configs(2, epochs=1)
    data = synthetic_mnist(64, seed=0)
    results = run_hpo(
        cfgs, data, data, num_groups=2,
        out_dir=str(tmp_path / "out"),
        save_images=False, verbose=False,
    )
    assert [r.status for r in results] == ["completed"] * 2
    assert telemetry.get_bus() is None


# -- step-time semantics (StepTimer satellite + StepSeries) ------------


def test_steptimer_stacked_attribution():
    t = StepTimer()
    for _ in range(4):
        t.mark(lanes=4)  # K=4 stacked bucket dispatches
    s = t.stats()
    assert s["steps"] == 4  # dispatches, as before
    assert s["lane_steps"] == 16  # but 16 lane-steps of progress
    assert s["per_lane_steps_per_s"] == pytest.approx(
        16 / s["total_s"]
    )
    # Unstacked marks keep the exact legacy stats shape (no new keys).
    t2 = StepTimer()
    t2.mark()
    t2.mark()
    assert "lane_steps" not in t2.stats()


def test_steptimer_separates_sync_population():
    """The p95 satellite of ISSUE 4: sparse sync=True marks (device-
    inclusive, systematically longer) must not contaminate the
    dispatch-only percentiles — the two populations report separately,
    mirroring StepSeries' dispatch/device books."""
    t = StepTimer()
    # Hand-build the two populations (no sleeps): 20 fast dispatch
    # marks and 2 slow synced ones.
    t.times = [0.001] * 20 + [0.5, 0.6]
    t.lanes = [1] * 22
    t.synced = [False] * 20 + [True, True]
    s = t.stats()
    assert s["steps"] == 22
    assert s["p95_s"] == pytest.approx(0.001)  # uncontaminated
    assert s["mean_s"] == pytest.approx(0.001)
    assert s["total_s"] == pytest.approx(20 * 0.001 + 1.1)
    dev = s["device_sampled"]
    assert dev["count"] == 2
    assert dev["p50_s"] == pytest.approx(0.55)
    # No sync marks -> exact legacy shape, no new keys.
    t2 = StepTimer()
    t2.times, t2.lanes, t2.synced = [0.001] * 3, [1] * 3, [False] * 3
    assert "device_sampled" not in t2.stats()


def test_step_series_open_interval():
    """open_interval breaks the chain: the next mark opens instead of
    closing a boundary-spanning interval (epoch boundaries must not
    read as giant steps)."""
    s = tele_metrics.StepSeries(sample_every=0)
    s.mark()
    assert s.mark() is not None  # normal chained mark observes
    s.open_interval()
    assert s.mark() is None  # re-opened: nothing observed
    assert s.mark() is not None
    assert s.dispatches == 2


def test_step_series_synced_mark_returns_none():
    """A device-synced sample's interval includes the drained dispatch
    backlog — it must go to the device book but NOT be returned as a
    dispatch dt (the straggler detector would false-fire on it every
    sample_every marks and burn its capture budget)."""
    import jax.numpy as jnp

    v = jnp.zeros(())
    s = tele_metrics.StepSeries(sample_every=1)  # every mark syncs
    s.mark(v)  # opening
    assert s.mark(v) is None
    assert s.device.count == 1  # ...but the device book observed it
    s2 = tele_metrics.StepSeries(sample_every=0)  # never syncs
    s2.mark(v)
    assert s2.mark(v) is not None  # dispatch marks still feed the det.


def test_step_series_per_lane_rate():
    s = tele_metrics.StepSeries(sample_every=0)
    s.mark()  # opens the first interval
    for _ in range(5):
        s.mark(steps=2, lanes=3)  # fused-2 dispatches on a 3-lane bucket
    snap = s.snapshot()
    assert snap["dispatches"] == 5
    assert snap["steps"] == 10
    assert snap["lane_steps"] == 30
    assert snap["per_lane_steps_per_s"] == pytest.approx(
        3 * snap["steps_per_s"]
    )
    assert snap["dispatch"]["count"] == 5


def test_histogram_percentile_buckets():
    h = tele_metrics.Histogram(bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 4
    assert h.percentile(50) == 0.1  # bucket upper bound estimate
    assert h.percentile(100) == 10.0
    h.observe(100.0)  # +Inf bucket reports the max seen
    assert h.percentile(100) == 100.0


def test_registry_labels_and_snapshot():
    reg = tele_metrics.MetricsRegistry()
    reg.counter("retries", trial="3").inc()
    reg.counter("retries", trial="3").inc()
    reg.gauge("lanes", group="0").set(4)
    snap = reg.snapshot()
    assert snap["counters"]['retries{trial="3"}'] == 2.0
    assert snap["gauges"]['lanes{group="0"}'] == 4.0


# -- console tools -----------------------------------------------------


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_sweep_top_renders_live_and_finished(tmp_path, capsys):
    tdir, _paths = _demo_events(tmp_path)
    sweep_top = _load_tool("sweep_top")
    assert sweep_top.main([tdir]) == 0
    out = capsys.readouterr().out
    assert "goodput" in out
    assert "sweep finished" in out
    assert re.search(r"^0\s+ok", out, re.M)  # trial 0 row, completed
    assert re.search(r"^0\s+ok\s+2", out, re.M)  # ...on attempt 2
    # Live tail: truncate the file mid-line; the renderer holds the
    # torn tail for the next poll instead of crashing.
    ev_path = os.path.join(tdir, "events.jsonl")
    blob = open(ev_path).read()
    open(ev_path, "w").write(blob[: len(blob) // 2])
    assert sweep_top.main([ev_path]) == 0


def test_ledger_view_settled_vs_in_flight(tmp_path, capsys):
    from multidisttorch_tpu.hpo.ledger import SweepLedger

    out_dir = str(tmp_path / "sweep")
    led = SweepLedger(out_dir)
    led.attempt_start(0, "aaaa", 1)
    led.attempt_end(0, "aaaa", 1, "completed", summary={"steps": 8})
    led.attempt_start(1, "bbbb", 1)
    led.attempt_end(1, "bbbb", 1, "retrying", error="boom")
    led.attempt_start(1, "bbbb", 2)  # in flight: no end record
    ledger_view = _load_tool("ledger_view")
    assert ledger_view.main([out_dir]) == 0
    out = capsys.readouterr().out
    assert "SETTLED" in out and "IN-FLIGHT" in out
    assert "#1:ok" in out
    assert "#1:retry -> #2:run" in out


def test_sweep_top_missing_file_errors(tmp_path, capsys):
    sweep_top = _load_tool("sweep_top")
    assert sweep_top.main([str(tmp_path / "nope")]) == 1


def test_sweep_top_json_snapshot(tmp_path, capsys):
    """--json: machine-readable one-shot of the same fold (ISSUE 4
    satellite) — CI consumes this instead of screen-scraping."""
    tdir, _paths = _demo_events(tmp_path)
    capsys.readouterr()  # drain the demo sweep's own log lines
    sweep_top = _load_tool("sweep_top")
    assert sweep_top.main([tdir, "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["done"] is True
    assert snap["goodput"] is not None
    t0 = snap["trials"]["0"]
    assert t0["attempts"] == 2 and t0["status"] == "completed"
    # Device books folded off the event stream: cost record + memory
    # watermark per series key.
    assert snap["device_books"]
    book = next(iter(snap["device_books"].values()))
    assert book.get("flops_per_lane_step") or book.get("peak_bytes")


def test_ledger_view_json_snapshot(tmp_path, capsys):
    from multidisttorch_tpu.hpo.ledger import SweepLedger

    out_dir = str(tmp_path / "sweep")
    led = SweepLedger(out_dir)
    led.attempt_start(0, "aaaa", 1)
    led.attempt_end(0, "aaaa", 1, "completed", summary={"steps": 8})
    led.attempt_start(1, "bbbb", 1)
    ledger_view = _load_tool("ledger_view")
    assert ledger_view.main([out_dir, "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["configs"] == 2
    assert snap["settled"] == 1 and snap["in_flight"] == 1
    assert snap["by_config"]["aaaa"]["attempts"][0]["status"] == "completed"


# -- chaos harness telemetry block (trace acceptance) ------------------


@pytest.mark.chaos
def test_chaos_harness_traces_every_fault(tmp_path):
    from multidisttorch_tpu.faults.harness import run_chaos_bench

    report = run_chaos_bench(
        str(tmp_path / "chaos"), trials=3, epochs=2, include_preempt=False
    )
    tel = report["telemetry"]
    assert tel["all_faults_traced"]
    assert tel["trace_monotonic"]
    assert tel["faults_fired"] > 0
    assert tel["events_recorded"] > 0
    assert os.path.exists(tel["trace"])
    # Telemetry is globally off again after the harness returns.
    assert telemetry.get_bus() is None
