"""Train-step tests: DDP-equivalence across a submesh, loss decrease,
eval/sample contracts. Parity targets /root/reference/vae-hpo.py:61-131."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.steps import (
    create_train_state,
    make_eval_step,
    make_multi_step,
    make_sample_step,
    make_train_step,
)


def _synthetic_batch(rng: np.random.Generator, n: int) -> jnp.ndarray:
    """MNIST-shaped structured data: blurry blobs in [0,1], learnable."""
    centers = rng.integers(6, 22, size=(n, 2))
    yy, xx = np.mgrid[0:28, 0:28]
    imgs = np.exp(
        -((yy[None] - centers[:, 0, None, None]) ** 2
          + (xx[None] - centers[:, 1, None, None]) ** 2) / 20.0
    ).astype(np.float32)
    return jnp.asarray(imgs.reshape(n, 784))


def test_grad_parity_submesh_vs_single_device():
    # The DDP-equivalence property: one step on a 4-device submesh with
    # the batch sharded must produce the same new params as one step on
    # a 1-device group with the full batch (the reference relies on the
    # same property of DDP's all-reduce, vae-hpo.py:130).
    model = VAE(hidden_dim=32, latent_dim=8)
    tx = optax.adam(1e-3)
    big = setup_groups(2)[0]      # 4 devices
    small = setup_groups(8)[0]    # 1 device
    rng = np.random.default_rng(0)
    batch = _synthetic_batch(rng, 32)
    key = jax.random.key(0)

    s_big = create_train_state(big, model, tx, jax.random.key(7))
    s_small = create_train_state(small, model, tx, jax.random.key(7))
    step_big = make_train_step(big, model, tx)
    step_small = make_train_step(small, model, tx)

    s_big, m_big = step_big(s_big, batch, key)
    s_small, m_small = step_small(s_small, batch, key)

    assert float(m_big["loss_sum"]) == pytest.approx(
        float(m_small["loss_sum"]), rel=1e-4
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        ),
        s_big.params,
        s_small.params,
    )


def test_loss_decreases():
    # The reference's de-facto integration test: decreasing printed loss
    # (vae-hpo.py:87-92). 60 steps on structured synthetic data.
    model = VAE(hidden_dim=64, latent_dim=8)
    tx = optax.adam(1e-3)
    trial = setup_groups(2)[1]
    state = create_train_state(trial, model, tx, jax.random.key(0))
    step = make_train_step(trial, model, tx)
    rng = np.random.default_rng(1)
    losses = []
    for i in range(60):
        batch = _synthetic_batch(rng, 64)
        state, metrics = step(state, batch, jax.random.fold_in(jax.random.key(1), i))
        losses.append(float(metrics["loss_sum"]) / 64)
    assert np.mean(losses[-10:]) < 0.5 * np.mean(losses[:5])
    assert int(state.step) == 60


def test_beta_changes_training_loss():
    model = VAE(hidden_dim=32, latent_dim=8)
    tx = optax.adam(1e-3)
    trial = setup_groups(8)[2]
    batch = _synthetic_batch(np.random.default_rng(2), 16)
    key = jax.random.key(3)
    s1 = create_train_state(trial, model, tx, jax.random.key(4))
    s2 = create_train_state(trial, model, tx, jax.random.key(4))
    _, m1 = make_train_step(trial, model, tx, beta=1.0)(s1, batch, key)
    _, m2 = make_train_step(trial, model, tx, beta=4.0)(s2, batch, key)
    assert float(m2["loss_sum"]) > float(m1["loss_sum"])


def test_eval_step_returns_recon_probs():
    model = VAE(hidden_dim=32, latent_dim=8)
    tx = optax.adam(1e-3)
    trial = setup_groups(2)[0]
    state = create_train_state(trial, model, tx, jax.random.key(0))
    ev = make_eval_step(trial, model)
    batch = _synthetic_batch(np.random.default_rng(3), 16)
    out = ev(state, batch)
    assert out["recon"].shape == (16, 784)
    probs = np.asarray(out["recon"])
    assert probs.min() >= 0.0 and probs.max() <= 1.0
    assert np.isfinite(float(out["loss_sum"]))


def test_masked_eval_covers_every_row_exactly():
    # Full-test-set parity (reference test(), vae-hpo.py:101-105): the
    # pad-and-mask eval over ceil(n/batch) padded batches must equal a
    # dense unmasked eval over all n rows — including n < batch_size.
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.data.sampler import EvalDataIterator

    model = VAE(hidden_dim=32, latent_dim=8)
    tx = optax.adam(1e-3)
    trial = setup_groups(2)[0]
    state = create_train_state(trial, model, tx, jax.random.key(0))
    ev = make_eval_step(trial, model, with_recon=False, masked=True)

    for n_rows in (20, 5):  # 20 = 2.5 batches of 8; 5 < one batch
        data = synthetic_mnist(n_rows, seed=7)
        it = EvalDataIterator(data, trial, batch_size=8)
        assert it.num_batches == -(-n_rows // 8)
        total = None
        for batch, w in it.batches():
            out = ev(state, batch, w)
            total = out["loss_sum"] if total is None else total + out["loss_sum"]
        # dense reference: all rows in one unmasked batch on a 1-device
        # group (no divisibility constraint there)
        dense_trial = setup_groups(8)[0]
        dense_state = create_train_state(
            dense_trial, model, tx, jax.random.key(0)
        )
        dense_ev = make_eval_step(dense_trial, model, with_recon=False)
        dense = dense_ev(dense_state, jnp.asarray(data.images))
        np.testing.assert_allclose(
            float(total), float(dense["loss_sum"]), rtol=2e-5
        )


def test_sample_step_shape_and_range():
    model = VAE(hidden_dim=32, latent_dim=8)
    tx = optax.adam(1e-3)
    trial = setup_groups(4)[3]
    state = create_train_state(trial, model, tx, jax.random.key(0))
    sample = make_sample_step(trial, model, num_samples=64)
    imgs = np.asarray(sample(state, jax.random.key(9)))
    # Reference dumps randn(64, 20) -> decode -> 64 images
    # (vae-hpo.py:163-170).
    assert imgs.shape == (64, 784)
    assert imgs.min() >= 0.0 and imgs.max() <= 1.0


def test_multi_step_matches_sequential_steps():
    # The scan-fused K-step dispatch must be numerically equivalent to K
    # individual dispatches driven by the same per-step keys.
    model = VAE(hidden_dim=32, latent_dim=8)
    tx = optax.adam(1e-3)
    trial = setup_groups(2)[0]
    rng = np.random.default_rng(5)
    batches = jnp.stack([_synthetic_batch(rng, 16) for _ in range(4)])
    key = jax.random.key(11)

    s_seq = create_train_state(trial, model, tx, jax.random.key(12))
    step = make_train_step(trial, model, tx)
    seq_losses = []
    for r in jax.random.split(key, 4):
        s_seq, m = step(s_seq, batches[len(seq_losses)], r)
        seq_losses.append(float(m["loss_sum"]))

    s_multi = create_train_state(trial, model, tx, jax.random.key(12))
    multi = make_multi_step(trial, model, tx)
    s_multi, metrics = multi(s_multi, batches, key)

    assert metrics["loss_sum"].shape == (4,)
    np.testing.assert_allclose(
        np.asarray(metrics["loss_sum"]), seq_losses, rtol=1e-5
    )
    assert int(s_multi.step) == 4
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        ),
        s_multi.params,
        s_seq.params,
    )


def test_multi_step_batch_sharded_over_data_axis():
    # The stacked (K, B, ...) batch shards dim 1 over the submesh's data
    # axis; result must match a 1-device group run bit-for-bit in math.
    model = VAE(hidden_dim=32, latent_dim=8)
    tx = optax.adam(1e-3)
    big = setup_groups(2)[0]   # 4 devices
    one = setup_groups(8)[0]   # 1 device
    rng = np.random.default_rng(6)
    batches = jnp.stack([_synthetic_batch(rng, 16) for _ in range(3)])
    key = jax.random.key(13)

    outs = []
    for trial in (big, one):
        s = create_train_state(trial, model, tx, jax.random.key(14))
        s, metrics = make_multi_step(trial, model, tx)(s, batches, key)
        outs.append(np.asarray(metrics["loss_sum"]))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-4)


def test_concurrent_trials_independent_results():
    # Two trials with different hyperparams on disjoint submeshes must
    # produce results identical to running each alone (no cross-trial
    # interference) — the property the reference gets from disjoint
    # communicators (example-subgroup.py:25-33).
    model = VAE(hidden_dim=32, latent_dim=8)
    trials = setup_groups(2)
    batch = _synthetic_batch(np.random.default_rng(4), 32)
    key = jax.random.key(5)

    def run_alone(trial, lr):
        tx = optax.adam(lr)
        s = create_train_state(trial, model, tx, jax.random.key(6))
        step = make_train_step(trial, model, tx)
        for i in range(5):
            s, m = step(s, batch, jax.random.fold_in(key, i))
        return float(m["loss_sum"])

    alone = [run_alone(t, lr) for t, lr in zip(trials, [1e-3, 3e-3])]

    # interleaved dispatch of both trials
    txs = [optax.adam(1e-3), optax.adam(3e-3)]
    states = [
        create_train_state(t, model, tx, jax.random.key(6))
        for t, tx in zip(trials, txs)
    ]
    steps = [make_train_step(t, model, tx_) for t, tx_ in zip(trials, txs)]
    last = [None, None]
    for i in range(5):
        for j in range(2):
            states[j], m = steps[j](states[j], batch, jax.random.fold_in(key, i))
            last[j] = float(m["loss_sum"])
    assert last[0] == pytest.approx(alone[0], rel=1e-5)
    assert last[1] == pytest.approx(alone[1], rel=1e-5)


def test_remat_training_is_numerically_identical():
    # jax.checkpoint recomputes activations in the backward pass; the
    # optimizer trajectory must not change at all (same grads, same
    # updates) — only the memory/FLOPs schedule does.
    model = VAE(hidden_dim=32, latent_dim=8)
    (trial,) = setup_groups(1)
    batch = _synthetic_batch(np.random.default_rng(9), 16)
    key = jax.random.key(3)

    def run(remat):
        tx = optax.adam(1e-3)
        s = create_train_state(trial, model, tx, jax.random.key(1))
        step = make_train_step(trial, model, tx, remat=remat)
        losses = []
        for i in range(3):
            s, m = step(s, batch, jax.random.fold_in(key, i))
            losses.append(float(m["loss_sum"]))
        return losses, s

    plain_losses, plain_state = run(False)
    remat_losses, remat_state = run(True)
    np.testing.assert_allclose(plain_losses, remat_losses, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        plain_state.params,
        remat_state.params,
    )


def test_grad_accum_vae_trains_and_keeps_loss_semantics():
    # grad_accum=4: activation memory drops to a quarter-batch; the
    # logged loss_sum must still be the whole batch's summed loss (the
    # reference logging contract) and training must decrease it.
    model = VAE(hidden_dim=32, latent_dim=8)
    (trial,) = setup_groups(1)
    tx = optax.adam(1e-3)
    state = create_train_state(trial, model, tx, jax.random.key(0))
    step = make_train_step(trial, model, tx, grad_accum=4)
    batch = _synthetic_batch(np.random.default_rng(11), 16)
    key = jax.random.key(4)
    losses = []
    for i in range(6):
        state, m = step(state, batch, jax.random.fold_in(key, i))
        losses.append(float(m["loss_sum"]))
    assert losses[-1] < losses[0]
    # per-sample scale sanity: summed loss / batch is in the ELBO range
    assert 20.0 < losses[0] / 16 < 2000.0


def test_grad_accum_rejects_indivisible_batch():
    model = VAE(hidden_dim=16, latent_dim=4)
    (trial,) = setup_groups(1)
    tx = optax.adam(1e-3)
    state = create_train_state(trial, model, tx, jax.random.key(0))
    step = make_train_step(trial, model, tx, grad_accum=3)
    batch = _synthetic_batch(np.random.default_rng(0), 16)  # 16 % 3 != 0
    with pytest.raises(ValueError, match="grad_accum"):
        step(state, batch, jax.random.key(1))


def test_classifier_grad_accum_matches_full_batch_exactly():
    # Deterministic forward: accumulated microbatch grads == full-batch
    # grads, so one update from either path lands on the same params.
    from multidisttorch_tpu.models.resnet import ResNet
    from multidisttorch_tpu.train.classifier import (
        create_classifier_state,
        make_classifier_train_step,
    )

    model = ResNet(stage_sizes=(1,), base_channels=8, image_hw=16)
    (trial,) = setup_groups(1)
    tx = optax.adam(1e-3)
    rng = np.random.default_rng(3)
    images = jnp.asarray(rng.uniform(0, 1, (16, 16 * 16 * 3)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 10, (16,)).astype(np.int32))

    outs = {}
    for accum in (1, 4):
        state = create_classifier_state(trial, model, tx, jax.random.key(0))
        step = make_classifier_train_step(trial, model, tx, grad_accum=accum)
        state, m = step(state, images, labels)
        outs[accum] = (jax.device_get(state.params), float(m["loss"]),
                       float(m["accuracy"]))
    assert outs[1][1] == pytest.approx(outs[4][1], rel=1e-5)
    assert outs[1][2] == outs[4][2]
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        outs[1][0],
        outs[4][0],
    )
