"""Scenario zoo (ISSUE 18): named, seeded, bit-reproducible workload
scenarios driving the PRODUCTION scheduler classes with the
control-plane profiler armed. Drills: registry round-trip, seeded
determinism (bit-identical reports modulo wall clock), per-scenario SLO
verdict wiring (honest-tenant judgment for deadline_gaming, dynamic-arm
judgment for fabric scenarios), ctl flight books in every envelope, and
the default-spec bit-identity guarantee (zoo knobs off = zero extra rng
draws)."""

from __future__ import annotations

import pytest

from multidisttorch_tpu.service.loadgen import (
    SCENARIOS,
    LoadSpec,
    run_loadgen,
    run_scenario,
    zoo_names,
)
from multidisttorch_tpu.telemetry import ctlprof

pytestmark = pytest.mark.ctlprof

# Small-N: the zoo's contracts (determinism, SLO wiring, books) hold at
# any N; CI's dedicated job replays larger N via bench --zoo.
N = 1500


@pytest.fixture(autouse=True)
def _no_leaked_profiler():
    ctlprof.disable()
    yield
    ctlprof.disable()


# -- registry ----------------------------------------------------------


def test_registry_round_trip():
    names = zoo_names()
    assert names == sorted(names)
    assert set(names) == set(SCENARIOS)
    # The promoted fabric drills ride in the same registry:
    assert {"coordinated_burst", "split_storm"} <= set(names)
    assert {
        "diurnal_wave", "tenant_burst", "deadline_gaming",
        "pipeline_whale_shrimp", "dataset_thrash",
    } <= set(names)
    for name in names:
        ent = SCENARIOS[name]
        assert ent["kind"] in ("pool", "fabric")
        if ent["kind"] == "pool":
            assert ent["latency_threshold_s"] > 0
            assert 0 < ent["latency_objective"] <= 1
            assert 0 < ent["deadline_objective"] <= 1


def test_unknown_scenario_raises():
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("no_such_scenario")


# -- seeded determinism ------------------------------------------------


_WALL_KEYS = frozenset(
    {"wall_s", "submissions_per_wall_s", "ctl_passes_per_s"}
)


def _scrub(obj):
    """Drop wall-clock-derived fields; everything left must be
    bit-identical across reruns of the same (scenario, seed, N)."""
    if isinstance(obj, dict):
        return {
            k: _scrub(v) for k, v in obj.items() if k not in _WALL_KEYS
        }
    if isinstance(obj, list):
        return [_scrub(v) for v in obj]
    return obj


@pytest.mark.parametrize(
    "name", ["diurnal_wave", "deadline_gaming", "pipeline_whale_shrimp"]
)
def test_scenario_bit_reproducible(name):
    a = run_scenario(name, n_submissions=N, ctl=False)
    b = run_scenario(name, n_submissions=N, ctl=False)
    assert _scrub(a["report"]) == _scrub(b["report"])
    assert _scrub(a["slo"]) == _scrub(b["slo"])
    assert a["gates"] == b["gates"]


def test_seed_changes_workload():
    a = run_scenario("tenant_burst", n_submissions=N, ctl=False, seed=0)
    b = run_scenario("tenant_burst", n_submissions=N, ctl=False, seed=1)
    assert _scrub(a["report"]) != _scrub(b["report"])


def test_zoo_knobs_off_keep_default_spec_bit_identical():
    """Every zoo knob at its off-value must consume ZERO extra rng
    draws — the pre-zoo default workload replays bit-identically, so
    every historical loadgen baseline stays comparable."""
    base = run_loadgen(LoadSpec(n_submissions=800, seed=7))
    explicit = run_loadgen(LoadSpec(
        n_submissions=800, seed=7,
        wave_amp=0.0, burst_share=0.0, burst_tenant=None,
        gamer_tenant=None, whale_frac=0.0, thrash_buckets=0,
    ))
    assert _scrub(base) == _scrub(explicit)


# -- SLO verdict wiring ------------------------------------------------


def test_pool_scenario_slo_wiring_and_books():
    assert ctlprof.get_ctlprof() is None
    art = run_scenario("diurnal_wave", n_submissions=N)
    # run_scenario armed its OWN profiler and retired it:
    assert ctlprof.get_ctlprof() is None
    ent = SCENARIOS["diurnal_wave"]
    thr = ent["latency_threshold_s"]
    slos = art["slo"]["slos"]
    assert f"placement_p_{int(thr)}s" in slos
    assert "deadline_hit_rate" in slos
    # Exact offline evaluation — thresholds sit ON bucket bounds:
    assert all(s["exact"] for s in slos.values())
    assert art["gates"]["slo_exact"]
    assert set(art["gates"]) == {"zero_lost", "slo_met", "slo_exact"}
    # Fairness is informational, never a zoo gate (scenarios skew
    # offered demand on purpose):
    assert "fairness_max_abs_ratio_error" in art["headline"]
    # Every envelope carries per-phase ctl flight books:
    ctl = art["ctl"]
    assert ctl["enabled"] is True
    assert ctl["passes"]["count"] > 0
    for ph in ("bin_pack_scan", "edf_insert", "fair_share_pick"):
        blk = ctl["phases"][ph]
        assert blk["calls"] > 0
        lo, hi = blk["bucket_err"]["p99_s"]
        assert lo <= blk["p99_s"] <= hi
    assert ctl["work_touched"]["examined"] > 0
    assert art["ctl_trace"]["traceEvents"]


def test_deadline_gaming_judges_honest_tenants_only():
    art = run_scenario("deadline_gaming", n_submissions=N, ctl=False)
    dl = art["report"]["deadline"]
    # The report banks the honest/gamer split; the gamer's
    # self-inflicted tight-slack misses must not sink the verdict.
    assert dl["honest"]["completed_tagged"] > 0
    assert dl["gamer"]["completed_tagged"] > 0
    honest_rate = dl["honest"]["hits"] / dl["honest"]["completed_tagged"]
    gamer_rate = dl["gamer"]["hits"] / dl["gamer"]["completed_tagged"]
    assert honest_rate > gamer_rate  # EDF contains the gamer
    ev = art["slo"]["slos"]["deadline_hit_rate"]
    assert ev["total"] == dl["honest"]["completed_tagged"]
    assert ev["total"] - ev["bad"] == dl["honest"]["hits"]


def test_fabric_scenario_judged_on_dynamic_arm():
    art = run_scenario("split_storm", n_submissions=800)
    assert art["kind"] == "fabric"
    # The static arm is the designed-to-degrade control; the verdict
    # reads the dynamic arm and the drill's relative gates.
    assert art["slo"]["met"] == art["slo"]["dynamic"]["met"]
    assert "static" in art["slo"]
    assert "p99_within_10pct_of_static" in art["gates"]
    assert art["gates"]["zero_lost"]
    # Fabric-only phases landed in the books:
    assert art["ctl"]["enabled"]
    assert art["ctl"]["passes"]["count"] > 0


def test_whale_scenario_places_vector_shapes():
    from multidisttorch_tpu.service.loadgen import _Sim

    ent = SCENARIOS["pipeline_whale_shrimp"]
    kw = dict(ent["overrides"])
    kw.update(n_submissions=N, seed=0)
    sim = _Sim(LoadSpec(**kw))
    report = sim.run()
    whales = [st for st in sim.trials.values() if st.entry.sizes]
    assert whales, "whale_frac > 0 produced no vector submissions"
    # All-or-nothing vector placements drained to completion — the
    # multi-block alloc + block-by-block free path carried real load.
    assert all(st.done_at is not None for st in whales)
    assert report["zero_lost"]
