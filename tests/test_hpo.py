"""HPO driver tests: concurrency without barriers, per-trial outputs,
parity with /root/reference/vae-hpo.py's trial dispatch."""

import json
import os

import numpy as np
import pytest

from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
from multidisttorch_tpu.parallel.mesh import setup_groups


def _small_cfg(trial_id, **kw):
    defaults = dict(
        trial_id=trial_id,
        epochs=1,
        batch_size=16,
        hidden_dim=32,
        latent_dim=8,
        log_interval=100,
    )
    defaults.update(kw)
    return TrialConfig(**defaults)


@pytest.fixture(scope="module")
def data():
    return synthetic_mnist(128, seed=0), synthetic_mnist(32, seed=1)


def test_two_concurrent_trials(tmp_path, data):
    train, test = data
    configs = [_small_cfg(0), _small_cfg(1, lr=3e-3)]
    results = run_hpo(
        configs, train, test, out_dir=str(tmp_path), verbose=False
    )
    assert [r.trial_id for r in results] == [0, 1]
    for r in results:
        assert r.steps == 8  # 128/16 batches x 1 epoch
        assert np.isfinite(r.final_train_loss)
        assert np.isfinite(r.final_test_loss)
        assert r.wall_s > 0


def test_unequal_epochs_no_barrier(tmp_path, data):
    # The reference's sweep trains trial g for epochs+g epochs and then
    # blocks everyone on a world barrier (Q3). Here unequal trials must
    # complete with their own step counts.
    train, test = data
    configs = [_small_cfg(0, epochs=1), _small_cfg(1, epochs=3)]
    results = run_hpo(
        configs, train, None, out_dir=str(tmp_path), verbose=False,
        save_images=False,
    )
    assert results[0].steps == 8
    assert results[1].steps == 24


def test_per_trial_output_dirs_no_collision(tmp_path, data):
    # Q4 fix: outputs keyed by trial id, never by group-local rank.
    train, test = data
    configs = [_small_cfg(0), _small_cfg(1)]
    results = run_hpo(configs, train, test, out_dir=str(tmp_path), verbose=False)
    dirs = [r.out_dir for r in results]
    assert len(set(dirs)) == 2
    for r in results:
        files = os.listdir(r.out_dir)
        assert "metrics.json" in files
        assert "state.msgpack" in files
        assert any(f.startswith("reconstruction_") for f in files)
        assert any(f.startswith("sample_") for f in files)
        with open(os.path.join(r.out_dir, "metrics.json")) as f:
            metrics = json.load(f)
        assert metrics["trial_id"] == r.trial_id
        assert len(metrics["history"]) == 1


def test_trial_config_generalizes_hpo_knobs(tmp_path, data):
    # Q7: per-trial lr and beta actually take effect (different results).
    train, _ = data
    configs = [
        _small_cfg(0, lr=1e-3, beta=1.0, epochs=1),
        _small_cfg(1, lr=1e-3, beta=8.0, epochs=1),
    ]
    results = run_hpo(
        configs, train, None, out_dir=str(tmp_path), verbose=False,
        save_images=False, save_checkpoints=False,
    )
    assert results[0].final_train_loss != results[1].final_train_loss


def test_explicit_groups_and_mismatch(tmp_path, data):
    train, _ = data
    groups = setup_groups(4)
    with pytest.raises(ValueError, match="configs but"):
        run_hpo([_small_cfg(0)], train, None, groups=groups)


def test_shard_across_trials_legacy_mode(tmp_path, data):
    train, _ = data
    configs = [_small_cfg(0), _small_cfg(1)]
    results = run_hpo(
        configs, train, None, out_dir=str(tmp_path),
        shard_across_trials=True, verbose=False,
        save_images=False, save_checkpoints=False,
    )
    # each trial sees half the 128 rows -> 4 batches of 16
    assert all(r.steps == 4 for r in results)


def test_logging_parity_format(tmp_path, data, capsys):
    # Reference log lines: "Train Epoch: ..." / "====> Epoch: ... Average
    # loss: ..." / "====> Test set loss: ..." (vae-hpo.py:76-92,118-119).
    train, test = data
    run_hpo(
        [_small_cfg(0, log_interval=4)], train, test,
        groups=setup_groups(1), out_dir=str(tmp_path),
        save_images=False, save_checkpoints=False,
    )
    out = capsys.readouterr().out
    assert "Train Epoch: 1 [" in out
    assert "====> Epoch: 1 Average loss:" in out
    assert "====> Test set loss:" in out
    assert "[0:0]" in out  # provenance prefix
