"""HPO driver tests: concurrency without barriers, per-trial outputs,
parity with /root/reference/vae-hpo.py's trial dispatch."""

import json
import os

import numpy as np
import pytest

from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
from multidisttorch_tpu.parallel.mesh import setup_groups


def _small_cfg(trial_id, **kw):
    defaults = dict(
        trial_id=trial_id,
        epochs=1,
        batch_size=16,
        hidden_dim=32,
        latent_dim=8,
        log_interval=100,
    )
    defaults.update(kw)
    return TrialConfig(**defaults)


@pytest.fixture(scope="module")
def data():
    return synthetic_mnist(128, seed=0), synthetic_mnist(32, seed=1)


def test_two_concurrent_trials(tmp_path, data):
    train, test = data
    configs = [_small_cfg(0), _small_cfg(1, lr=3e-3)]
    results = run_hpo(
        configs, train, test, out_dir=str(tmp_path), verbose=False
    )
    assert [r.trial_id for r in results] == [0, 1]
    for r in results:
        assert r.steps == 8  # 128/16 batches x 1 epoch
        assert np.isfinite(r.final_train_loss)
        assert np.isfinite(r.final_test_loss)
        assert r.wall_s > 0


def test_eval_covers_full_test_set_even_smaller_than_batch(tmp_path, data):
    # Round-1 gap (VERDICT missing #2): eval silently dropped the
    # non-batch-multiple tail and skipped test sets smaller than one
    # batch. Reference parity requires every test row to score
    # (vae-hpo.py:101-105). 10 test rows < batch_size 16 must still
    # produce a finite test loss (batching-independence of the masked
    # average itself is asserted in test_train.py).
    train, _ = data
    tiny_test = synthetic_mnist(10, seed=3)
    r_small = run_hpo(
        [_small_cfg(0)],
        train,
        tiny_test,
        out_dir=str(tmp_path / "a"),
        verbose=False,
    )[0]
    assert np.isfinite(r_small.final_test_loss)
    # Same rows, different batch size: the per-row masked coverage makes
    # the reported average independent of batching.
    r_big_batch = run_hpo(
        [_small_cfg(0, batch_size=8)],
        train,
        tiny_test,
        out_dir=str(tmp_path / "b"),
        verbose=False,
    )[0]
    assert np.isfinite(r_big_batch.final_test_loss)


def test_unequal_epochs_no_barrier(tmp_path, data):
    # The reference's sweep trains trial g for epochs+g epochs and then
    # blocks everyone on a world barrier (Q3). Here unequal trials must
    # complete with their own step counts.
    train, test = data
    configs = [_small_cfg(0, epochs=1), _small_cfg(1, epochs=3)]
    results = run_hpo(
        configs, train, None, out_dir=str(tmp_path), verbose=False,
        save_images=False,
    )
    assert results[0].steps == 8
    assert results[1].steps == 24


def test_per_trial_output_dirs_no_collision(tmp_path, data):
    # Q4 fix: outputs keyed by trial id, never by group-local rank.
    train, test = data
    configs = [_small_cfg(0), _small_cfg(1)]
    results = run_hpo(configs, train, test, out_dir=str(tmp_path), verbose=False)
    dirs = [r.out_dir for r in results]
    assert len(set(dirs)) == 2
    for r in results:
        files = os.listdir(r.out_dir)
        assert "metrics.json" in files
        assert "state.msgpack" in files
        assert any(f.startswith("reconstruction_") for f in files)
        assert any(f.startswith("sample_") for f in files)
        with open(os.path.join(r.out_dir, "metrics.json")) as f:
            metrics = json.load(f)
        assert metrics["trial_id"] == r.trial_id
        assert len(metrics["history"]) == 1
        # Data provenance (round-4): a synthetic-data trial must say so
        # in its own recorded metrics, not just in bench artifacts.
        assert metrics["dataset"] == "synthetic-mnist"
        assert metrics["dataset_synthetic"] is True
        assert r.dataset == "synthetic-mnist"
        assert r.dataset_synthetic is True


def test_run_hpo_with_model_parallel_tp_shardings(tmp_path, data):
    # Round-4: within-trial weight sharding through the driver itself —
    # model_parallel carves 2-D submeshes, param_shardings_builder maps
    # each trial to its sharding tree, and losses must match the pure-DP
    # sweep (sharding never changes the math).
    from multidisttorch_tpu.models.vae import vae_tp_shardings

    train, test = data
    kw = dict(
        train_data=train, test_data=test, verbose=False, save_images=False,
    )
    r_dp = run_hpo(
        [_small_cfg(0)], out_dir=str(tmp_path / "dp"), **kw
    )[0]
    r_tp = run_hpo(
        [_small_cfg(0)],
        out_dir=str(tmp_path / "tp"),
        model_parallel=2,
        param_shardings_builder=lambda trial, model: vae_tp_shardings(trial),
        **kw,
    )[0]
    assert np.isclose(r_tp.final_train_loss, r_dp.final_train_loss, rtol=2e-4)
    assert np.isclose(r_tp.final_test_loss, r_dp.final_test_loss, rtol=2e-4)


def test_run_hpo_model_parallel_rejects_user_groups(tmp_path, data):
    from multidisttorch_tpu.parallel.mesh import setup_groups

    train, test = data
    with pytest.raises(ValueError, match="model_parallel"):
        run_hpo(
            [_small_cfg(0)], train, test, groups=setup_groups(1),
            model_parallel=2, out_dir=str(tmp_path), verbose=False,
        )


def test_balanced_assignment_beats_round_robin():
    # VERDICT r3 weak #9: multi-controller scheduling must not leave a
    # freed submesh idle behind a statically long queue. The
    # deterministic least-loaded rule cuts the predicted makespan vs
    # round-robin whenever epoch counts differ.
    from multidisttorch_tpu.hpo.driver import (
        balanced_assignment,
        predicted_cost,
    )

    costs = [4, 1, 1, 1]
    assign = balanced_assignment(costs, 2)
    assert assign == [0, 1, 1, 1]
    loads = [sum(c for c, g in zip(costs, assign) if g == j) for j in (0, 1)]
    assert max(loads) == 4  # round-robin would be 5 (groups [4,1] / [1,1])
    # determinism: pure function of its inputs
    assert balanced_assignment(costs, 2) == assign
    # equal costs degrade to round-robin (multihost tests rely on this)
    assert balanced_assignment([1, 1, 1], 2) == [0, 1, 0]
    # predicted cost scales with the duration knobs
    a = predicted_cost(_small_cfg(0, epochs=2, batch_size=16), 128)
    b = predicted_cost(_small_cfg(0, epochs=1, batch_size=16), 128)
    assert a == 2 * b


def test_train_epoch_host_syncs_are_o1(tmp_path, data):
    # VERDICT r3 item 8: per-epoch metric fetches must be O(1), not
    # O(batches) — on-device accumulation, one float() per epoch for the
    # train average and one for the test average, plus one per log line.
    train, test = data
    r_quiet = run_hpo(
        [_small_cfg(0, epochs=2)],
        train,
        test,
        out_dir=str(tmp_path / "q"),
        verbose=False,
        save_images=False,
    )[0]
    # verbose=False: no log-line syncs at all -> exactly 2 per epoch.
    assert r_quiet.host_syncs == 2 * 2

    r_verbose = run_hpo(
        [_small_cfg(0, epochs=1, log_interval=100)],
        train,
        test,
        out_dir=str(tmp_path / "v"),
        verbose=True,
        save_images=False,
    )[0]
    # 8 batches, log_interval=100 -> one log line (batch 0) + 2 fetches.
    assert r_verbose.host_syncs <= 1 + 2


def test_sampled_eval_config_knob(tmp_path, data):
    # eval_sampled=True threads the eval RNG end-to-end through the
    # driver; the reported test loss differs from posterior-mean eval of
    # the same trained params (same seeds/config otherwise).
    train, test = data
    r_mean = run_hpo(
        [_small_cfg(0)], train, test,
        out_dir=str(tmp_path / "m"), verbose=False, save_images=False,
    )[0]
    r_sampled = run_hpo(
        [_small_cfg(0, eval_sampled=True)], train, test,
        out_dir=str(tmp_path / "s"), verbose=False, save_images=False,
    )[0]
    assert np.isfinite(r_sampled.final_test_loss)
    assert r_sampled.final_test_loss != r_mean.final_test_loss
    # identical training: the train path is untouched by the eval knob
    assert r_sampled.final_train_loss == pytest.approx(
        r_mean.final_train_loss, rel=1e-6
    )


def test_trial_config_generalizes_hpo_knobs(tmp_path, data):
    # Q7: per-trial lr and beta actually take effect (different results).
    train, _ = data
    configs = [
        _small_cfg(0, lr=1e-3, beta=1.0, epochs=1),
        _small_cfg(1, lr=1e-3, beta=8.0, epochs=1),
    ]
    results = run_hpo(
        configs, train, None, out_dir=str(tmp_path), verbose=False,
        save_images=False, save_checkpoints=False,
    )
    assert results[0].final_train_loss != results[1].final_train_loss


def test_explicit_groups_and_mismatch(tmp_path, data):
    train, _ = data
    groups = setup_groups(4)
    with pytest.raises(ValueError, match="configs but"):
        run_hpo([_small_cfg(0)], train, None, groups=groups)


def test_shard_across_trials_legacy_mode(tmp_path, data):
    train, _ = data
    configs = [_small_cfg(0), _small_cfg(1)]
    results = run_hpo(
        configs, train, None, out_dir=str(tmp_path),
        shard_across_trials=True, verbose=False,
        save_images=False, save_checkpoints=False,
    )
    # each trial sees half the 128 rows -> 4 batches of 16
    assert all(r.steps == 4 for r in results)


def test_logging_parity_format(tmp_path, data, capsys):
    # Reference log lines: "Train Epoch: ..." / "====> Epoch: ... Average
    # loss: ..." / "====> Test set loss: ..." (vae-hpo.py:76-92,118-119).
    train, test = data
    run_hpo(
        [_small_cfg(0, log_interval=4)], train, test,
        groups=setup_groups(1), out_dir=str(tmp_path),
        save_images=False, save_checkpoints=False,
    )
    out = capsys.readouterr().out
    assert "Train Epoch: 1 [" in out
    assert "====> Epoch: 1 Average loss:" in out
    assert "====> Test set loss:" in out
    assert "[0:0]" in out  # provenance prefix


def test_elastic_more_configs_than_groups(tmp_path, data):
    # The reference hard-binds one trial per group forever
    # (vae-hpo.py:200-202); here 5 configs share 2 submeshes, freed
    # groups picking up queued work.
    train, _ = data
    configs = [_small_cfg(i, epochs=1 + (i % 2)) for i in range(5)]
    results = run_hpo(
        configs, train, None, num_groups=2, out_dir=str(tmp_path),
        verbose=False, save_images=False, save_checkpoints=False,
    )
    assert [r.trial_id for r in results] == [0, 1, 2, 3, 4]
    for r in results:
        assert r.status == "completed"
        assert r.steps == 8 * r.config.epochs
    # both submeshes were used
    assert len({r.group_id for r in results}) == 2


def test_resilient_sweep_isolates_failures(tmp_path, data):
    train, _ = data

    def builder(cfg):
        from multidisttorch_tpu.models.vae import VAE

        if cfg.trial_id == 1:
            raise RuntimeError("boom")
        return VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)

    configs = [_small_cfg(i) for i in range(3)]
    results = run_hpo(
        configs, train, None, num_groups=2, out_dir=str(tmp_path),
        verbose=False, save_images=False, save_checkpoints=False,
        model_builder=builder, resilient=True,
    )
    statuses = {r.trial_id: r.status for r in results}
    assert statuses == {0: "completed", 1: "failed", 2: "completed"}
    failed = next(r for r in results if r.trial_id == 1)
    assert "boom" in failed.error


def test_non_resilient_sweep_raises(tmp_path, data):
    train, _ = data

    def builder(cfg):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        run_hpo(
            [_small_cfg(0)], train, None, out_dir=str(tmp_path),
            verbose=False, save_images=False, save_checkpoints=False,
            model_builder=builder,
        )


def test_resume_continues_from_checkpoint(tmp_path, data):
    train, _ = data
    # phase 1: train 1 epoch with checkpoints ("interrupted" sweep)
    r1 = run_hpo(
        [_small_cfg(0, epochs=1)], train, None, out_dir=str(tmp_path),
        verbose=False, save_images=False,
    )[0]
    assert r1.steps == 8

    # phase 2: same trial, target 3 epochs, resume -> trains only 2 more
    r2 = run_hpo(
        [_small_cfg(0, epochs=3)], train, None, out_dir=str(tmp_path),
        verbose=False, save_images=False, resume=True,
    )[0]
    assert r2.status == "completed"
    assert r2.steps == 24  # cumulative optimizer steps across both runs
    assert len(r2.history) == 3  # epoch-1 record restored + 2 new

    # phase 3: everything done -> skipped entirely
    r3 = run_hpo(
        [_small_cfg(0, epochs=3)], train, None, out_dir=str(tmp_path),
        verbose=False, save_images=False, resume=True,
    )[0]
    assert r3.status == "resumed_complete"
    assert r3.steps == 24


def test_resume_matches_uninterrupted_run(tmp_path, data):
    # Determinism: 1 epoch + resumed 2 == straight 2 epochs, bitwise on
    # the final train loss (same data permutations, same step RNG).
    train, _ = data
    straight = run_hpo(
        [_small_cfg(0, epochs=2)], train, None,
        out_dir=str(tmp_path / "straight"), verbose=False,
        save_images=False,
    )[0]
    run_hpo(
        [_small_cfg(0, epochs=1)], train, None,
        out_dir=str(tmp_path / "resumed"), verbose=False,
        save_images=False,
    )
    resumed = run_hpo(
        [_small_cfg(0, epochs=2)], train, None,
        out_dir=str(tmp_path / "resumed"), verbose=False,
        save_images=False, resume=True,
    )[0]
    assert resumed.final_train_loss == straight.final_train_loss


def test_resume_with_sharded_state_matches_uninterrupted(tmp_path, data):
    # Round-4: the sharded checkpoint/restore path end-to-end through
    # the driver — a TP sweep interrupted after 1 epoch and resumed must
    # match the straight 2-epoch TP sweep bitwise. (The restored state's
    # physical sharding itself is asserted in
    # test_utils.py::test_sharded_state_roundtrip_keeps_sharding; loss
    # equality here can't distinguish sharded from replicated restore —
    # sharding never changes the math by design.)
    from multidisttorch_tpu.models.vae import vae_tp_shardings

    train, _ = data
    kw = dict(
        train_data=train, test_data=None, verbose=False, save_images=False,
        model_parallel=2,
        param_shardings_builder=lambda t, m: vae_tp_shardings(t),
    )
    straight = run_hpo(
        [_small_cfg(0, epochs=2)], out_dir=str(tmp_path / "straight"), **kw
    )[0]
    run_hpo([_small_cfg(0, epochs=1)], out_dir=str(tmp_path / "res"), **kw)
    resumed = run_hpo(
        [_small_cfg(0, epochs=2)], out_dir=str(tmp_path / "res"),
        resume=True, **kw,
    )[0]
    assert resumed.steps == 16
    assert resumed.final_train_loss == straight.final_train_loss


def test_resume_refuses_changed_hyperparameters(tmp_path, data):
    train, _ = data
    run_hpo(
        [_small_cfg(0, epochs=1, lr=1e-3)], train, None,
        out_dir=str(tmp_path), verbose=False, save_images=False,
    )
    with pytest.raises(ValueError, match="different\\s+hyperparameters"):
        run_hpo(
            [_small_cfg(0, epochs=2, lr=1e-2)], train, None,
            out_dir=str(tmp_path), verbose=False, save_images=False,
            resume=True,
        )


def test_elastic_shard_across_trials_partitions_by_group(tmp_path, data):
    # Legacy Q1 sharding under elastic scheduling: shards are keyed by
    # submesh (a valid partition), not by config count.
    train, _ = data
    configs = [_small_cfg(i) for i in range(4)]
    results = run_hpo(
        configs, train, None, num_groups=2, out_dir=str(tmp_path),
        shard_across_trials=True, verbose=False,
        save_images=False, save_checkpoints=False,
    )
    # each group's shard is 64 of 128 rows -> 4 batches of 16 per trial
    assert all(r.steps == 4 for r in results)


def test_checkpoint_write_failure_fails_trial_not_sweep(
    tmp_path, data, monkeypatch
):
    """A failed background checkpoint write must surface as a trial
    failure (not be silently swallowed by the writer thread), and the
    trial must not advertise a checkpoint it never wrote."""
    import multidisttorch_tpu.hpo.driver as drv

    train, _ = data

    real_save = drv.save_state

    def failing_save(state, path, **kw):
        if "trial-1" in path:
            raise OSError("disk full")
        return real_save(state, path, **kw)

    monkeypatch.setattr(drv, "save_state", failing_save)
    configs = [_small_cfg(0), _small_cfg(1)]
    results = run_hpo(
        configs, train, None, out_dir=str(tmp_path), verbose=False,
        save_images=False, resilient=True,
    )
    statuses = {r.trial_id: r.status for r in results}
    assert statuses == {0: "completed", 1: "failed"}
    failed = next(r for r in results if r.trial_id == 1)
    assert "checkpoint write" in failed.error
    assert failed.checkpoint == ""
    ok = next(r for r in results if r.trial_id == 0)
    assert ok.checkpoint and os.path.exists(ok.checkpoint)


def test_checkpoint_files_are_atomic_no_tmp_left(tmp_path, data):
    train, _ = data
    run_hpo(
        [_small_cfg(0)], train, None, out_dir=str(tmp_path),
        verbose=False, save_images=False,
    )
    ckpt_dir = tmp_path / "trial-0"
    names = {p.name for p in ckpt_dir.iterdir()}
    assert "state.msgpack" in names and "state.msgpack.json" in names
    assert not any(n.endswith(".tmp") for n in names)


def test_resume_detects_state_metadata_skew(tmp_path, data):
    """A crash between the state-file and sidecar replaces leaves the
    state one epoch ahead of the metadata; resume must refuse, not
    silently re-train the already-applied epoch."""
    train, _ = data
    run_hpo(
        [_small_cfg(0, epochs=2)], train, None, out_dir=str(tmp_path),
        verbose=False, save_images=False,
    )
    meta_path = tmp_path / "trial-0" / "state.msgpack.json"
    meta = json.loads(meta_path.read_text())
    meta["completed_epochs"] -= 1  # sidecar now one epoch behind the state
    meta["step"] -= 8
    meta_path.write_text(json.dumps(meta))
    with pytest.raises(ValueError, match="skewed"):
        run_hpo(
            [_small_cfg(0, epochs=3)], train, None, out_dir=str(tmp_path),
            verbose=False, save_images=False, resume=True,
        )


def test_fused_steps_sweep_matches_step_count(tmp_path, data):
    # fused_steps > 1 dispatches chunks of K scan-fused steps (plus an
    # unfused tail); step counts, history, and outputs must match the
    # per-step mode's contract. 128/16 = 8 batches, K=3 -> chunks of
    # 3+3, tail of 2.
    train, test = data
    configs = [_small_cfg(0, fused_steps=3, epochs=2), _small_cfg(1, fused_steps=3)]
    results = run_hpo(
        configs, train, test, out_dir=str(tmp_path), verbose=False
    )
    assert results[0].steps == 16 and results[1].steps == 8
    for r in results:
        assert np.isfinite(r.final_train_loss)
        assert len(r.history) == r.config.epochs


def test_fused_steps_loss_decreases(tmp_path, data):
    train, _ = data
    (r,) = run_hpo(
        [_small_cfg(0, fused_steps=4, epochs=6)],
        train,
        None,
        out_dir=str(tmp_path),
        verbose=False,
        save_images=False,
        save_checkpoints=False,
    )
    first = r.history[0]["avg_train_loss"]
    last = r.history[-1]["avg_train_loss"]
    assert last < first


def test_fused_steps_log_cadence_preserved(tmp_path, data, capsys):
    # The batch indices that log in per-step mode must still log when
    # chunked: log_interval=4 with K=3 over 8 batches -> batches 0 and 4.
    train, _ = data
    run_hpo(
        [_small_cfg(0, fused_steps=3, log_interval=4)],
        train,
        None,
        out_dir=str(tmp_path),
        num_groups=1,
        save_images=False,
        save_checkpoints=False,
    )
    out = capsys.readouterr().out
    assert "[0/128" in out and "[64/128" in out


def test_fused_steps_logs_every_interval_when_smaller_than_chunk(
    tmp_path, data, capsys
):
    # log_interval=2 < fused_steps=5 over 8 batches: per-step mode logs
    # batches 0,2,4,6 — the chunked path must log all of them too.
    train, _ = data
    run_hpo(
        [_small_cfg(0, fused_steps=5, log_interval=2)],
        train,
        None,
        out_dir=str(tmp_path),
        num_groups=1,
        save_images=False,
        save_checkpoints=False,
    )
    out = capsys.readouterr().out
    for start in (0, 32, 64, 96):  # batch idx x 16 samples
        assert f"[{start}/128" in out, f"missing log line for sample {start}"


def test_resume_refuses_fused_steps_change_from_legacy_checkpoint(
    tmp_path, data
):
    # A sidecar written before the fused_steps field existed must compare
    # it against the TrialConfig default (1), so resuming with a
    # different value is refused instead of silently re-training under a
    # new RNG stream.
    train, test = data
    cfg = _small_cfg(0)
    run_hpo([cfg], train, test, out_dir=str(tmp_path), num_groups=1,
            verbose=False)
    meta_path = os.path.join(str(tmp_path), "trial-0", "state.msgpack.json")
    with open(meta_path) as f:
        meta = json.load(f)
    del meta["fused_steps"]  # simulate a pre-fused_steps checkpoint
    with open(meta_path, "w") as f:
        json.dump(meta, f)

    with pytest.raises(ValueError, match="fused_steps"):
        run_hpo(
            [_small_cfg(0, fused_steps=4, epochs=2)],
            train,
            test,
            out_dir=str(tmp_path),
            num_groups=1,
            verbose=False,
            resume=True,
        )


def test_profile_dir_writes_trace(tmp_path, data):
    train, _ = data
    prof = tmp_path / "prof"
    run_hpo(
        [_small_cfg(0)],
        train,
        None,
        out_dir=str(tmp_path / "out"),
        num_groups=1,
        verbose=False,
        save_images=False,
        save_checkpoints=False,
        profile_dir=str(prof),
    )
    # jax.profiler writes plugins/profile/<ts>/*.xplane.pb under the dir
    found = list(prof.rglob("*.xplane.pb"))
    assert found, f"no profiler artifacts under {prof}"
