"""VAE model + loss tests, incl. numerical parity with the reference's
torch implementation (/root/reference/vae-hpo.py:19-58).

The parity fixture re-implements the reference architecture in torch
(CPU) inside the test, loads identical weights into both frameworks, and
compares activations, loss values, and gradients on the deterministic
(eps=0) path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multidisttorch_tpu.models.vae import VAE, init_vae_params
from multidisttorch_tpu.ops.losses import (
    bernoulli_recon_sum,
    elbo_loss_sum,
    gaussian_kl_sum,
    softmax_cross_entropy_mean,
)

torch = pytest.importorskip("torch")
import torch.nn.functional as tF  # noqa: E402


def _torch_vae_and_flax_params(rng: np.random.Generator):
    """Build the reference torch VAE and a matching flax param tree."""
    import torch.nn as tnn

    class TorchVAE(tnn.Module):
        # Architecture per /root/reference/vae-hpo.py:19-45.
        def __init__(self):
            super().__init__()
            self.fc1 = tnn.Linear(784, 400)
            self.fc21 = tnn.Linear(400, 20)
            self.fc22 = tnn.Linear(400, 20)
            self.fc3 = tnn.Linear(20, 400)
            self.fc4 = tnn.Linear(400, 784)

        def encode(self, x):
            h = tF.relu(self.fc1(x))
            return self.fc21(h), self.fc22(h)

        def decode(self, z):
            return torch.sigmoid(self.fc4(tF.relu(self.fc3(z))))

    tmodel = TorchVAE()
    flax_params = {}
    with torch.no_grad():
        for name, (din, dout) in {
            "fc1": (784, 400),
            "fc21": (400, 20),
            "fc22": (400, 20),
            "fc3": (20, 400),
            "fc4": (400, 784),
        }.items():
            w = rng.normal(0, 0.05, size=(dout, din)).astype(np.float32)
            b = rng.normal(0, 0.05, size=(dout,)).astype(np.float32)
            layer = getattr(tmodel, name)
            layer.weight.copy_(torch.from_numpy(w))
            layer.bias.copy_(torch.from_numpy(b))
            # flax Dense kernel is (in, out) = torch weight transposed
            flax_params[name] = {"kernel": jnp.asarray(w.T), "bias": jnp.asarray(b)}
    return tmodel, flax_params


@pytest.fixture(scope="module")
def parity_setup():
    rng = np.random.default_rng(0)
    tmodel, flax_params = _torch_vae_and_flax_params(rng)
    x = rng.uniform(0, 1, size=(8, 784)).astype(np.float32)
    return tmodel, flax_params, x


def test_encoder_parity(parity_setup):
    tmodel, fparams, x = parity_setup
    model = VAE()
    mu_j, logvar_j = model.apply({"params": fparams}, jnp.asarray(x), method=VAE.encode)
    with torch.no_grad():
        mu_t, logvar_t = tmodel.encode(torch.from_numpy(x))
    np.testing.assert_allclose(np.asarray(mu_j), mu_t.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(logvar_j), logvar_t.numpy(), rtol=1e-4, atol=1e-5
    )


def test_decoder_parity(parity_setup):
    tmodel, fparams, _ = parity_setup
    model = VAE()
    z = np.random.default_rng(1).normal(size=(8, 20)).astype(np.float32)
    probs_j = model.apply({"params": fparams}, jnp.asarray(z), method=VAE.decode_probs)
    with torch.no_grad():
        probs_t = tmodel.decode(torch.from_numpy(z))
    np.testing.assert_allclose(
        np.asarray(probs_j), probs_t.numpy(), rtol=1e-4, atol=1e-5
    )


def test_loss_parity_deterministic_path(parity_setup):
    # eps=0 => z=mu: loss comparable without matching RNG streams.
    tmodel, fparams, x = parity_setup
    model = VAE()
    xj = jnp.asarray(x)
    mu, logvar = model.apply({"params": fparams}, xj, method=VAE.encode)
    logits = model.apply({"params": fparams}, mu, method=VAE.decode)
    loss_j = float(elbo_loss_sum(logits, xj, mu, logvar))

    xt = torch.from_numpy(x)
    with torch.no_grad():
        mu_t, logvar_t = tmodel.encode(xt)
        recon_t = tmodel.decode(mu_t)
        # Reference loss_function (vae-hpo.py:49-58): summed BCE + KLD.
        bce = tF.binary_cross_entropy(recon_t, xt, reduction="sum")
        kld = -0.5 * torch.sum(1 + logvar_t - mu_t.pow(2) - logvar_t.exp())
        loss_t = float(bce + kld)
    assert loss_j == pytest.approx(loss_t, rel=1e-4)


def test_gradient_parity_deterministic_path(parity_setup):
    tmodel, fparams, x = parity_setup
    model = VAE()
    xj = jnp.asarray(x)

    def loss_fn(params):
        mu, logvar = model.apply({"params": params}, xj, method=VAE.encode)
        logits = model.apply({"params": params}, mu, method=VAE.decode)
        return elbo_loss_sum(logits, xj, mu, logvar)

    grads = jax.grad(loss_fn)(fparams)

    xt = torch.from_numpy(x)
    mu_t, logvar_t = tmodel.encode(xt)
    recon_t = tmodel.decode(mu_t)
    bce = tF.binary_cross_entropy(recon_t, xt, reduction="sum")
    kld = -0.5 * torch.sum(1 + logvar_t - mu_t.pow(2) - logvar_t.exp())
    (bce + kld).backward()

    for name in ["fc1", "fc21", "fc22", "fc3", "fc4"]:
        tgrad = getattr(tmodel, name).weight.grad.numpy()
        jgrad = np.asarray(grads[name]["kernel"]).T
        np.testing.assert_allclose(jgrad, tgrad, rtol=5e-3, atol=1e-4)


def test_bce_from_logits_matches_probability_form():
    # Our stable from-logits BCE must equal the reference's
    # F.binary_cross_entropy(sigmoid(l), x, "sum") (vae-hpo.py:50).
    rng = np.random.default_rng(2)
    logits = rng.normal(0, 3, size=(16, 784)).astype(np.float32)
    x = rng.uniform(0, 1, size=(16, 784)).astype(np.float32)
    ours = float(bernoulli_recon_sum(jnp.asarray(logits), jnp.asarray(x)))
    theirs = float(
        tF.binary_cross_entropy(
            torch.sigmoid(torch.from_numpy(logits)),
            torch.from_numpy(x),
            reduction="sum",
        )
    )
    assert ours == pytest.approx(theirs, rel=1e-4)


def test_kl_closed_form_zero_at_standard_normal():
    mu = jnp.zeros((4, 20))
    logvar = jnp.zeros((4, 20))
    assert float(gaussian_kl_sum(mu, logvar)) == 0.0


def test_beta_scales_kl_only():
    rng = np.random.default_rng(3)
    logits = jnp.asarray(rng.normal(size=(4, 784)).astype(np.float32))
    x = jnp.asarray(rng.uniform(size=(4, 784)).astype(np.float32))
    mu = jnp.asarray(rng.normal(size=(4, 20)).astype(np.float32))
    logvar = jnp.asarray(rng.normal(size=(4, 20)).astype(np.float32))
    base = elbo_loss_sum(logits, x, mu, logvar, beta=1.0)
    doubled = elbo_loss_sum(logits, x, mu, logvar, beta=2.0)
    assert float(doubled - base) == pytest.approx(
        float(gaussian_kl_sum(mu, logvar)), rel=1e-5
    )


def test_reparameterize_uses_rng_stream():
    model = VAE()
    params = init_vae_params(jax.random.key(0), model)["params"]
    x = jnp.ones((2, 784)) * 0.5
    out1 = model.apply({"params": params}, x, rngs={"reparam": jax.random.key(1)})
    out2 = model.apply({"params": params}, x, rngs={"reparam": jax.random.key(2)})
    out1b = model.apply({"params": params}, x, rngs={"reparam": jax.random.key(1)})
    assert not np.allclose(np.asarray(out1[0]), np.asarray(out2[0]))
    np.testing.assert_array_equal(np.asarray(out1[0]), np.asarray(out1b[0]))


def test_sampled_eval_matches_torch_reference(parity_setup):
    """VERDICT r3 item 6: eval_sampled reproduces the reference's test
    semantics — the full sampled forward (vae-hpo.py:101-105 calls
    model(data), which reparameterizes, :42-45) — and, with identical
    params and identical z, its loss equals the torch reference's."""
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import TrainState, make_eval_step

    tmodel, fparams, x = parity_setup
    model = VAE()
    (g,) = setup_groups(1)
    xj = jnp.asarray(x)
    key = jax.random.key(5)

    # Full sampled forward — exactly what eval_core does under
    # sampled=True, same 'reparam' stream.
    logits_f, mu_f, logvar_f = model.apply(
        {"params": fparams}, xj, rngs={"reparam": key}
    )
    manual = float(elbo_loss_sum(logits_f, xj, mu_f, logvar_f))

    state = TrainState(
        params=g.device_put(fparams),
        opt_state=None,
        step=jnp.zeros((), jnp.int32),
    )
    eval_step = make_eval_step(g, model, with_recon=False, sampled=True)
    got = float(
        eval_step(state, jax.device_put(xj, g.batch_sharding), key)[
            "loss_sum"
        ]
    )
    assert got == pytest.approx(manual, rel=1e-5)

    # Recover the exact z the stream produced (method-call reuses the
    # same top-level 'reparam' stream) and feed the SAME z to the torch
    # reference's loss: identical params + identical noise must give the
    # reference's sampled test loss.
    z = model.apply(
        {"params": fparams}, mu_f, logvar_f,
        method=VAE.reparameterize, rngs={"reparam": key},
    )
    np.testing.assert_allclose(
        np.asarray(model.apply({"params": fparams}, z, method=VAE.decode)),
        np.asarray(logits_f),
        rtol=1e-5,
        atol=1e-6,
    )
    xt = torch.from_numpy(x)
    with torch.no_grad():
        mu_t, logvar_t = tmodel.encode(xt)
        recon_t = tmodel.decode(torch.from_numpy(np.asarray(z)))
        bce = tF.binary_cross_entropy(recon_t, xt, reduction="sum")
        kld = -0.5 * torch.sum(1 + logvar_t - mu_t.pow(2) - logvar_t.exp())
        loss_t = float(bce + kld)
    assert got == pytest.approx(loss_t, rel=1e-4)


def test_sampled_eval_differs_from_posterior_mean(parity_setup):
    # The two eval semantics must actually differ (sampled z != mu), and
    # the posterior-mean loss is the tighter (smaller) bound in
    # expectation — here checked on one draw of a trained-free model.
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import TrainState, make_eval_step

    _, fparams, x = parity_setup
    model = VAE()
    (g,) = setup_groups(1)
    state = TrainState(
        params=g.device_put(fparams),
        opt_state=None,
        step=jnp.zeros((), jnp.int32),
    )
    batch = jax.device_put(jnp.asarray(x), g.batch_sharding)
    mean_loss = float(
        make_eval_step(g, model, with_recon=False)(state, batch)["loss_sum"]
    )
    sampled_loss = float(
        make_eval_step(g, model, with_recon=False, sampled=True)(
            state, batch, jax.random.key(9)
        )["loss_sum"]
    )
    assert sampled_loss != mean_loss


def test_softmax_xent():
    logits = jnp.asarray([[10.0, 0.0, 0.0], [0.0, 10.0, 0.0]])
    labels = jnp.asarray([0, 1])
    assert float(softmax_cross_entropy_mean(logits, labels)) < 1e-3
