"""Ring attention: exactness vs dense reference on a sharded sequence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from multidisttorch_tpu.ops.ring_attention import (
    dense_attention_reference,
    make_ring_attention,
)
from multidisttorch_tpu.parallel.mesh import setup_groups


def _qkv(rng, b=2, t=32, h=2, d=8):
    return tuple(
        jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        for _ in range(3)
    )


@pytest.mark.parametrize("ngroups,causal", [(2, False), (2, True), (1, False), (1, True)])
def test_matches_dense_reference(ngroups, causal):
    trial = setup_groups(ngroups)[0]  # 4- or 8-device ring
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng)
    ring = make_ring_attention(trial, causal=causal)
    out = ring(q, k, v)
    ref = dense_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )


def test_sequence_is_actually_sharded():
    trial = setup_groups(2)[1]
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, t=64)
    out = make_ring_attention(trial)(q, k, v)
    # output sequence dim sharded over the submesh axis
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(2, 64 // 4, 2, 8)}


def test_two_trials_run_ring_attention_concurrently():
    # trial parallelism x sequence parallelism: two disjoint rings
    trials = setup_groups(2)
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng)
    outs = [make_ring_attention(t)(q, k, v) for t in trials]
    ref = dense_attention_reference(q, k, v)
    for out in outs:
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
        )


@pytest.mark.parametrize("causal", [False, True])
def test_2d_sequence_x_head_parallel_matches_dense(causal):
    # (data=4 x model=2) mesh: the sequence rides the ring while heads
    # shard over the model axis — the 2-D attention configuration that
    # composes with transformer_tp_shardings. Values AND grads exact.
    (trial,) = setup_groups(1, model_parallel=2)  # data 4 x model 2
    rng = np.random.default_rng(7)
    q, k, v = _qkv(rng, t=16, h=4)  # t div 4, heads div 2
    ring = make_ring_attention(trial, causal=causal)
    assert ring.head_sharded
    out = ring(q, k, v)
    ref = dense_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6
    )
    g = jax.grad(lambda q: jnp.sum(ring(q, k, v) ** 2))(q)
    g_ref = jax.grad(
        lambda q: jnp.sum(dense_attention_reference(q, k, v,
                                                    causal=causal) ** 2)
    )(q)
    np.testing.assert_allclose(
        np.asarray(g), np.asarray(g_ref), rtol=5e-5, atol=5e-6
    )


def test_2d_head_divisibility_checked():
    (trial,) = setup_groups(1, model_parallel=2)
    rng = np.random.default_rng(8)
    q, k, v = _qkv(rng, t=16, h=3)  # 3 heads don't divide model=2
    ring = make_ring_attention(trial)
    with pytest.raises(ValueError, match="not divisible"):
        ring(q, k, v)
    # explicit opt-out replicates heads and still matches dense
    flat = make_ring_attention(trial, shard_heads=False)
    np.testing.assert_allclose(
        np.asarray(flat(q, k, v)),
        np.asarray(dense_attention_reference(q, k, v)),
        rtol=2e-5, atol=2e-6,
    )


def test_extreme_logits_stable():
    trial = setup_groups(2)[0]
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng)
    q = q * 40.0  # large scores: online softmax must not overflow
    out = make_ring_attention(trial)(q, k, v)
    ref = dense_attention_reference(q, k, v)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5
    )
