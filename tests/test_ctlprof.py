"""Control-plane flight books (ISSUE 18, docs/OBSERVABILITY.md
"Control-plane books"): the zero-cost-when-off contract (tier-1 —
patching ``ctlprof._clock`` with a raiser proves the off path reads no
clock), work-touched accounting on a scripted real seam, the books
schema with honest bucket-bound error bars, the Perfetto pass-ring
track, the sampling fallback, registry mirroring, and the cross-round
regression ledger's drift flags."""

from __future__ import annotations

import json
import os
import time

import pytest

from multidisttorch_tpu.service.loadgen import LoadSpec, run_loadgen
from multidisttorch_tpu.service.queue import (
    SubmissionQueue,
    SweepClient,
    intake_dir,
)
from multidisttorch_tpu.telemetry import ctlprof
from multidisttorch_tpu.telemetry.metrics import MetricsRegistry

pytestmark = pytest.mark.ctlprof


@pytest.fixture(autouse=True)
def _no_leaked_profiler():
    """Every test starts and ends with the profiler OFF (module-global
    state, same discipline as the telemetry bus tests)."""
    ctlprof.disable()
    yield
    ctlprof.disable()


def _boom_clock():
    raise AssertionError(
        "ctlprof clock read with the profiler OFF — the "
        "zero-cost-when-off contract is broken"
    )


# -- zero-cost-when-off (the CI tier-1 guard) --------------------------


def test_ctlprof_off_reads_no_clock(tmp_path, monkeypatch):
    """With no profiler armed, driving the real control plane through
    every seam family (intake drain + a full discrete-event scheduling
    run: admission, fair-share, EDF, bin-pack, preemption, defrag)
    must never reach the profiler's clock indirection."""
    assert ctlprof.get_ctlprof() is None
    monkeypatch.setattr(ctlprof, "_clock", _boom_clock)
    # Real journal seam:
    d = str(tmp_path)
    c = SweepClient(d, tenant="alice")
    c.submit({"epochs": 1}, priority=0, size=1)
    q = SubmissionQueue(d)
    fresh = q.drain_intake(known_ids=set())
    assert len(fresh) == 1
    # Real scheduler passes, thousands of them:
    report = run_loadgen(LoadSpec(n_submissions=300, seed=3))
    assert report["zero_lost"]
    assert ctlprof.get_ctlprof() is None


# -- work-touched accounting on a scripted pass ------------------------


def test_work_touched_exact_on_intake_drain(tmp_path):
    """Scripted spool: 3 committed submissions + 1 torn ``.tmp`` file.
    The intake_drain books must read examined=4 (every directory entry
    iterated), mutated=3 (journaled fresh), scan efficiency 0.75."""
    d = str(tmp_path)
    c = SweepClient(d, tenant="alice")
    for _ in range(3):
        c.submit({"epochs": 1}, priority=1, size=1)
    torn = os.path.join(intake_dir(d), "zz-torn.json.tmp")
    with open(torn, "w") as f:
        f.write('{"never": "committed"')
    prof = ctlprof.configure()
    try:
        q = SubmissionQueue(d)
        fresh = q.drain_intake(known_ids=set())
        assert len(fresh) == 3
        books = prof.books()
    finally:
        ctlprof.disable()
    ph = books["phases"]["intake_drain"]
    assert ph["calls"] == 1
    assert ph["examined"] == 4
    assert ph["mutated"] == 3
    assert ph["scan_efficiency"] == pytest.approx(0.75)
    assert ph["worst_call"]["examined"] == 4
    wt = books["work_touched"]
    assert wt["examined"] == 4 and wt["mutated"] == 3


# -- books schema + honest percentiles ---------------------------------


def test_books_schema_and_bucket_error_bounds():
    prof = ctlprof.configure()
    try:
        prof.pass_begin()
        t = prof.t0()
        prof.note("bin_pack_scan", t, examined=4000, mutated=3)
        t = prof.t0()
        prof.note("edf_insert", t, examined=7, mutated=1)
        prof.pass_end()
        books = prof.books()
    finally:
        ctlprof.disable()
    assert books["enabled"] is True
    assert books["passes"]["count"] == 1
    assert books["passes"]["per_s"] > 0
    # Listing order follows the PHASES taxonomy:
    assert list(books["phases"]) == ["edf_insert", "bin_pack_scan"]
    fracs = sum(b["wall_frac"] for b in books["phases"].values())
    assert fracs == pytest.approx(1.0)
    bp = books["phases"]["bin_pack_scan"]
    assert bp["scan_efficiency"] == pytest.approx(3 / 4000)
    # Honest percentiles: every reported percentile sits inside its
    # bucket bounds, and the bounds are one fine log bucket apart
    # (8/decade => factor 10^(1/8) ~= 1.33).
    for blk in (bp, books["passes"]):
        for p in ("p50_s", "p95_s", "p99_s"):
            lo, hi = blk["bucket_err"][p]
            assert lo <= blk[p] <= hi
            if lo > 0:
                assert hi / lo == pytest.approx(10 ** 0.125, rel=1e-6)
    # Worst-pass capture aggregates the pass's notes:
    worst = books["passes"]["worst"]
    assert worst["phases"]["bin_pack_scan"]["examined"] == 4000


def test_unknown_phase_lazily_added():
    prof = ctlprof.configure()
    try:
        t = prof.t0()
        prof.note("experimental_phase", t, examined=1, mutated=1)
        books = prof.books()
    finally:
        ctlprof.disable()
    assert books["phases"]["experimental_phase"]["calls"] == 1


# -- Perfetto control-plane track --------------------------------------


def test_trace_events_ring_relative():
    prof = ctlprof.configure(ring=8)
    try:
        for _ in range(3):
            prof.pass_begin()
            t = prof.t0()
            prof.note("admission", t, examined=2, mutated=2)
            prof.pass_end()
        evs = prof.trace_events(pid=0)
    finally:
        ctlprof.disable()
    metas = [e for e in evs if e["ph"] == "M"]
    slices = [e for e in evs if e["ph"] == "X"]
    assert any(
        e["name"] == "process_name"
        and e["args"]["name"] == "control-plane"
        for e in metas
    )
    assert sum(1 for e in slices if e["name"] == "ctl_pass") == 3
    adm = [e for e in slices if e["name"] == "admission"]
    assert len(adm) == 3
    assert all(e["args"] == {"examined": 2, "mutated": 2} for e in adm)
    # Ring-relative clock: every ts lands at/after the oldest pass.
    assert all(e["ts"] >= 0 for e in slices)
    assert all(e["pid"] == 0 for e in evs)


def test_trace_events_empty_ring():
    prof = ctlprof.configure()
    try:
        assert prof.trace_events() == []
    finally:
        ctlprof.disable()


# -- registry mirroring ------------------------------------------------


def test_registry_mirroring_at_books_cadence():
    reg = MetricsRegistry()
    prof = ctlprof.configure(registry=reg)
    try:
        prof.pass_begin()
        t = prof.t0()
        prof.note("fair_share_pick", t, examined=12, mutated=1)
        prof.pass_end()
        prof.books()  # counters mirror at books cadence, not per-note
    finally:
        ctlprof.disable()
    assert (
        reg.counter("ctl_phase_examined_total", phase="fair_share_pick")
        .value == 12.0
    )
    assert reg.counter("ctl_passes_total").value == 1.0
    # Wall histograms are registry-native series (no mirroring):
    h = reg.histogram(
        "ctl_phase_wall_s",
        bounds=ctlprof.CTL_TIME_BUCKETS,
        phase="fair_share_pick",
    )
    assert h.count == 1


# -- sampling fallback -------------------------------------------------


def test_sampler_writes_flame_file(tmp_path):
    flame = str(tmp_path / "ctl_flame.txt")
    prof = ctlprof.configure(sample_hz=250.0, flame_path=flame)
    try:
        assert prof.sampler is not None
        deadline = time.perf_counter() + 0.5
        x = 0
        while time.perf_counter() < deadline and prof.sampler.samples < 3:
            x += sum(range(200))  # keep this thread on-CPU to sample
    finally:
        retired = ctlprof.disable()
    assert retired.sampler.samples >= 1
    assert not retired.sampler.is_alive()  # bounded: thread stopped
    with open(flame) as f:
        lines = [ln for ln in f.read().splitlines() if ln]
    assert lines
    # Collapsed-stack format: "frame;frame;...;leaf count"
    stack, count = lines[0].rsplit(" ", 1)
    assert ";" in stack and int(count) >= 1


def test_sample_hz_env_default(monkeypatch):
    monkeypatch.setenv("MDT_CTLPROF_SAMPLE_HZ", "0")
    prof = ctlprof.configure()
    try:
        assert prof.sampler is None
    finally:
        ctlprof.disable()
    monkeypatch.setenv("MDT_CTLPROF_SAMPLE_HZ", "not-a-number")
    prof = ctlprof.configure()
    try:
        assert prof.sampler is None  # garbage env = sampler off
    finally:
        ctlprof.disable()


# -- regression ledger -------------------------------------------------


def _fake_books(bin_pack_frac: float) -> dict:
    other = 1.0 - bin_pack_frac
    return {
        "enabled": True,
        "phases": {
            "bin_pack_scan": {
                "wall_frac": bin_pack_frac, "p99_s": 1e-4,
                "bucket_err": {"p99_s": [9e-5, 1.2e-4]},
                "scan_efficiency": 0.001,
            },
            "edf_insert": {
                "wall_frac": other, "p99_s": 1e-5,
                "bucket_err": {"p99_s": [9e-6, 1.2e-5]},
                "scan_efficiency": 1.0,
            },
        },
        "passes": {"per_s": 9000.0},
        "work_touched": {
            "examined": 1000, "mutated": 10, "scan_efficiency": 0.01,
        },
    }


def test_ledger_fold_and_drift_flags(tmp_path):
    path = str(tmp_path / "ctlprof_ledger.jsonl")
    rec1 = ctlprof.ledger_record(
        "zoo", "diurnal_wave", _fake_books(0.50),
        submissions_per_wall_s=10000.0,
    )
    assert rec1["phase_wall_frac"]["bin_pack_scan"] == pytest.approx(0.5)
    assert rec1["scan_efficiency"] == pytest.approx(0.01)
    folded1 = ctlprof.fold_ledger_round(path, rec1)
    assert folded1["vs_prev_rounds"] == {"prior_rounds": 0}
    # Round 2: throughput -40%, bin_pack wall fraction +0.25 absolute —
    # both drift flags must trip against the prior median.
    rec2 = ctlprof.ledger_record(
        "zoo", "diurnal_wave", _fake_books(0.75),
        submissions_per_wall_s=6000.0,
    )
    folded2 = ctlprof.fold_ledger_round(path, rec2)
    vs = folded2["vs_prev_rounds"]
    assert vs["prior_rounds"] == 1
    assert vs["drift_exceeds_20pct"] is True
    assert vs["ratio_to_median"] == pytest.approx(0.6)
    assert vs["phase_drift"] is True
    assert "bin_pack_scan" in vs["phase_frac_shifts"]
    # Rounds are keyed (kind, scenario): another scenario sees none.
    rec3 = ctlprof.ledger_record(
        "zoo", "tenant_burst", _fake_books(0.5),
        submissions_per_wall_s=6000.0,
    )
    assert ctlprof.fold_ledger_round(path, rec3)["vs_prev_rounds"] == {
        "prior_rounds": 0
    }
    # Torn-tail tolerant reader:
    with open(path, "a") as f:
        f.write('{"torn": ')
    rows = ctlprof.read_ledger(path)
    assert len(rows) == 3
    assert all("vs_prev_rounds" in r for r in rows)
    assert json.loads(json.dumps(rows[0]))  # JSON-clean


def test_ledger_summary_reads_bucket_bounds():
    summary = ctlprof.ledger_phase_summary(_fake_books(0.5))
    assert summary["bin_pack_scan"]["p99_bounds_s"] == [9e-5, 1.2e-4]
    assert summary["edf_insert"]["scan_efficiency"] == 1.0
