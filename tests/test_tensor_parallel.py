"""2-D (data × model) trial submeshes: Megatron-style tensor parallelism
within a trial — a capability beyond the reference (SURVEY.md §2c lists
TP as absent there), validated against the 1-D data-parallel path.

Runs on 8 virtual CPU devices (tests/conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from multidisttorch_tpu.models.conv_vae import ConvVAE, conv_tp_shardings
from multidisttorch_tpu.models.resnet import ResNet, resnet_tp_shardings
from multidisttorch_tpu.models.vae import VAE, vae_tp_shardings
from multidisttorch_tpu.train.classifier import (
    create_classifier_state,
    make_classifier_train_step,
)
from multidisttorch_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    setup_groups,
)
from multidisttorch_tpu.train.steps import (
    create_train_state,
    make_train_step,
    state_shardings,
)


def test_2d_carving_shapes_and_disjointness():
    groups = setup_groups(2, model_parallel=2)
    assert len(groups) == 2
    seen = set()
    for g in groups:
        assert g.size == 4
        assert g.data_size == 2
        assert g.model_size == 2
        assert dict(g.mesh.shape) == {DATA_AXIS: 2, MODEL_AXIS: 2}
        ids = {d.id for d in g.devices}
        assert not (ids & seen)
        seen |= ids
    assert len(seen) == 8


def test_model_parallel_must_divide_group():
    with pytest.raises(ValueError, match="model_parallel"):
        setup_groups(2, model_parallel=3)  # group of 4, mp=3
    with pytest.raises(ValueError, match="model_parallel"):
        setup_groups(1, model_parallel=0)


def test_1d_groups_report_trivial_model_axis():
    (g,) = setup_groups(1)
    assert g.model_size == 1
    assert g.data_size == g.size == 8


def test_tp_params_are_actually_sharded():
    (g,) = setup_groups(1, model_parallel=4)  # 2 data x 4 model
    model = VAE(hidden_dim=32, latent_dim=8)
    state = create_train_state(
        g, model, optax.adam(1e-3), jax.random.key(0),
        param_shardings=vae_tp_shardings(g),
    )
    fc1 = state.params["fc1"]["kernel"]
    # column-parallel: (784, 32) split into (784, 8) shards on the model axis
    assert fc1.shape == (784, 32)
    assert fc1.addressable_shards[0].data.shape == (784, 8)
    # Adam moments inherit the weight sharding (eager init,
    # computation-follows-data)
    mu_fc1 = state.opt_state[0].mu["fc1"]["kernel"]
    assert mu_fc1.addressable_shards[0].data.shape == (784, 8)
    # row-parallel consumer: (32, 8) split into (8, 8) shards
    fc21 = state.params["fc21"]["kernel"]
    assert fc21.addressable_shards[0].data.shape == (8, 8)


def _train_losses(model_parallel: int, steps: int = 4) -> list[float]:
    if model_parallel == 1:
        (g,) = setup_groups(1)
        shardings = None
        state = create_train_state(g, VAE(hidden_dim=32, latent_dim=8),
                                   optax.adam(1e-3), jax.random.key(0))
    else:
        (g,) = setup_groups(1, model_parallel=model_parallel)
        model = VAE(hidden_dim=32, latent_dim=8)
        state = create_train_state(
            g, model, optax.adam(1e-3), jax.random.key(0),
            param_shardings=vae_tp_shardings(g),
        )
        shardings = state_shardings(state)
    model = VAE(hidden_dim=32, latent_dim=8)
    step = make_train_step(g, model, optax.adam(1e-3), shardings=shardings)
    batch = jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (16, 784)).astype(np.float32)
    )
    batch = jax.device_put(batch, g.batch_sharding)
    losses = []
    for i in range(steps):
        state, m = step(state, batch, jax.random.fold_in(jax.random.key(7), i))
        losses.append(float(m["loss_sum"]))
    return losses


def test_tp_training_matches_data_parallel():
    # Same seeds, same data: a (2 data x 4 model) trial must optimize
    # identically to the 8-wide pure-DP trial (up to reduction order).
    dp = _train_losses(1)
    tp = _train_losses(4)
    np.testing.assert_allclose(dp, tp, rtol=2e-4)


def _conv_vae_losses(model_parallel: int, steps: int = 3) -> list[float]:
    # Tiny ConvVAE (c=8 → channels 8/16/32, all divisible by mp=4) so the
    # CPU-device conv stack stays fast; same seeds/data across carvings.
    make = lambda: ConvVAE(latent_dim=8, base_channels=8)
    if model_parallel == 1:
        (g,) = setup_groups(1)
        shardings = None
        state = create_train_state(
            g, make(), optax.adam(1e-3), jax.random.key(0)
        )
    else:
        (g,) = setup_groups(1, model_parallel=model_parallel)
        model = make()
        state = create_train_state(
            g, model, optax.adam(1e-3), jax.random.key(0),
            param_shardings=conv_tp_shardings(g, model),
        )
        shardings = state_shardings(state)
    step = make_train_step(g, make(), optax.adam(1e-3), shardings=shardings)
    batch = jax.device_put(
        jnp.asarray(
            np.random.default_rng(0)
            .uniform(0, 1, (16, 32 * 32 * 3))
            .astype(np.float32)
        ),
        g.batch_sharding,
    )
    losses = []
    for i in range(steps):
        state, m = step(state, batch, jax.random.fold_in(jax.random.key(7), i))
        losses.append(float(m["loss_sum"]))
    return losses


def test_conv_vae_tp_training_matches_data_parallel():
    # BASELINE.md config 3's model under TP: a (2 data x 4 model) carve
    # must optimize identically to pure 8-wide DP.
    dp = _conv_vae_losses(1)
    tp = _conv_vae_losses(4)
    np.testing.assert_allclose(dp, tp, rtol=2e-4)


def test_conv_tp_requires_divisible_channels():
    (g,) = setup_groups(1, model_parallel=4)
    with pytest.raises(ValueError, match="base_channels"):
        conv_tp_shardings(g, ConvVAE(base_channels=6))


def test_conv_tp_params_are_actually_sharded():
    (g,) = setup_groups(1, model_parallel=4)
    model = ConvVAE(latent_dim=8, base_channels=8)
    state = create_train_state(
        g, model, optax.adam(1e-3), jax.random.key(0),
        param_shardings=conv_tp_shardings(g, model),
    )
    # enc0 column-parallel: (3,3,3,8) kernel → (3,3,3,2) shards
    k = state.params["enc0"]["kernel"]
    assert k.shape == (3, 3, 3, 8)
    assert k.addressable_shards[0].data.shape == (3, 3, 3, 2)
    # enc1 row-parallel consumer: (3,3,8,16) → (3,3,2,16) shards
    k = state.params["enc1"]["kernel"]
    assert k.addressable_shards[0].data.shape == (3, 3, 2, 16)
    # Adam moments inherit the sharding (eager init)
    mu = state.opt_state[0].mu["enc0"]["kernel"]
    assert mu.addressable_shards[0].data.shape == (3, 3, 3, 2)


def _resnet_losses(model_parallel: int, steps: int = 3) -> list[float]:
    # Two-stage mini ResNet (channels 8/16, one projection shortcut) —
    # exercises every sharding rule incl. the replicated Conv_2 path.
    make = lambda: ResNet(
        num_classes=10, stage_sizes=(1, 1), base_channels=8, image_hw=16
    )
    tx = optax.adam(1e-3)
    if model_parallel == 1:
        (g,) = setup_groups(1)
        shardings = None
        state = create_classifier_state(g, make(), tx, jax.random.key(0))
    else:
        (g,) = setup_groups(1, model_parallel=model_parallel)
        model = make()
        state = create_classifier_state(
            g, model, tx, jax.random.key(0),
            param_shardings=resnet_tp_shardings(g, model),
        )
        shardings = state_shardings(state)
    step = make_classifier_train_step(g, make(), tx, shardings=shardings)
    rng = np.random.default_rng(0)
    images = jax.device_put(
        jnp.asarray(rng.uniform(0, 1, (16, 16 * 16 * 3)).astype(np.float32)),
        g.batch_sharding,
    )
    labels = jax.device_put(
        jnp.asarray(rng.integers(0, 10, (16,)).astype(np.int32)),
        g.batch_sharding,
    )
    losses = []
    for _ in range(steps):
        state, m = step(state, images, labels)
        losses.append(float(m["loss"]))
    return losses


def test_resnet_tp_training_matches_data_parallel():
    # BASELINE.md config 4's model under TP on a (4 data x 2 model) carve.
    dp = _resnet_losses(1)
    tp = _resnet_losses(2)
    np.testing.assert_allclose(dp, tp, rtol=2e-4)


def test_resnet_tp_shardings_cover_block_structure():
    (g,) = setup_groups(1, model_parallel=2)
    model = ResNet(stage_sizes=(1, 1), base_channels=8, image_hw=16)
    sh = resnet_tp_shardings(g, model)
    # First block's Megatron pair: col conv (+sharded norm), row conv.
    blk = sh["BasicBlock_0"]
    assert blk["Conv_0"]["kernel"].spec == jax.sharding.PartitionSpec(
        None, None, None, MODEL_AXIS
    )
    assert blk["GroupNorm_0"]["scale"].spec == jax.sharding.PartitionSpec(
        MODEL_AXIS
    )
    assert blk["Conv_1"]["kernel"].spec == jax.sharding.PartitionSpec(
        None, None, MODEL_AXIS, None
    )
    assert blk["GroupNorm_1"]["scale"].spec == jax.sharding.PartitionSpec()
    # Stage-crossing block has a projection shortcut — replicated.
    assert "Conv_2" in sh["BasicBlock_1"]
    assert sh["BasicBlock_1"]["Conv_2"]["kernel"].spec == (
        jax.sharding.PartitionSpec()
    )
    # Stem and head stay replicated (layout joins).
    assert sh["stem"]["kernel"].spec == jax.sharding.PartitionSpec()
    assert sh["head"]["kernel"].spec == jax.sharding.PartitionSpec()


def test_tp_state_layout_is_stable_across_steps():
    (g,) = setup_groups(1, model_parallel=2)
    model = VAE(hidden_dim=32, latent_dim=8)
    tx = optax.adam(1e-3)
    state = create_train_state(
        g, model, tx, jax.random.key(0),
        param_shardings=vae_tp_shardings(g),
    )
    sh = state_shardings(state)
    step = make_train_step(g, model, tx, shardings=sh)
    batch = jax.device_put(
        jnp.zeros((16, 784), jnp.float32), g.batch_sharding
    )
    state, _ = step(state, batch, jax.random.key(1))
    # output layout identical to input layout — no drift, no reshard
    assert jax.tree.all(
        jax.tree.map(
            lambda a, s: a.sharding == s, state.params, sh.params
        )
    )


def test_resnet_tp_with_grad_accum_matches_full_batch():
    # Composition: TP weight sharding x microbatch gradient accumulation
    # — deterministic classifier, so one accumulated step equals one
    # full-batch step exactly on the same TP submesh.
    (g,) = setup_groups(1, model_parallel=2)
    model = ResNet(stage_sizes=(1,), base_channels=8, image_hw=16)
    tx = optax.adam(1e-3)
    rng = np.random.default_rng(4)
    images = jax.device_put(
        jnp.asarray(rng.uniform(0, 1, (16, 16 * 16 * 3)).astype(np.float32)),
        g.batch_sharding,
    )
    labels = jax.device_put(
        jnp.asarray(rng.integers(0, 10, (16,)).astype(np.int32)),
        g.batch_sharding,
    )
    outs = {}
    for accum in (1, 4):
        state = create_classifier_state(
            g, model, tx, jax.random.key(0),
            param_shardings=resnet_tp_shardings(g, model),
        )
        step = make_classifier_train_step(
            g, model, tx, shardings=state_shardings(state), grad_accum=accum
        )
        state, m = step(state, images, labels)
        outs[accum] = (jax.device_get(state.params), float(m["loss"]))
    assert outs[1][1] == pytest.approx(outs[4][1], rel=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
        outs[1][0],
        outs[4][0],
    )
