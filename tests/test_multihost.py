"""True multi-controller integration tests: 2 cooperating processes,
4 virtual CPU devices each (8-device world over the Gloo-backed JAX
distributed runtime).

The reference could only validate multi-node behavior by running on the
real clusters its env detection targets (SURVEY.md §4); these tests
exercise the same contracts — per-process trial membership, a submesh
spanning processes, cross-process PBT weight exchange — in plain pytest.

Subprocesses are required (jax.distributed is per-process global state),
so these tests bypass the in-process 8-fake-device conftest harness.
"""

import json
import os
import socket
import subprocess
import sys
import threading

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "mh_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(mode: str, tmp_path) -> list[dict]:
    """Run the worker twice (ranks 0/1) through the framework's own
    OpenMPI-style env detection; return both RESULT payloads."""
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU plugin in workers
        env.update(
            OMPI_COMM_WORLD_SIZE="2",
            OMPI_COMM_WORLD_RANK=str(rank),
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, _WORKER, mode, str(tmp_path / "out")],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    # Drain both pipes concurrently: one rank dying mid-collective can
    # fill its pipe while its peer blocks in the collective — sequential
    # communicate() would deadlock the pair. Kill whatever survives a
    # timeout so a hung rendezvous can't poison later tests.
    outs: list = [None, None]

    def drain(i, p):
        try:
            outs[i] = p.communicate(timeout=420)[0]
        except subprocess.TimeoutExpired:
            pass

    try:
        threads = [
            threading.Thread(target=drain, args=(i, p))
            for i, p in enumerate(procs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=450)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert out is not None, f"rank {rank} timed out"
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"no RESULT line in:\n{out[-4000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))
    return sorted(results, key=lambda r: r["pid"])


@pytest.mark.multihost
def test_split_groups_each_process_runs_its_trial(tmp_path):
    r0, r1 = _launch("hpo_split", tmp_path)
    # Process g owns group g only -> runs only trial g (the reference's
    # membership contract, vae-hpo.py:200-202, without any collective).
    assert r0["local_trials"] == [0]
    assert r1["local_trials"] == [1]
    assert r0["steps"]["0"] == 8 and r1["steps"]["1"] == 8


@pytest.mark.multihost
def test_spanning_group_trains_identically_on_both_processes(tmp_path):
    r0, r1 = _launch("hpo_span", tmp_path)
    # SPMD: both processes executed the same trial over the shared
    # 8-device submesh and must agree bit-for-bit on the results.
    assert r0["final_train_loss"] == r1["final_train_loss"]
    assert r0["final_test_loss"] == r1["final_test_loss"]
    assert r0["steps"] == r1["steps"] == 16
    # Writer gating: artifacts exist, and only rank 0 (owner of the
    # group's first device) reports having written the checkpoint.
    assert r0["wrote_metrics"] and r1["wrote_metrics"]  # shared FS view
    assert r0["wrote_ckpt"] and not r1["wrote_ckpt"]


@pytest.mark.multihost
def test_resilient_split_groups_isolate_deterministic_failure(tmp_path):
    r0, r1 = _launch("resilient_split", tmp_path)
    # Trial 1 (group 1, wholly owned by process 1) fails
    # deterministically; the sweep completes everywhere, and group 0's
    # elastic queue still serves trial 2.
    assert r0["statuses"] == {"0": "completed", "2": "completed"}
    assert r1["statuses"] == {"1": "failed"}
    assert "injected deterministic failure" in r1["errors"]["1"]


@pytest.mark.multihost
def test_resilient_spanning_group_agrees_on_writer_only_failure(tmp_path):
    r0, r1 = _launch("resilient_span_io", tmp_path)
    # The image write failed on the WRITER process only; the
    # epoch-boundary health reduction must kill trial 0 on BOTH owner
    # processes (without it, rank 1 keeps stepping trial 0 while rank 0
    # has freed the submesh — desynchronized collectives / hang). Both
    # must then complete trial 1 on the freed submesh.
    for r in (r0, r1):
        assert r["statuses"] == {"0": "failed", "1": "completed"}, r
        assert r["trial1_steps"] == 16
    # Rank 0 carries the real error; rank 1 learned of it via agreement.
    assert "injected writer-only disk failure" in r0["errors"]["0"]
    assert "peer" in r1["errors"]["0"] or "injected" in r1["errors"]["0"]


@pytest.mark.multihost
def test_resilient_spanning_group_agrees_on_asymmetric_setup_failure(tmp_path):
    r0, r1 = _launch("resilient_span_setup", tmp_path)
    # Setup raised on process 1 only; the setup agreement keeps process
    # 0 from stepping a trial its peer never constructed.
    for r in (r0, r1):
        assert r["statuses"] == {"0": "failed", "1": "completed"}, r
    assert "injected one-process setup failure" in r1["errors"]["0"]
    assert "peer" in r0["errors"]["0"]


@pytest.mark.multihost
def test_pbt_cross_process_exploit_agrees(tmp_path):
    r0, r1 = _launch("pbt", tmp_path)
    # Global decisions (scores, ranking, exploit targets, perturbed lrs)
    # must be identical on every process; at least one exploit crossed
    # the process boundary via broadcast_one_to_all.
    assert r0["best_member"] == r1["best_member"]
    assert r0["best_eval_loss"] == r1["best_eval_loss"]
    assert r0["final_lrs"] == r1["final_lrs"]
    assert r0["scores"] == r1["scores"]
    assert r0["n_exploits"] == r1["n_exploits"] >= 1
