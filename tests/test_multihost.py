"""True multi-controller integration tests: N cooperating processes with
M virtual CPU devices each over the Gloo-backed JAX distributed runtime
(2x4 for the core cases, 4x2 for the >2-process agreement/writer-gating
and uneven-ownership cases).

The reference could only validate multi-node behavior by running on the
real clusters its env detection targets (SURVEY.md §4); these tests
exercise the same contracts — per-process trial membership, a submesh
spanning processes, cross-process PBT weight exchange — in plain pytest.

Subprocesses are required (jax.distributed is per-process global state),
so these tests bypass the in-process 8-fake-device conftest harness.
"""

import json
import os
import socket
import subprocess
import sys
import threading

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "mh_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(
    mode: str,
    tmp_path,
    *,
    nprocs: int = 2,
    devs_per_proc: int = 4,
    timeout: int = 420,
    extra_env: dict | None = None,
) -> list[dict]:
    """Run ``nprocs`` worker ranks through the framework's own
    OpenMPI-style env detection; return every RESULT payload.

    The default 2x4 world matches the original harness; 4x2 exercises
    agreement/writer-gating at >2 processes (the reference's own demo is
    an 8-process world, example-subgroup.py:39)."""
    port = _free_port()
    procs = []
    for rank in range(nprocs):
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU plugin in workers
        env.update(
            OMPI_COMM_WORLD_SIZE=str(nprocs),
            OMPI_COMM_WORLD_RANK=str(rank),
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            MH_DEVS_PER_PROC=str(devs_per_proc),
            **(extra_env or {}),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, _WORKER, mode, str(tmp_path / "out")],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    # Drain all pipes concurrently: one rank dying mid-collective can
    # fill its pipe while its peers block in the collective — sequential
    # communicate() would deadlock the group. Kill whatever survives a
    # timeout so a hung rendezvous can't poison later tests.
    outs: list = [None] * nprocs

    def drain(i, p):
        try:
            outs[i] = p.communicate(timeout=timeout)[0]
        except subprocess.TimeoutExpired:
            pass

    try:
        threads = [
            threading.Thread(target=drain, args=(i, p))
            for i, p in enumerate(procs)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout + 30)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert out is not None, f"rank {rank} timed out"
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-4000:]}"
    results = []
    for out in outs:
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"no RESULT line in:\n{out[-4000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))
    return sorted(results, key=lambda r: r["pid"])


@pytest.mark.multihost
def test_split_groups_each_process_runs_its_trial(tmp_path):
    r0, r1 = _launch("hpo_split", tmp_path)
    # Process g owns group g only -> runs only trial g (the reference's
    # membership contract, vae-hpo.py:200-202, without any collective).
    assert r0["local_trials"] == [0]
    assert r1["local_trials"] == [1]
    assert r0["steps"]["0"] == 8 and r1["steps"]["1"] == 8


@pytest.mark.multihost
def test_spanning_group_trains_identically_on_both_processes(tmp_path):
    r0, r1 = _launch("hpo_span", tmp_path)
    # SPMD: both processes executed the same trial over the shared
    # 8-device submesh and must agree bit-for-bit on the results.
    assert r0["final_train_loss"] == r1["final_train_loss"]
    assert r0["final_test_loss"] == r1["final_test_loss"]
    assert r0["steps"] == r1["steps"] == 16
    # Writer gating: artifacts exist, and only rank 0 (owner of the
    # group's first device) reports having written the checkpoint.
    assert r0["wrote_metrics"] and r1["wrote_metrics"]  # shared FS view
    assert r0["wrote_ckpt"] and not r1["wrote_ckpt"]


@pytest.mark.multihost
def test_resilient_split_groups_isolate_deterministic_failure(tmp_path):
    r0, r1 = _launch("resilient_split", tmp_path)
    # Trial 1 (group 1, wholly owned by process 1) fails
    # deterministically; the sweep completes everywhere, and group 0's
    # elastic queue still serves trial 2.
    assert r0["statuses"] == {"0": "completed", "2": "completed"}
    assert r1["statuses"] == {"1": "failed"}
    assert "injected deterministic failure" in r1["errors"]["1"]


@pytest.mark.multihost
def test_resilient_spanning_group_agrees_on_writer_only_failure(tmp_path):
    r0, r1 = _launch("resilient_span_io", tmp_path)
    # The image write failed on the WRITER process only; the
    # epoch-boundary health reduction must kill trial 0 on BOTH owner
    # processes (without it, rank 1 keeps stepping trial 0 while rank 0
    # has freed the submesh — desynchronized collectives / hang). Both
    # must then complete trial 1 on the freed submesh.
    for r in (r0, r1):
        assert r["statuses"] == {"0": "failed", "1": "completed"}, r
        assert r["trial1_steps"] == 16
    # Rank 0 carries the real error; rank 1 learned of it via agreement.
    assert "injected writer-only disk failure" in r0["errors"]["0"]
    assert "peer" in r1["errors"]["0"] or "injected" in r1["errors"]["0"]


@pytest.mark.multihost
def test_resilient_spanning_group_agrees_on_asymmetric_setup_failure(tmp_path):
    r0, r1 = _launch("resilient_span_setup", tmp_path)
    # Setup raised on process 1 only; the setup agreement keeps process
    # 0 from stepping a trial its peer never constructed.
    for r in (r0, r1):
        assert r["statuses"] == {"0": "failed", "1": "completed"}, r
    assert "injected one-process setup failure" in r1["errors"]["0"]
    assert "peer" in r0["errors"]["0"]


@pytest.mark.multihost
def test_spanning_group_trains_identically_on_four_processes(tmp_path):
    # VERDICT r3 item 7: the 2-process harness capped validation below
    # the reference's own 8-process demo (example-subgroup.py:39). Same
    # spanning-SPMD contract at 4 processes x 2 devices.
    rs = _launch("hpo_span", tmp_path, nprocs=4, devs_per_proc=2, timeout=600)
    assert len(rs) == 4
    assert len({r["final_train_loss"] for r in rs}) == 1
    assert len({r["final_test_loss"] for r in rs}) == 1
    assert all(r["steps"] == 16 for r in rs)
    # Writer gating at 4 processes: exactly one owner wrote the ckpt —
    # the owner of device 0 (process 0).
    assert [r["wrote_ckpt"] for r in rs] == [True, False, False, False]
    assert all(r["wrote_metrics"] for r in rs)  # shared-FS view


@pytest.mark.multihost
def test_resilient_spanning_agreement_at_four_processes(tmp_path):
    # Writer-only I/O failure agreed across FOUR owner processes: every
    # process must kill trial 0 identically and complete trial 1.
    rs = _launch(
        "resilient_span_io", tmp_path, nprocs=4, devs_per_proc=2,
        timeout=600,
    )
    assert len(rs) == 4
    for r in rs:
        assert r["statuses"] == {"0": "failed", "1": "completed"}, r
        assert r["trial1_steps"] == 16
    assert "injected writer-only disk failure" in rs[0]["errors"]["0"]
    for r in rs[1:]:
        assert "peer" in r["errors"]["0"] or "injected" in r["errors"]["0"]


@pytest.mark.multihost
def test_uneven_ownership_spanning_groups(tmp_path):
    # Two 3-device groups over a 4x2 world: owners hold UNEQUAL device
    # counts (2/1 and 1/2), and process 3 owns nothing. Membership,
    # bit-identical SPMD results across co-owners, writer gating, and a
    # clean no-op exit for the unowned process.
    rs = _launch("hpo_uneven", tmp_path, nprocs=4, devs_per_proc=2,
                 timeout=600)
    assert len(rs) == 4
    assert rs[0]["local_trials"] == [0]
    assert rs[1]["local_trials"] == [0, 1]
    assert rs[2]["local_trials"] == [1]
    assert rs[3]["local_trials"] == []
    # co-owners agree bit-for-bit per trial
    assert rs[0]["losses"]["0"] == rs[1]["losses"]["0"]
    assert rs[1]["losses"]["1"] == rs[2]["losses"]["1"]
    # writers: group 0's first device is on proc 0; group 1's on proc 1
    assert rs[0]["wrote_ckpt"]["0"] and not rs[1]["wrote_ckpt"]["0"]
    assert rs[1]["wrote_ckpt"]["1"] and not rs[2]["wrote_ckpt"]["1"]


@pytest.mark.multihost
def test_sequence_parallel_lm_spans_processes(tmp_path):
    # Long-context across HOSTS: one 64-token context sharded over 8
    # devices owned by 2 processes — ring attention's K/V rotation
    # crosses the process boundary. SPMD identity + learning.
    r0, r1 = _launch("lm_sp", tmp_path)
    assert r0["seq_shard_len"] == 8  # 64 tokens / 8 devices
    assert r0["first_loss"] == r1["first_loss"]
    assert r0["final_loss"] == r1["final_loss"]
    assert r0["first_loss"] > 1.5  # near-random at init (ln 16 ≈ 2.77)
    assert r0["final_loss"] < 0.8  # learned the periodic pattern


@pytest.mark.multihost
def test_moe_lm_ep_x_sp_spans_processes(tmp_path):
    # One (data=4 x model=2) trial spanning 2 processes: experts split
    # over the model axis, context ringing over the data axis — the
    # EP x SP composition under real multi-controller SPMD.
    r0, r1 = _launch("moe_lm_ep_sp", tmp_path)
    assert r0["expert_shard"] == 1  # 2 experts / 2-wide model axis
    assert r0["seq_shard_len"] == 8
    assert r0["first_loss"] == r1["first_loss"]
    assert r0["final_loss"] == r1["final_loss"]
    assert r0["final_loss"] < r0["first_loss"] * 0.5


@pytest.mark.multihost
def test_ring_flash_lm_spans_processes(tmp_path):
    # Same cross-process long-context world through the ring-flash path:
    # each hop's block pair runs the Pallas flash kernel while K/V
    # cross the process boundary on the ppermute ring.
    r0, r1 = _launch("lm_sp_flash", tmp_path)
    assert r0["seq_shard_len"] == 8
    assert r0["first_loss"] == r1["first_loss"]
    assert r0["final_loss"] == r1["final_loss"]
    assert r0["first_loss"] > 1.5
    assert r0["final_loss"] < 0.8


@pytest.mark.multihost
def test_spanning_tp_trial_checkpoints(tmp_path):
    # Weight-sharded (TP) trial spanning 2 processes with checkpointing
    # on: the epoch checkpoint must gather-to-replicated on all owners
    # so the writer can serialize — the sweep completes identically on
    # both processes and the checkpoint lands on disk.
    r0, r1 = _launch("hpo_span_tp", tmp_path)
    for r in (r0, r1):
        assert r["status"] == "completed", r
        assert r["steps"] == 16
        assert r["ckpt_exists"]
    assert r0["final_train_loss"] == r1["final_train_loss"]
    assert r0["wrote_ckpt"] and not r1["wrote_ckpt"]


@pytest.mark.multihost
def test_pbt_four_processes_population4_agrees(tmp_path):
    # PBT's global decisions (scores, ranking, exploits, perturbed lrs)
    # must agree across FOUR processes with a 4-member population (one
    # member per 2-device group, each wholly owned by one process), with
    # at least one exploit crossing a process boundary.
    rs = _launch(
        "pbt", tmp_path, nprocs=4, devs_per_proc=2, timeout=600,
        extra_env={"MH_PBT_POP": "4"},
    )
    assert len(rs) == 4
    for r in rs[1:]:
        assert r["best_member"] == rs[0]["best_member"]
        assert r["best_eval_loss"] == rs[0]["best_eval_loss"]
        assert r["final_lrs"] == rs[0]["final_lrs"]
        assert r["scores"] == rs[0]["scores"]
    assert rs[0]["n_exploits"] >= 1


@pytest.mark.multihost
def test_pbt_cross_process_exploit_agrees(tmp_path):
    r0, r1 = _launch("pbt", tmp_path)
    # Global decisions (scores, ranking, exploit targets, perturbed lrs)
    # must be identical on every process; at least one exploit crossed
    # the process boundary via broadcast_one_to_all.
    assert r0["best_member"] == r1["best_member"]
    assert r0["best_eval_loss"] == r1["best_eval_loss"]
    assert r0["final_lrs"] == r1["final_lrs"]
    assert r0["scores"] == r1["scores"]
    assert r0["n_exploits"] == r1["n_exploits"] >= 1
