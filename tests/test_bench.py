"""bench.py is the driver's round-end artifact capture — a crash there
loses the round's hardware evidence, so its pure helpers and (shrunk)
measurement paths get regression tests. Everything runs on the CPU test
mesh; nothing here touches the TPU probe path."""

import json
import os
import subprocess
import sys

import pytest

import bench


def test_flagship_flops_positive():
    f = bench._train_flops_per_sample()
    # 5 dense layers of the 784-400-20 VAE: 3x forward, 2 FLOPs/MAC
    assert f == 3.0 * 2.0 * (784 * 400 + 400 * 20 + 400 * 20 + 20 * 400 + 400 * 784)


def test_lm_flops_formula():
    f = bench._lm_train_flops_per_token(d=64, layers=2, t=128, vocab=256)
    fwd = 2 * (24.0 * 64 * 64 + 2.0 * 128 * 64) + 2.0 * 64 * 256
    assert f == 3.0 * fwd


@pytest.mark.parametrize(
    "kind,expected",
    [
        ("TPU v4", 275e12),
        ("TPU v5 lite", 197e12),
        ("TPU v5e", 197e12),
        ("TPU v5p", 459e12),
        ("TPU v6e", 918e12),
        ("cpu", None),
    ],
)
def test_peak_flops_lookup(kind, expected, monkeypatch):
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    assert bench._peak_flops_per_chip(kind) == expected


def test_peak_flops_env_hint_only_for_unknown(monkeypatch):
    # A stale generation hint must not override a real detection...
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v4")
    assert bench._peak_flops_per_chip("TPU v5e") == 197e12
    # ...but resolves genuinely unknown kinds.
    assert bench._peak_flops_per_chip("TPU weird") == 275e12


def test_tpu_triage_is_read_only_and_structured():
    t = bench._tpu_triage()
    assert isinstance(t, dict)
    # The wedge-attribution evidence the artifact contract promises:
    # device-node state, holder processes, and the tunnel's own state.
    assert {"device_nodes", "accel_node_holders", "axon"} <= set(t)


def test_bench_lm_smoke(monkeypatch):
    monkeypatch.setattr(bench, "LM_VOCAB", 64)
    monkeypatch.setattr(bench, "LM_DMODEL", 32)
    monkeypatch.setattr(bench, "LM_HEADS", 2)
    monkeypatch.setattr(bench, "LM_LAYERS", 1)
    monkeypatch.setattr(bench, "LM_SEQ", 32)
    monkeypatch.setattr(bench, "LM_BATCH", 8)
    monkeypatch.setattr(bench, "LM_STEPS", 2)
    monkeypatch.setattr(bench, "MEASURE_REPEATS", 1)
    r = bench.bench_lm()
    assert r["tokens_per_sec_per_chip"] > 0
    assert r["attention_winner"] == "dense_xla"  # flash is TPU-gated
    assert r["mfu"] is None  # no peak off-TPU
    # FLOPs figure must describe the (shrunk) config it reports
    assert r["train_flops_per_token"] == bench._lm_train_flops_per_token(
        d=32, layers=1, t=32, vocab=64
    )
    # MFU cross-check (ISSUE 4 satellite): XLA's own cost analysis of
    # the timed program rides next to the analytic estimate, with the
    # >10% disagreement verdict — no more trust-me arithmetic.
    agree = r["flops_agreement"]
    assert agree["analytic"] == r["train_flops_per_token"]
    assert agree["cost_analysis"] and agree["cost_analysis"] > 0
    assert isinstance(agree["disagrees_over_10pct"], bool)
    import numpy as np

    assert np.isfinite(r["final_loss"])


def test_bench_decode_smoke(monkeypatch):
    monkeypatch.setattr(bench, "LM_VOCAB", 64)
    monkeypatch.setattr(bench, "LM_DMODEL", 32)
    monkeypatch.setattr(bench, "LM_HEADS", 2)
    monkeypatch.setattr(bench, "LM_LAYERS", 1)
    monkeypatch.setattr(bench, "LM_SEQ", 32)
    monkeypatch.setattr(bench, "LM_BATCH", 8)
    monkeypatch.setattr(bench, "MEASURE_REPEATS", 1)
    r = bench.bench_decode()
    assert r["decode_tokens_per_sec_per_chip"] > 0
    assert r["generated_per_pass"] == 8 * 16
    assert r["prompt_len"] == 16


def test_bench_ours_smoke(monkeypatch):
    monkeypatch.setattr(bench, "CHUNK_STEPS", 3)
    monkeypatch.setattr(bench, "MEASURE_CHUNKS", 2)
    monkeypatch.setattr(bench, "MEASURE_REPEATS", 2)
    r = bench.bench_ours()
    # Headline + the distribution the artifact contract promises
    # (VERDICT r4 item 4: median, p10/p90, per-pass rates).
    assert r["samples_per_sec_per_chip"] > 0
    assert len(r["pass_samples_per_sec_per_chip"]) == r["passes"] == 2
    assert 0 < r["p10"] <= r["p90"]
    # Cost-analysis cross-check of the flagship MFU numerator.
    agree = r["flops_agreement"]
    assert agree["analytic"] == bench._train_flops_per_sample()
    assert agree["cost_analysis"] and agree["cost_analysis"] > 0


def test_kernel_smoke_all_pass():
    # Off-TPU this runs the kernels in interpret mode — semantics-only
    # proof, but it must agree with the XLA reference in BOTH dtypes for
    # every kernel, fwd and bwd (the suite banks these verdicts).
    r = bench.bench_kernel_smoke()
    assert r["platform"] == "cpu"
    for name in ("fused_elbo_f32", "fused_elbo_bf16",
                 "flash_attention_f32", "flash_attention_bf16",
                 "flash_attention_pad_f32"):
        assert r[name]["ok"], f"{name}: {r[name].get('error')}"


def test_bench_stacked_smoke(monkeypatch):
    monkeypatch.setattr(bench, "STACKED_TRIALS", 2)
    monkeypatch.setattr(bench, "STACKED_LEVELS", (1, 2))
    monkeypatch.setattr(bench, "STACKED_MEASURE_STEPS", 2)
    monkeypatch.setattr(bench, "STACKED_REPEATS", 1)
    r = bench.bench_stacked()
    assert r["trials"] == 2
    assert [lvl["k"] for lvl in r["levels"]] == [1, 2]
    for lvl in r["levels"]:
        assert lvl["samples_per_sec_per_chip"] > 0
        assert lvl["chips_used"] == min(8, 2 // lvl["k"])
        assert lvl["dispatches_per_trial_step"] == round(1 / lvl["k"], 4)
        assert lvl["speedup_vs_k1"] > 0
    assert r["k4_vs_k1"] is None  # no K=4 level in the shrunk sweep
    assert "cpu_caveat" in r  # the virtual-device methodology caveat


def test_flagship_cpu_history_parses_both_tail_forms(tmp_path, monkeypatch):
    # Prior-round BENCH artifacts arrive in two shapes: a clean JSON
    # line (r02-r04 era; no flagship_passes -> top-level value, implicit
    # chunk 100) and a front-truncated tail where only the
    # flagship_passes object survives (r05 era). Both must parse; a
    # TPU round and a garbage file must not.
    monkeypatch.chdir(tmp_path)
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "tail": json.dumps({
            "metric": "vae_train_samples_per_sec_per_chip",
            "value": 26519.5, "detail": {"platform": "cpu"},
        }) + "\n",
    }))
    (tmp_path / "BENCH_r05.json").write_text(json.dumps({
        "tail": ', "mfu": null, "detail": {"platform": "cpu", '
                '"device_kind": "cpu", "flagship_passes": '
                '{"samples_per_sec_per_chip": 23158.8, "chunk_steps": 100}}}',
    }))
    (tmp_path / "BENCH_r07.json").write_text(json.dumps({
        "tail": json.dumps({
            "value": 12e6, "detail": {"platform": "tpu"},
        }) + "\n",
    }))
    (tmp_path / "BENCH_r08.json").write_text("not json at all")
    hist = bench._flagship_cpu_history()
    assert {(h["samples_per_sec_per_chip"], h["chunk_steps"]) for h in hist} \
        == {(26519.5, 100), (23158.8, 100)}


def test_drift_flag_fires_on_seeded_slowdown():
    history = [
        {"file": "BENCH_r02.json", "samples_per_sec_per_chip": 26000.0,
         "chunk_steps": 100},
        {"file": "BENCH_r03.json", "samples_per_sec_per_chip": 22600.0,
         "chunk_steps": 100},
        {"file": "BENCH_r04.json", "samples_per_sec_per_chip": 22250.0,
         "chunk_steps": 100},
        # different shape: must NOT enter the same-shape comparison
        {"file": "BENCH_rX.json", "samples_per_sec_per_chip": 5.0,
         "chunk_steps": 1},
    ]
    # seeded ~35% slowdown vs the chunk-100 median (22600)
    slow = bench._drift_vs_prev_rounds(22600.0 * 0.65, 100, history)
    assert slow["drift_exceeds_20pct"] is True
    assert slow["median_prior"] == 22600.0
    assert len(slow["prior_rounds"]) == 3  # chunk-1 round excluded
    # in-band move: no flag
    ok = bench._drift_vs_prev_rounds(22600.0 * 1.1, 100, history)
    assert ok["drift_exceeds_20pct"] is False
    # no same-shape priors -> no block at all
    assert bench._drift_vs_prev_rounds(100.0, 777, history) is None


def test_last_tpu_artifact_selection(tmp_path, monkeypatch):
    # Picks the newest real-TPU payload, skips CPU-fallback artifacts,
    # strips triage blobs, and marks the result stale with provenance.
    monkeypatch.chdir(tmp_path)
    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "bench_tpu_old.json").write_text(json.dumps({
        "value": 1.0, "detail": {"platform": "tpu", "tpu_triage": {"x": 1}},
    }))
    (art / "bench_tpu_cpu_fallback.json").write_text(json.dumps({
        "value": 2.0, "detail": {"platform": "cpu"},
    }))
    (art / "bench_tpu_new.json").write_text(json.dumps({
        "value": 3.0,
        "detail": {"backend": {"platform": "tpu", "tpu_triage": {}}},
    }))
    os.utime(art / "bench_tpu_old.json", (1, 1))
    got = bench._last_tpu_artifact()
    assert got["stale"] is True
    assert got["file"].endswith("bench_tpu_new.json")
    assert got["payload"]["value"] == 3.0
    assert "tpu_triage" not in got["payload"]["detail"].get("backend", {})
    assert "captured_utc" in got


def test_last_tpu_artifact_none_when_absent(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    assert bench._last_tpu_artifact() is None


def test_bench_suite_checkpoints_each_section(monkeypatch):
    # A wedged tunnel HANGS mid-suite; sections already captured must
    # have hit the checkpoint before any later section can block.
    for name in ("bench_kernel_smoke", "bench_ours", "bench_to_elbo",
                 "bench_loader", "bench_stacked"):
        monkeypatch.setattr(bench, name, lambda *a, **k: {"ok": 1})
    calls = []
    r = bench.bench_suite(lambda partial: calls.append(set(partial)))
    assert len(calls) == 8  # one checkpoint per section
    assert calls[0] == {"kernel_smoke"}  # cheapest evidence banks first
    assert calls[-1] == set(r)
    # A failing checkpoint must never kill the capture itself.
    def bad_checkpoint(partial):
        raise OSError("disk full")
    r2 = bench.bench_suite(bad_checkpoint)
    assert set(r2) == set(r)


def test_last_tpu_artifact_robust_ranking(tmp_path, monkeypatch):
    # Three hostile-dir cases the selection must survive: a stray
    # non-object JSON file, the mutable _latest alias (newest mtime but
    # not provenance), and a newer DEGRADED tpu capture (value null)
    # that must not shadow an older good one.
    monkeypatch.chdir(tmp_path)
    art = tmp_path / "artifacts"
    art.mkdir()
    (art / "bench_tpu_notes.json").write_text('["not", "an", "artifact"]')
    (art / "bench_tpu_good.json").write_text(json.dumps({
        "value": 5.0, "detail": {"platform": "tpu"},
    }))
    (art / "bench_tpu_degraded.json").write_text(json.dumps({
        "value": None, "detail": {"platform": "tpu"},
    }))
    (art / "bench_tpu_suite_latest.json").write_text(json.dumps({
        "value": 9.0, "detail": {"platform": "tpu"},
    }))
    os.utime(art / "bench_tpu_good.json", (10, 10))
    got = bench._last_tpu_artifact()
    assert got["file"].endswith("bench_tpu_good.json")
    assert got["payload"]["value"] == 5.0
    # With no healthy capture at all, the newest degraded one still
    # surfaces (evidence beats silence).
    (art / "bench_tpu_good.json").unlink()
    got = bench._last_tpu_artifact()
    assert got["file"].endswith("bench_tpu_degraded.json")


@pytest.mark.slow  # spawns a full bench subprocess (~1 min)
def test_cli_emits_one_json_line():
    # The driver contract: stdout is exactly one parseable JSON object
    # with the required keys. Use the cheap loader mode to keep the
    # subprocess fast, and force CPU so no TPU probe runs.
    p = subprocess.run(
        [sys.executable, bench.__file__, "--loader"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "MDT_PLATFORM": ""},
    )
    assert p.returncode == 0, p.stderr[-500:]
    lines = [l for l in p.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1
    d = json.loads(lines[0])
    assert {"metric", "value", "unit", "vs_baseline"} <= set(d)
