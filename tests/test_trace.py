"""End-to-end submission tracing + SLO engine (docs/OBSERVABILITY.md
"Tracing & SLOs").

The honesty contracts under test:

- a SIGKILLed (or just absent) end reconstructs as an OPEN span —
  never a fabricated end;
- a torn journal tail costs exactly the torn record;
- a fabric failover's submission keeps ONE contiguous span tree
  spanning both fence epochs;
- the trace id minted at submit rides spool -> journal -> ledger;
- the SLO engine's burn-rate alerts are edge-triggered and its
  offline histogram evaluation is exact on bucket-aligned thresholds.
"""

import json
import os
import time

import pytest

from multidisttorch_tpu.service import queue as squeue
from multidisttorch_tpu.telemetry import slo as tslo
from multidisttorch_tpu.telemetry import trace as ttrace
from multidisttorch_tpu.telemetry.metrics import Histogram

pytestmark = pytest.mark.trace


def wfile(path, records):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def journal(d, records):
    wfile(os.path.join(d, squeue.QUEUE_NAME), records)


def ledger(d, records):
    wfile(os.path.join(d, "sweep_ledger.jsonl"), records)


def sub_rec(sid, ts, *, tenant="t", trace_id="", epoch=None, **cfg):
    rec = {
        "event": "submitted",
        "sub": {
            "submission_id": sid,
            "tenant": tenant,
            "config": cfg,
            "submit_ts": ts - 0.05,
            **({"trace_id": trace_id} if trace_id else {}),
        },
        "ts": ts,
    }
    if epoch is not None:
        rec["epoch"] = epoch
    return rec


def ev(kind, sid, ts, *, epoch=None, **extra):
    rec = {"event": kind, "submission_id": sid, "ts": ts, **extra}
    if epoch is not None:
        rec["epoch"] = epoch
    return rec


# --------------------------------------------------------------------
# trace ids ride the durable files
# --------------------------------------------------------------------


class TestTraceIds:
    def test_submit_mints_and_spools_trace_id(self, tmp_path):
        d = str(tmp_path)
        client = squeue.SweepClient(d, tenant="a")
        sid = client.submit({"epochs": 1})
        assert client.last_submission.trace_id
        with open(os.path.join(d, "intake", sid + ".json")) as f:
            spooled = json.load(f)
        assert spooled["trace_id"] == client.last_submission.trace_id

    def test_journal_transitions_carry_trace(self, tmp_path):
        d = str(tmp_path)
        client = squeue.SweepClient(d, tenant="a")
        sid = client.submit({"epochs": 1})
        q = squeue.SubmissionQueue(d)
        (sub,) = q.drain_intake(known_ids=set())
        q.admitted(sid, trial_id=0, chash="c", bucket="b")
        q.settled(sid, trial_id=0, status="completed")
        recs = squeue.load_queue(d)
        trace = sub.trace
        assert all(
            r.get("trace") == trace
            for r in recs
            if r.get("event") in ("admitted", "settled")
        )
        folded = squeue.fold_queue(recs)
        assert folded[sid]["trace_id"] == trace

    def test_legacy_records_derive_deterministically(self, tmp_path):
        d = str(tmp_path)
        journal(d, [sub_rec("old-1", 10.0), ev("admitted", "old-1", 10.1,
                                                trial_id=0)])
        folded = squeue.fold_queue(squeue.load_queue(d))
        derived = folded["old-1"]["trace_id"]
        assert derived == ttrace.default_trace_id("old-1")
        assert derived.startswith("d")

    def test_fenced_queue_stamps_epoch(self, tmp_path):
        d = str(tmp_path)
        q = squeue.SubmissionQueue(d, epoch=3)
        q.admitted("s", trial_id=0, chash="c", bucket="b")
        (rec,) = squeue.load_queue(d)
        assert rec["epoch"] == 3


# --------------------------------------------------------------------
# skeleton reconstruction
# --------------------------------------------------------------------


class TestSkeleton:
    def test_full_lifecycle_phases(self, tmp_path):
        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0, trace_id="abc"),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b"),
                ev("placed", "s-1", 101.0, trial_id=0, start=0, size=1,
                   lanes=1, stacked=False, resumed=False),
                ev("settled", "s-1", 105.0, trial_id=0,
                   status="completed"),
            ],
        )
        traces = ttrace.build_submission_traces(d)
        tr = traces["s-1"]
        assert tr["trace_id"] == "abc"
        assert tr["state"] == squeue.SETTLED
        names = [s["name"] for s in tr["spans"]]
        assert names[0].startswith("submission")
        assert "spool_wait" in names and "admission" in names
        assert "queue_wait" in names and "placement #1" in names
        assert tr["open_spans"] == 0 and not tr["orphans"]
        bd = ttrace.latency_breakdown(tr)
        assert bd["total_s"] == pytest.approx(105.0 - 99.95, abs=1e-6)
        assert bd["phase_totals_s"]["queue_wait"] == pytest.approx(0.8)
        comp = ttrace.trace_completeness(traces)
        assert comp["complete"] and comp["settled_complete"] == 1

    def test_sigkill_leaves_honestly_open_spans(self, tmp_path):
        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b"),
                ev("placed", "s-1", 101.0, trial_id=0, start=0, size=1,
                   lanes=1, stacked=False, resumed=False),
                # ... SIGKILL: no further records ever land.
            ],
        )
        tr = ttrace.build_submission_traces(d)["s-1"]
        root = tr["spans"][0]
        placement = next(
            s for s in tr["spans"] if s["name"] == "placement #1"
        )
        assert root["end"] is None and placement["end"] is None
        assert tr["open_spans"] == 2  # root + placement, nothing invented
        bd = ttrace.latency_breakdown(tr)
        prow = next(r for r in bd["spans"] if r["name"] == "placement #1")
        assert prow["open"] and prow["dur_s"] is None
        # A live submission is REPORTED open, never failed:
        comp = ttrace.trace_completeness(
            ttrace.build_submission_traces(d)
        )
        assert comp["complete"] and comp["open_spans_live"] == 2

    def test_torn_journal_tail_drops_only_torn_record(self, tmp_path):
        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b"),
                ev("placed", "s-1", 101.0, trial_id=0, start=0, size=1,
                   lanes=1, stacked=False, resumed=False),
            ],
        )
        # Crash mid-append: half a 'settled' record, no newline.
        with open(os.path.join(d, squeue.QUEUE_NAME), "a") as f:
            f.write('{"event": "settled", "submission_id": "s-1", "sta')
        tr = ttrace.build_submission_traces(d)["s-1"]
        # The torn settle is gone — the trace honestly still shows the
        # submission PLACED with open spans; everything before the tear
        # survives intact.
        assert tr["state"] == squeue.PLACED
        assert tr["open_spans"] == 2
        assert any(s["name"] == "placement #1" for s in tr["spans"])

    def test_rejection_closes_at_admission(self, tmp_path):
        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0),
                ev("rejected", "s-1", 100.3, verdict="rejected_quota",
                   reason="over quota"),
            ],
        )
        traces = ttrace.build_submission_traces(d)
        tr = traces["s-1"]
        assert tr["state"] == squeue.REJECTED
        assert tr["spans"][0]["end"] == 100.3
        assert ttrace.trace_completeness(traces)["complete"]


# --------------------------------------------------------------------
# failover contiguity across fence epochs
# --------------------------------------------------------------------


class TestFailoverContiguity:
    def _failover_journal(self, d):
        journal(
            d,
            [
                sub_rec("s-1", 100.0, trace_id="tr1", epoch=1),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b",
                   epoch=1),
                ev("placed", "s-1", 101.0, trial_id=0, start=0, size=1,
                   lanes=1, stacked=False, resumed=False, epoch=1),
                # SIGKILL here; the adopter (epoch 2) replays:
                ev("unplaced", "s-1", 104.0, trial_id=0,
                   reason="daemon restart recovery", epoch=2),
                ev("placed", "s-1", 104.5, trial_id=0, start=0, size=1,
                   lanes=1, stacked=False, resumed=True, epoch=2),
                ev("settled", "s-1", 108.0, trial_id=0,
                   status="completed", epoch=2),
            ],
        )

    def test_one_contiguous_tree_spanning_epochs(self, tmp_path):
        d = str(tmp_path)
        self._failover_journal(d)
        traces = ttrace.build_submission_traces(d)
        tr = traces["s-1"]
        assert tr["epochs"] == [1, 2]
        assert tr["epoch_takeovers"] == 1
        takeover = next(
            s for s in tr["spans"] if s["name"].startswith("fence_takeover")
        )
        assert takeover["tags"]["from_epoch"] == 1
        assert takeover["tags"]["to_epoch"] == 2
        # First placement CLOSED by the adopter's unplaced record (the
        # truth: the old submesh died with the old daemon), second
        # placement closed by settle — zero open, zero orphans.
        p1, p2 = [
            s for s in tr["spans"] if s["name"].startswith("placement")
        ]
        assert p1["end"] == 104.0 and p1["tags"]["epoch"] == 1
        assert p2["end"] == 108.0 and p2["tags"]["epoch"] == 2
        comp = ttrace.trace_completeness(traces)
        assert comp["complete"]
        assert comp["epoch_takeovers"] == 1
        assert comp["multi_epoch_submissions"] == 1

    def test_ledger_attempts_attach_across_epochs(self, tmp_path):
        d = str(tmp_path)
        self._failover_journal(d)
        ledger(
            d,
            [
                {"event": "attempt_start", "trial_id": 0,
                 "config_hash": "c", "attempt": 1, "trace": "tr1",
                 "ts": 100.9, "epoch": 1},
                # No attempt_end from epoch 1 — the daemon died.
                {"event": "attempt_start", "trial_id": 0,
                 "config_hash": "c", "attempt": 2, "trace": "tr1",
                 "ts": 104.4, "epoch": 2},
                {"event": "attempt_end", "trial_id": 0,
                 "config_hash": "c", "attempt": 2,
                 "status": "completed", "ts": 107.9, "epoch": 2},
            ],
        )
        tr = ttrace.build_submission_traces(d)["s-1"]
        attempts = [
            s
            for s in tr["spans"]
            if s["name"].startswith("attempt") and s["kind"] == "span"
        ]
        assert len(attempts) == 2
        a1, a2 = sorted(attempts, key=lambda s: s["start"])
        # The killed attempt stays OPEN (no invented end) but is NOT an
        # orphan — it attaches to epoch 1's placement.
        assert a1["end"] is None
        assert a2["end"] == 107.9 and a2["tags"]["status"] == "completed"
        assert not tr["orphans"]

    def test_setup_attempt_attaches_to_queue_wait(self, tmp_path):
        """A setup-phase failure ledgers attempts WITHOUT any `placed`
        journal record (the runtime's _setup_failed path): the attempt
        belongs to the queue_wait covering it — not an orphan, and the
        requeue closes the previous wait (no open-span leak)."""
        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b"),
                ev("unplaced", "s-1", 100.6, trial_id=0,
                   reason="setup retry: ValueError: bad dataset"),
                ev("settled", "s-1", 101.0, trial_id=0,
                   status="failed"),
            ],
        )
        ledger(
            d,
            [
                {"event": "attempt_start", "trial_id": 0,
                 "config_hash": "c", "attempt": 1, "ts": 100.3},
                {"event": "attempt_end", "trial_id": 0,
                 "config_hash": "c", "attempt": 1,
                 "status": "retrying", "ts": 100.55},
            ],
        )
        traces = ttrace.build_submission_traces(d)
        tr = traces["s-1"]
        assert not tr["orphans"]
        waits = [s for s in tr["spans"] if s["name"] == "queue_wait"]
        assert len(waits) == 2
        assert waits[0]["end"] == 100.6 and waits[1]["end"] == 101.0
        att = next(
            s for s in tr["spans"] if s["name"].startswith("attempt")
        )
        assert tr["spans"][att["parent"]] is waits[0]
        assert ttrace.trace_completeness(traces)["complete"]

    def test_orphan_attempt_fails_completeness(self, tmp_path):
        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b"),
                ev("settled", "s-1", 101.0, trial_id=0,
                   status="completed"),
            ],
        )
        # An attempt entirely OUTSIDE the submission's window: orphan.
        ledger(
            d,
            [
                {"event": "attempt_start", "trial_id": 0,
                 "config_hash": "c", "attempt": 1, "ts": 200.0},
            ],
        )
        traces = ttrace.build_submission_traces(d)
        assert traces["s-1"]["orphans"]
        comp = ttrace.trace_completeness(traces)
        assert not comp["complete"]
        assert comp["orphan_spans"] == 1


# --------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------


class TestExport:
    def test_perfetto_open_span_has_unmatched_begin(self, tmp_path):
        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b"),
                ev("placed", "s-1", 101.0, trial_id=0, start=0, size=1,
                   lanes=1, stacked=False, resumed=False),
            ],
        )
        trace = ttrace.build_perfetto(ttrace.build_submission_traces(d))
        evs = trace["traceEvents"]
        begins = [e for e in evs if e.get("ph") == "B"]
        ends = [e for e in evs if e.get("ph") == "E"]
        # root + placement open -> two more B than E.
        assert len(begins) - len(ends) == 2
        open_names = {e["name"] for e in begins} - {
            e["name"] for e in ends
        }
        assert "placement #1" in open_names

    def test_export_roundtrip(self, tmp_path):
        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b"),
                ev("placed", "s-1", 101.0, trial_id=0, start=0, size=1,
                   lanes=1, stacked=False, resumed=False),
                ev("settled", "s-1", 102.0, trial_id=0,
                   status="completed"),
            ],
        )
        out = ttrace.export_traces(d, str(tmp_path / "traces"))
        with open(out["spans"]) as f:
            spans = json.load(f)
        assert "s-1" in spans and spans["s-1"]["state"] == "settled"
        with open(out["perfetto"]) as f:
            pf = json.load(f)
        assert pf["traceEvents"]
        assert out["completeness"]["complete"]

    def test_sweep_trace_cli(self, tmp_path, capsys):
        import sys

        sys.path.insert(
            0,
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "tools",
            ),
        )
        import sweep_trace

        d = str(tmp_path)
        journal(
            d,
            [
                sub_rec("s-1", 100.0, trace_id="abc"),
                ev("admitted", "s-1", 100.2, trial_id=0, bucket="b"),
                ev("placed", "s-1", 101.0, trial_id=0, start=0, size=1,
                   lanes=1, stacked=False, resumed=False),
                ev("settled", "s-1", 102.0, trial_id=0,
                   status="completed"),
            ],
        )
        assert sweep_trace.main([d]) == 0
        out = capsys.readouterr().out
        assert "s-1" in out and "abc" in out
        assert sweep_trace.main([d, "s-1"]) == 0
        out = capsys.readouterr().out
        assert "queue_wait" in out and "placement #1" in out
        # Lookup by trace id works too; json shape parses.
        assert sweep_trace.main([d, "abc", "--json"]) == 0
        bd = json.loads(capsys.readouterr().out)
        assert bd["submission_id"] == "s-1"
        assert bd["phase_totals_s"]["queue_wait"] == pytest.approx(0.8)


# --------------------------------------------------------------------
# SLO engine
# --------------------------------------------------------------------


class TestSloEngine:
    def test_latency_compliance_and_budget(self):
        eng = tslo.SloEngine(
            (
                tslo.SloSpec(
                    name="p", kind=tslo.LATENCY,
                    source="placement_latency", threshold_s=1.0,
                    objective=0.9,
                ),
            )
        )
        now = time.time()
        for i in range(8):
            eng.observe_latency("placement_latency", 0.5, ts=now)
        eng.observe_latency("placement_latency", 2.0, ts=now)
        (row,) = eng.evaluate(now=now)["slos"]["p"]
        assert row["total"] == 9 and row["bad"] == 1
        assert row["compliance"] == pytest.approx(8 / 9, abs=1e-4)
        assert not row["met"]  # 0.888 < 0.9
        assert row["budget_spent_frac"] == pytest.approx(
            (1 / 9) / 0.1, abs=0.01
        )

    def test_burn_alert_is_edge_triggered(self):
        from multidisttorch_tpu import telemetry

        spec = tslo.SloSpec(
            name="p", kind=tslo.LATENCY, source="x", threshold_s=1.0,
            objective=0.9, windows=((10.0, 2.0), (60.0, 1.0)),
        )
        eng = tslo.SloEngine((spec,))
        telemetry.configure(None)
        try:
            bus = telemetry.get_bus()
            now = time.time()
            for _ in range(10):
                eng.observe_latency("x", 5.0, ts=now)  # 100% bad
            r1 = eng.evaluate(now=now)
            assert r1["alerting"] and r1["alerts"][0]["slo"] == "p"
            eng.evaluate(now=now)  # still firing: no second event
            fired = [
                e for e in bus.recent() if e.kind == "slo_alert"
            ]
            assert len(fired) == 1
            assert fired[0].data["state"] == "firing"
            # Burn subsides (observations age out of both windows):
            r2 = eng.evaluate(now=now + 120.0)
            assert not r2["alerting"]
            fired = [e for e in bus.recent() if e.kind == "slo_alert"]
            assert len(fired) == 2
            assert fired[1].data["state"] == "resolved"
        finally:
            telemetry.disable()

    def test_gauge_floor_per_label(self):
        eng = tslo.SloEngine(
            (
                tslo.SloSpec(
                    name="g", kind=tslo.GAUGE_FLOOR,
                    source="tenant_goodput", floor=0.8, objective=0.5,
                ),
            )
        )
        now = time.time()
        eng.observe_gauge("tenant_goodput", 0.9, label="a", ts=now)
        eng.observe_gauge("tenant_goodput", 0.7, label="b", ts=now)
        eng.observe_gauge("tenant_goodput", None, label="c", ts=now)
        rows = eng.evaluate(now=now)["slos"]["g"]
        by = {r["label"]: r for r in rows}
        assert set(by) == {"a", "b"}  # None never observed
        assert by["a"]["bad"] == 0 and by["b"]["bad"] == 1

    def test_histogram_evaluation_exact_on_bucket_bounds(self):
        h = Histogram((1.0, 5.0, 60.0))
        for v in (0.5, 0.9, 2.0, 7.0):
            h.observe(v)
        spec = tslo.SloSpec(
            name="p", kind=tslo.LATENCY, source="x", threshold_s=5.0,
            objective=0.5,
        )
        ev_ = tslo.evaluate_histogram(spec, tslo.histogram_dict(h))
        assert ev_["exact"]
        assert ev_["total"] == 4 and ev_["bad"] == 1
        assert ev_["compliance"] == pytest.approx(0.75)
        # Off-bound threshold: conservative, flagged inexact.
        spec2 = tslo.SloSpec(
            name="p2", kind=tslo.LATENCY, source="x", threshold_s=3.0,
            objective=0.5,
        )
        ev2 = tslo.evaluate_histogram(spec2, tslo.histogram_dict(h))
        assert not ev2["exact"]
        assert ev2["bad"] == 2  # the 1..5 bucket counts bad

    def test_default_service_slos_align_with_latency_buckets(self):
        from multidisttorch_tpu.service.runtime import LATENCY_BUCKETS

        for spec in tslo.default_service_slos():
            if spec.kind == tslo.LATENCY:
                assert spec.threshold_s in LATENCY_BUCKETS

    def test_default_loadgen_slos_align_with_virtual_buckets(self):
        from multidisttorch_tpu.service.loadgen import (
            VIRTUAL_LATENCY_BUCKETS,
            default_loadgen_slos,
        )

        for spec in default_loadgen_slos():
            if spec.kind == tslo.LATENCY:
                assert spec.threshold_s in VIRTUAL_LATENCY_BUCKETS


class TestExemplars:
    def test_bucket_keeps_worst_offender(self):
        h = Histogram((1.0, 5.0))
        h.observe(0.2, exemplar="a")
        h.observe(0.9, exemplar="b")
        h.observe(3.0, exemplar="c")
        assert h.exemplars[0] == (0.9, "b")
        got = h.percentile_exemplar(99)
        assert got == {"value_s": 3.0, "id": "c"}
        stats = h.stats()
        assert stats["p99_exemplar"]["id"] == "c"

    def test_stats_shape_unchanged_without_exemplars(self):
        h = Histogram((1.0, 5.0))
        h.observe(0.2)
        assert "exemplars" not in h.stats()
        assert "p99_exemplar" not in h.stats()

    def test_loadgen_banks_full_histogram_and_exact_slo(self):
        from multidisttorch_tpu.service.loadgen import run_loadgen

        r = run_loadgen(n_submissions=1500, seed=3)
        h = r["placement_latency_hist"]
        assert h["count"] == r["placement_latency_s"]["count"]
        assert sum(h["counts"]) == h["count"]
        assert r["slo"]["slos"]["placement_p99_1000s"]["exact"]
        assert r["slo"]["slos"]["deadline_hit_rate"]["exact"]
        # The exact compliance must agree with the scalar p99 within
        # one bucket's resolution.
        if r["placement_latency_s"]["p99"] <= 1000.0:
            assert r["slo"]["slos"]["placement_p99_1000s"]["compliance"] \
                >= 0.98


# --------------------------------------------------------------------
# end-to-end over a real (tiny) service
# --------------------------------------------------------------------


class TestServiceEndToEnd:
    def test_trace_complete_and_slo_books_live_service(self, tmp_path):
        from multidisttorch_tpu import telemetry
        from multidisttorch_tpu.service.runtime import SweepService

        d = str(tmp_path)
        telemetry.configure(os.path.join(d, "telemetry"))
        try:
            client = squeue.SweepClient(d, tenant="alice")
            base = dict(
                batch_size=32, latent_dim=4, log_interval=1000, epochs=1
            )
            ids = [
                client.submit({**base, "hidden_dim": 16, "seed": i})
                for i in range(2)
            ]
            svc = SweepService(d, n_slices=2, max_lanes=2, data_rows=64)
            r = svc.serve(
                exit_when_drained=True, idle_grace_s=0.3, max_wall_s=180
            )
            assert set(r["settled"]) == set(ids)
            books = r["books"]
            # Exemplars in the books name real submissions.
            assert books["queue_wait"]["p99_exemplar"]["id"] in ids
            # SLO block present with the default objectives evaluated.
            assert "placement_p99_5s" in books["slo"]["slos"]
            assert "tenant_goodput_floor" in books["slo"]["slos"]
            (gp,) = books["slo"]["slos"]["tenant_goodput_floor"]
            assert gp["label"] == "alice" and gp["bad"] == 0
        finally:
            telemetry.disable()
        traces = ttrace.build_submission_traces(d)
        comp = ttrace.trace_completeness(traces)
        assert comp["complete"] and comp["settled"] == 2
        # Ledger attempts joined in, trace tags riding the ledger.
        led = squeue.read_jsonl_from(
            os.path.join(d, "sweep_ledger.jsonl"), 0
        )[0]
        ends = [e for e in led if e.get("event") == "attempt_end"]
        assert ends and all(e.get("trace") for e in ends)
        for sid in ids:
            assert any(
                s["name"].startswith("attempt")
                for s in traces[sid]["spans"]
            )

    def test_fenced_failover_trace_contiguity(self, tmp_path):
        """A fenced service dies mid-placement (abandoned, SIGKILL
        shape); a second incarnation (next fencing epoch) adopts the
        same directory, recovers, and settles. The submission's trace
        must be ONE contiguous tree spanning both epochs with zero
        orphans."""
        from multidisttorch_tpu.service.runtime import SweepService

        d = str(tmp_path)
        client = squeue.SweepClient(d, tenant="t")
        sid = client.submit(
            {
                "batch_size": 32,
                "latent_dim": 4,
                "log_interval": 1000,
                "epochs": 2,
                "hidden_dim": 16,
            }
        )
        svc1 = SweepService(
            d, n_slices=1, max_lanes=1, data_rows=64, fence_epoch=1
        )
        t0 = time.time()
        placed = False
        while time.time() - t0 < 60 and not placed:
            svc1.tick()
            placed = any(
                r.get("event") == "placed"
                for r in squeue.load_queue(d)
            )
        assert placed
        # "SIGKILL": no drain, no settle — just stop ticking and drop
        # the generators (join the checkpoint writer so the adopter's
        # scan-back sees a quiet dir).
        for ap in svc1.active.values():
            ap.gen.close()
            ap.run._join_ckpt()
        svc1.store.shutdown()

        svc2 = SweepService(
            d, n_slices=1, max_lanes=1, data_rows=64, fence_epoch=2
        )
        r = svc2.serve(
            exit_when_drained=True, idle_grace_s=0.3, max_wall_s=180
        )
        assert r["settled"].get(sid) == "completed"
        traces = ttrace.build_submission_traces(d)
        tr = traces[sid]
        assert tr["epochs"] == [1, 2]
        assert tr["epoch_takeovers"] >= 1
        comp = ttrace.trace_completeness(traces)
        assert comp["complete"]
        assert comp["multi_epoch_submissions"] == 1
        # The epoch-1 attempt the kill orphaned ends "preempted"-less:
        # it must be attached (placement #1), not an orphan, and the
        # epoch-2 attempt completed.
        attempts = [
            s
            for s in tr["spans"]
            if s["name"].startswith("attempt") and s["kind"] == "span"
        ]
        assert len(attempts) >= 2 and not tr["orphans"]
