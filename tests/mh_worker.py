"""Worker program for the multi-controller integration tests.

Launched as N cooperating processes by ``test_multihost.py`` (M virtual
CPU devices each, both set by the parent — 2x4 and 4x2 worlds today).
Bring-up goes through the framework's own launcher-env path: the parent
sets ``OMPI_COMM_WORLD_SIZE/RANK`` + ``MASTER_ADDR/PORT`` (the
reference's Summit-style environment,
``/root/reference/utils.py:13-16,108-109``) plus ``MH_DEVS_PER_PROC``,
and ``initialize_runtime`` does the rest.

Each mode prints one ``RESULT {json}`` line the parent asserts on.
"""

import json
import os
import sys

_DEVS_PER_PROC = int(os.environ.get("MH_DEVS_PER_PROC", "4"))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_DEVS_PER_PROC}"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    mode, out_dir = sys.argv[1], sys.argv[2]

    import multidisttorch_tpu as mdt
    from multidisttorch_tpu.data.datasets import synthetic_mnist

    nproc, pid = mdt.initialize_runtime()
    want_procs = int(os.environ["OMPI_COMM_WORLD_SIZE"])
    assert nproc == want_procs, f"expected {want_procs} processes, got {nproc}"
    assert len(jax.devices()) == nproc * _DEVS_PER_PROC, jax.devices()

    train = synthetic_mnist(128, seed=0)
    test = synthetic_mnist(32, seed=1)

    if mode == "hpo_split":
        # Two groups of 4 devices: group g is wholly owned by process g.
        # Each process must run exactly its own trial.
        from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo

        configs = [
            TrialConfig(g, epochs=1, batch_size=16, hidden_dim=16,
                        latent_dim=4, lr=1e-3 * (g + 1), seed=g)
            for g in range(2)
        ]
        results = run_hpo(
            configs, train, test, out_dir=out_dir, num_groups=2,
            verbose=False, save_images=False, save_checkpoints=False,
        )
        summary = {
            "pid": pid,
            "local_trials": [r.trial_id for r in results],
            "losses": {r.trial_id: round(r.final_train_loss, 4) for r in results},
            "steps": {r.trial_id: r.steps for r in results},
        }

    elif mode == "hpo_span":
        # ONE group spanning all 8 devices across both processes: the
        # multi-host data path (make_array_from_callback feeding) and
        # writer-process gating under real SPMD.
        from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo

        cfg = TrialConfig(0, epochs=2, batch_size=16, hidden_dim=16,
                          latent_dim=4, fused_steps=3)
        results = run_hpo(
            [cfg], train, test, out_dir=out_dir, num_groups=1,
            verbose=False, save_images=False, save_checkpoints=True,
        )
        r = results[0]
        summary = {
            "pid": pid,
            "final_train_loss": round(r.final_train_loss, 4),
            "final_test_loss": round(r.final_test_loss, 4),
            "steps": r.steps,
            "wrote_metrics": os.path.exists(
                os.path.join(out_dir, "trial-0", "metrics.json")
            ),
            "wrote_ckpt": bool(r.checkpoint),
        }

    elif mode == "resilient_split":
        # Failure isolation with wholly-owned groups: config 1 fails
        # deterministically (model_builder raises on its config on every
        # owner — here group 1's sole owner is process 1); the sweep
        # must complete everywhere with trial 1 marked failed and the
        # elastic queue still serving trial 2 on group 0.
        from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
        from multidisttorch_tpu.models.vae import VAE

        def builder(cfg):
            if cfg.trial_id == 1:
                raise RuntimeError("injected deterministic failure")
            return VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)

        configs = [
            TrialConfig(t, epochs=1, batch_size=16, hidden_dim=16,
                        latent_dim=4, seed=t)
            for t in range(3)
        ]
        results = run_hpo(
            configs, train, test, out_dir=out_dir, num_groups=2,
            verbose=False, save_images=False, save_checkpoints=False,
            model_builder=builder, resilient=True,
        )
        summary = {
            "pid": pid,
            "statuses": {r.trial_id: r.status for r in results},
            "errors": {r.trial_id: (r.error or "")[:120] for r in results},
        }

    elif mode == "resilient_span_io":
        # Failure isolation on a SPANNING submesh with an ASYMMETRIC
        # writer-only failure: the image write raises on the writer
        # process only (trial 0). The epoch-boundary health reduction
        # must make BOTH owner processes kill trial 0 and proceed to
        # trial 1 — the exact scenario that desynchronizes collectives
        # without cross-process agreement.
        from multidisttorch_tpu.hpo import driver as drv
        from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo

        real_save = drv.save_image_grid

        def exploding_save(arr, path, **kw):
            if "trial-0" in path:
                raise OSError("injected writer-only disk failure")
            return real_save(arr, path, **kw)

        drv.save_image_grid = exploding_save
        configs = [
            TrialConfig(t, epochs=2, batch_size=16, hidden_dim=16,
                        latent_dim=4, seed=t)
            for t in range(2)
        ]
        results = run_hpo(
            configs, train, test, out_dir=out_dir, num_groups=1,
            verbose=False, save_images=True, save_checkpoints=False,
            resilient=True,
        )
        summary = {
            "pid": pid,
            "statuses": {r.trial_id: r.status for r in results},
            "errors": {r.trial_id: (r.error or "")[:120] for r in results},
            "trial1_steps": next(
                r.steps for r in results if r.trial_id == 1
            ),
        }

    elif mode == "resilient_span_setup":
        # Asymmetric SETUP failure on a spanning submesh: the model
        # builder raises on process 1 only for trial 0. The setup
        # agreement must keep process 0 from stepping a trial its peer
        # never constructed; both must then run trial 1 to completion.
        from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
        from multidisttorch_tpu.models.vae import VAE

        def builder(cfg):
            if cfg.trial_id == 0 and jax.process_index() == 1:
                raise RuntimeError("injected one-process setup failure")
            return VAE(hidden_dim=cfg.hidden_dim, latent_dim=cfg.latent_dim)

        configs = [
            TrialConfig(t, epochs=1, batch_size=16, hidden_dim=16,
                        latent_dim=4, seed=t)
            for t in range(2)
        ]
        results = run_hpo(
            configs, train, test, out_dir=out_dir, num_groups=1,
            verbose=False, save_images=False, save_checkpoints=False,
            model_builder=builder, resilient=True,
        )
        summary = {
            "pid": pid,
            "statuses": {r.trial_id: r.status for r in results},
            "errors": {r.trial_id: (r.error or "")[:120] for r in results},
        }

    elif mode == "hpo_span_tp":
        # Weight-SHARDED trial on a process-spanning submesh WITH
        # checkpointing: the gather-to-replicated checkpoint path must be
        # dispatched on every owner (round-4 driver fix) — without it the
        # writer's device_get raises on non-addressable shards and the
        # trial dies at the epoch agreement.
        from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
        from multidisttorch_tpu.models.vae import vae_tp_shardings

        cfg = TrialConfig(0, epochs=2, batch_size=16, hidden_dim=16,
                          latent_dim=4)
        results = run_hpo(
            [cfg], train, test, out_dir=out_dir, num_groups=1,
            verbose=False, save_images=False, save_checkpoints=True,
            model_parallel=2,
            param_shardings_builder=lambda t, m: vae_tp_shardings(t),
        )
        r = results[0]
        summary = {
            "pid": pid,
            "status": r.status,
            "final_train_loss": round(r.final_train_loss, 4),
            "final_test_loss": round(r.final_test_loss, 4),
            "steps": r.steps,
            "wrote_ckpt": bool(r.checkpoint),
            "ckpt_exists": os.path.exists(
                os.path.join(out_dir, "trial-0", "state.msgpack")
            ),
        }

    elif mode == "hpo_uneven":
        # UNEVEN OWNERSHIP: carve two 3-device groups out of the first 6
        # devices of a (4 proc x 2 dev) world. Group 0 = devices 0-2
        # (procs 0+1 own 2/1 devices), group 1 = devices 3-5 (procs 1+2
        # own 1/2) — both spanning submeshes with ASYMMETRIC device
        # counts per owner; proc 3 owns nothing and must finish cleanly
        # (the reference orphan-rank scenario, quirk Q5, minus the hang).
        from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
        from multidisttorch_tpu.parallel.mesh import setup_groups

        groups = setup_groups(2, devices=jax.devices()[:6])
        configs = [
            TrialConfig(t, epochs=1, batch_size=12, hidden_dim=16,
                        latent_dim=4, seed=t)
            for t in range(2)
        ]
        results = run_hpo(
            configs, train, test, groups=groups, out_dir=out_dir,
            verbose=False, save_images=False, save_checkpoints=True,
        )
        summary = {
            "pid": pid,
            "local_trials": [r.trial_id for r in results],
            "losses": {
                r.trial_id: round(r.final_train_loss, 6) for r in results
            },
            "steps": {r.trial_id: r.steps for r in results},
            "wrote_ckpt": {
                r.trial_id: bool(r.checkpoint) for r in results
            },
        }

    elif mode in ("lm_sp", "lm_sp_flash"):
        # Sequence parallelism ACROSS PROCESSES: one 64-token context
        # sharded over all 8 devices of the 2-process world; ring
        # attention's K/V blocks cross the process boundary on the
        # ppermute ring. Both processes must train identically.
        # lm_sp_flash runs the same world through the ring-flash path
        # (Pallas-kernel hops, ops/pallas_attention.py) instead.
        import numpy as np
        import optax

        from multidisttorch_tpu.models.transformer import TransformerLM
        from multidisttorch_tpu.ops.pallas_attention import (
            make_ring_flash_attention,
        )
        from multidisttorch_tpu.ops.ring_attention import make_ring_attention
        from multidisttorch_tpu.parallel.mesh import DATA_AXIS, setup_groups
        from multidisttorch_tpu.train.lm import (
            create_lm_state,
            make_lm_train_step,
        )

        (g,) = setup_groups(1)
        make_attn = (
            make_ring_flash_attention if mode == "lm_sp_flash"
            else make_ring_attention
        )
        model = TransformerLM(
            vocab_size=16, d_model=32, num_heads=2, num_layers=2,
            max_len=64, attention=make_attn(g, causal=True),
        )
        tx = optax.adam(3e-3)
        state = create_lm_state(g, model, tx, jax.random.key(0),
                                example_len=64)
        step = make_lm_train_step(g, model, tx, sequence_parallel=True)
        base = np.tile(np.arange(8), 8)[:64]
        tokens_np = np.stack([base, (base + 3) % 8]).astype(np.int32)
        tokens = g.device_put(tokens_np, g.sharding(None, DATA_AXIS))
        losses = []
        for _ in range(25):
            state, m = step(state, tokens)
            losses.append(round(float(m["loss"]), 6))
        summary = {
            "pid": pid,
            "first_loss": losses[0],
            "final_loss": losses[-1],
            "seq_shard_len": 64 // g.size,
        }

    elif mode == "moe_lm_ep_sp":
        # EP x SP across processes: one (data x model) trial spanning
        # both processes — the context shards over the data-axis ring
        # (K/V crossing the process boundary) while the MoE experts
        # shard over the model axis. SPMD identity + learning.
        import numpy as np
        import optax

        from multidisttorch_tpu.models.transformer import (
            MoETransformerLM,
            moe_lm_ep_shardings,
        )
        from multidisttorch_tpu.ops.ring_attention import make_ring_attention
        from multidisttorch_tpu.parallel.mesh import DATA_AXIS, setup_groups
        from multidisttorch_tpu.train.lm import (
            create_lm_state,
            make_lm_train_step,
        )
        from multidisttorch_tpu.train.steps import state_shardings

        (g,) = setup_groups(1, model_parallel=2)
        t = 8 * g.data_size
        model = MoETransformerLM(
            vocab_size=16, d_model=16, num_heads=2, num_layers=1,
            num_experts=2, max_len=t,
            attention=make_ring_attention(g, causal=True,
                                          shard_heads=False),
        )
        tx = optax.adam(3e-3)
        state = create_lm_state(
            g, model, tx, jax.random.key(0), example_len=t,
            param_shardings=moe_lm_ep_shardings(g, model),
        )
        step = make_lm_train_step(
            g, model, tx, sequence_parallel=True,
            shardings=state_shardings(state),
        )
        base = np.tile(np.arange(8), t // 8 + 1)[:t]
        tokens = g.device_put(
            np.stack([base, (base + 3) % 16]).astype(np.int32),
            g.sharding(None, DATA_AXIS),
        )
        losses = []
        for _ in range(25):
            state, m = step(state, tokens)
            losses.append(round(float(m["loss"]), 6))
        w1 = state.params["block_0"]["moe"]["w1"]
        summary = {
            "pid": pid,
            "first_loss": losses[0],
            "final_loss": losses[-1],
            "expert_shard": int(w1.addressable_shards[0].data.shape[0]),
            # measured from the placed array, not recomputed from t —
            # a mis-carved mesh or replicated tokens must show up here
            "seq_shard_len": int(
                tokens.sharding.shard_shape(tokens.shape)[1]
            ),
        }

    elif mode == "elastic_restore_agree":
        # Cross-host restore agreement (docs/RESILIENCE.md "Elastic
        # multi-host"), over a REAL multi-process world and a real
        # keep-last checkpoint lineage. Process 1's VIEW of the newest
        # checkpoint is torn (verification rejects it — the NFS
        # close-to-open race, injected): the min-over-hosts agreement
        # must pull BOTH processes to the earlier step everyone can
        # verify; with healthy views both take the newest; with one
        # host seeing nothing valid, both degrade to scratch; and a
        # participant that never joins becomes a NAMED
        # WedgedCollective within the deadline, never a hang.
        import time as _time

        import numpy as np

        from multidisttorch_tpu.train import checkpoint as ckpt

        path = os.path.join(out_dir, "trial-0", "state.msgpack")
        if pid == 0:
            state = {"w": np.arange(8, dtype=np.float32)}
            ckpt.save_state(
                state, path,
                metadata={"step": 4, "completed_epochs": 1}, keep_last=3,
            )
            ckpt.save_state(
                state, path,
                metadata={"step": 8, "completed_epochs": 2}, keep_last=3,
            )
        mdt.sync_hosts("ckpts written", timeout_s=60)

        real_verify = ckpt.verify_checkpoint

        def set_verify(fn):
            if pid == 1:
                ckpt.verify_checkpoint = fn

        def agree(name, timeout_s=20):
            got = ckpt.agreed_restore_step(
                path, name=name, participants=[0, 1], timeout_s=timeout_s
            )
            return got[0] if got is not None else None

        summary = {"pid": pid}

        def torn_newest(p):
            ok, meta, reason = real_verify(p)
            if ok and meta and int(meta.get("step", 0)) >= 8:
                return False, meta, "simulated torn read (elastic test)"
            return ok, meta, reason

        set_verify(torn_newest)
        summary["torn_agreed"] = agree("t0:a1")
        set_verify(real_verify)
        summary["healthy_agreed"] = agree("t0:a2")
        set_verify(lambda p: (False, None, "all candidates torn"))
        summary["none_agreed"] = agree("t0:a3")
        set_verify(real_verify)
        # No-hang contract: process 1 skips agreement a4 entirely.
        if pid == 0:
            from multidisttorch_tpu.parallel.cluster import (
                WedgedCollective,
            )

            t0w = _time.time()
            try:
                agree("t0:a4", timeout_s=2)
                summary["wedge"] = "no-error"
            except WedgedCollective:
                summary["wedge"] = "WedgedCollective"
            summary["wedge_wait_s"] = round(_time.time() - t0w, 2)
        else:
            summary["wedge"] = "absent"
        mdt.sync_hosts("restore agreement drill done", timeout_s=60)

    elif mode == "pbt":
        # Cross-process exploit moves weights via broadcast_one_to_all;
        # every process must report identical global decisions.
        # Population defaults to 2 (one member per process in the 2x4
        # world); MH_PBT_POP scales it for wider worlds.
        from multidisttorch_tpu.hpo.pbt import PBTConfig, run_pbt

        cfg = PBTConfig(
            population=int(os.environ.get("MH_PBT_POP", "2")),
            generations=2, steps_per_generation=4,
            batch_size=16, hidden_dim=16, latent_dim=4,
            exploit_fraction=0.5, lr_min=1e-4, lr_max=1e-1, seed=0,
        )
        result = run_pbt(cfg, train, test, out_dir=out_dir, verbose=False)
        summary = {
            "pid": pid,
            "best_member": result.best_member,
            "best_eval_loss": round(result.best_eval_loss, 4),
            "final_lrs": [round(v, 8) for v in result.final_lrs],
            "n_exploits": sum(len(g["exploits"]) for g in result.history),
            "scores": [
                {k: round(v, 4) for k, v in g["scores"].items()}
                for g in result.history
            ],
        }

    else:
        raise SystemExit(f"unknown mode {mode}")

    print("RESULT " + json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
