"""Device performance books (ISSUE 4 tentpole): XLA cost analysis
extraction, MFU/roofline math, memory watermarks (allocator stats on
TPU, live-buffer accounting on CPU), and the run-summary contract —
every trial carries ``mfu`` (float, or explicit null WITH a reason)
and ``peak_memory_bytes``."""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from multidisttorch_tpu import telemetry
from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
from multidisttorch_tpu.telemetry import device as tele_device
from multidisttorch_tpu.telemetry import export as tele_export
from multidisttorch_tpu.telemetry import metrics as tele_metrics


@pytest.fixture(autouse=True)
def _telemetry_off_after():
    yield
    telemetry.disable()


def small_configs(n, epochs=1, **kw):
    return [
        TrialConfig(
            trial_id=i, epochs=epochs, batch_size=16, hidden_dim=16,
            latent_dim=4, seed=i, log_interval=10_000, **kw,
        )
        for i in range(n)
    ]


# -- cost analysis extraction ------------------------------------------


def test_compiled_cost_analysis_reports_flops_on_cpu():
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((32, 32))
    ca = tele_device.compiled_cost_analysis(f, (x,))
    assert ca["reason"] is None
    # 32x32 @ 32x32 is 2*32^3 = 65536 matmul FLOPs at minimum.
    assert ca["flops"] >= 2 * 32**3
    assert ca["bytes_accessed"] and ca["bytes_accessed"] > 0


def test_compiled_cost_analysis_unwraps_hook_wrappers():
    from multidisttorch_tpu.train.steps import wrap_step_with_hooks

    f = jax.jit(lambda s, x: s + x.sum())
    hooked = wrap_step_with_hooks(f, before=lambda b: None)
    ca = tele_device.compiled_cost_analysis(
        hooked, (jnp.float32(0.0), jnp.ones((8, 8)))
    )
    assert ca["flops"] is not None and ca["reason"] is None


def test_compiled_cost_analysis_graceful_on_non_lowerable():
    ca = tele_device.compiled_cost_analysis(lambda x: x, (1.0,))
    assert ca["flops"] is None
    assert "not a lowerable" in ca["reason"]


def test_peak_tables():
    assert tele_device.peak_flops_per_chip("TPU v4") == 275e12
    assert tele_device.peak_flops_per_chip("TPU v5e") == 197e12
    assert tele_device.peak_flops_per_chip("cpu") is None
    assert tele_device.peak_membw_per_chip("TPU v4") == pytest.approx(
        1.23e12
    )
    # bench.py delegates to the same table — the two MFU computations
    # cannot drift.
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench.py",
        ),
    )
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    assert bench._peak_flops_per_chip("TPU v4") == 275e12


def test_roofline_classification():
    # intensity 1000 FLOPs/byte >> ridge 275/1.23 ~ 224 -> compute.
    assert tele_device.roofline_class(1e6, 1e3, 275e12, 1.23e12) == (
        tele_device.COMPUTE_BOUND
    )
    # intensity 1 << ridge -> bandwidth.
    assert tele_device.roofline_class(1e3, 1e3, 275e12, 1.23e12) == (
        tele_device.BANDWIDTH_BOUND
    )
    assert tele_device.roofline_class(None, 1e3, 275e12, 1.23e12) is None
    assert tele_device.roofline_class(1e3, 1e3, None, 1.23e12) is None


# -- MFU math over the registry ----------------------------------------


def test_mfu_math_with_known_peak():
    telemetry.configure(None)
    reg = telemetry.get_registry()
    s = reg.step_series("trial-0")
    # Hand-build the books: 100 lane-steps in 2s at 1e9 FLOPs/step on a
    # 4-chip submesh with 1e12 peak -> 50e9 FLOP/s vs 4e12 = 0.0125.
    s.lane_steps, s.steps, s.total_s, s.dispatches = 100, 100, 2.0, 100
    reg.gauge("device_flops_per_lane_step", key="trial-0").set(1e9)
    reg.gauge("device_peak_flops_per_chip", key="trial-0").set(1e12)
    reg.gauge("device_mesh_devices", key="trial-0").set(4)
    books = tele_device.device_books(reg)
    assert books["trial-0"]["mfu"] == pytest.approx(0.0125)
    assert books["trial-0"]["mfu_reason"] is None


def test_mfu_null_reasons():
    telemetry.configure(None)
    reg = telemetry.get_registry()
    s = reg.step_series("trial-1")
    s.lane_steps, s.total_s = 10, 1.0
    # flops but no peak (the CPU shape).
    reg.gauge("device_flops_per_lane_step", key="trial-1").set(1e6)
    books = tele_device.device_books(reg)
    assert books["trial-1"]["mfu"] is None
    assert "peak FLOP/s" in books["trial-1"]["mfu_reason"]
    # no flops at all.
    reg.step_series("trial-2").lane_steps = 5
    books = tele_device.device_books(reg)
    assert books["trial-2"]["mfu"] is None
    assert "cost analysis" in books["trial-2"]["mfu_reason"]


def test_record_step_cost_cache_skips_recompile(monkeypatch):
    """Same cache key + same arg shapes = one AOT analysis: a sweep of
    N same-shape trials (or a retried trial) must not pay N extra
    compiles for identical numbers."""
    telemetry.configure(None)
    calls = {"n": 0}
    real = tele_device.compiled_cost_analysis

    def counting(fn, args, kwargs=None):
        calls["n"] += 1
        return real(fn, args, kwargs)

    monkeypatch.setattr(tele_device, "compiled_cost_analysis", counting)
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((16, 16))
    key = ("single", ("test-shape-bucket",))
    r1 = tele_device.record_step_cost("trial-0", f, (x,), cache_key=key)
    r2 = tele_device.record_step_cost("trial-1", f, (x,), cache_key=key)
    assert calls["n"] == 1  # second record served from the cache
    assert r1["flops_per_lane_step"] == r2["flops_per_lane_step"] > 0
    # A different arg shape is a different program: cache miss.
    tele_device.record_step_cost(
        "trial-2", f, (jnp.ones((32, 32)),), cache_key=key
    )
    assert calls["n"] == 2


def test_memory_watermark_gauge_keeps_max():
    g = tele_metrics.Gauge()
    g.set_max(100)
    g.set_max(50)
    assert g.value == 100
    g.set_max(200)
    assert g.value == 200


def test_sample_memory_live_buffer_fallback():
    """On CPU (memory_stats None) the live-buffer accounting must
    produce a real number covering resident arrays."""
    telemetry.configure(None)
    keep = jax.device_put(jnp.ones((256, 256), jnp.float32))  # 256 KiB
    rec = tele_device.sample_memory(
        "trial-9", [keep.devices().pop()], where="test"
    )
    assert rec["source"] in ("live_buffers", "memory_stats")
    assert rec["bytes_in_use"] >= keep.nbytes
    reg = telemetry.get_registry()
    assert (
        reg.gauge_value("device_peak_memory_bytes", key="trial-9")
        >= keep.nbytes
    )


# -- end-to-end: CPU smoke sweep run-summary contract ------------------


def _smoke_summary(tmp_path, **hpo_kw):
    tdir = str(tmp_path / "tele")
    data = synthetic_mnist(64, seed=0)
    with telemetry.telemetry_run(tdir):
        results = run_hpo(
            small_configs(hpo_kw.pop("n", 2), epochs=2),
            data, None,
            out_dir=str(tmp_path / "out"),
            save_images=False, verbose=False,
            **hpo_kw,
        )
        paths = tele_export.export_all(
            tdir, registry=telemetry.get_registry()
        )
    with open(paths["summary"]) as f:
        return results, json.load(f), paths


def test_run_summary_carries_per_trial_device_books(tmp_path):
    results, summary, paths = _smoke_summary(tmp_path, num_groups=2)
    assert all(r.status == "completed" for r in results)
    assert summary["device_books"]
    for tid in ("0", "1"):
        t = summary["trials"][tid]
        # The acceptance contract: mfu present — a float, or an
        # explicit null with a reason (CPU: no peak FLOP/s table).
        assert "mfu" in t
        if t["mfu"] is None:
            assert t["mfu_reason"]
        assert "peak_memory_bytes" in t
        # CPU live-buffer accounting yields a real watermark.
        assert t["peak_memory_bytes"] and t["peak_memory_bytes"] > 0
        book = summary["device_books"][t["device_series"]]
        # XLA's cost analysis ran on the compiled train step: a real
        # per-step FLOPs figure even on CPU — and a SUBMESH-GLOBAL one.
        # cost_analysis describes the per-device partitioned module
        # (1/n of global on this n-device submesh), so an unscaled
        # figure would fall BELOW the analytic matmul floor: fwd 2*MACs
        # over the 784-16-(4,4)-16-784 stack, train ~ 3x fwd, x batch.
        dims = [(784, 16), (16, 4), (16, 4), (4, 16), (16, 784)]
        floor = 3 * 2 * sum(a * b for a, b in dims) * 16
        assert book["flops_per_step"] and book["flops_per_step"] >= floor


def test_stacked_sweep_books_are_bucket_scoped(tmp_path):
    results, summary, _paths = _smoke_summary(
        tmp_path, n=3, num_groups=1, stack_trials=True, stack_max_lanes=2
    )
    assert [r.status for r in results] == ["completed"] * 3
    assert "bucket-g0" in summary["device_books"]
    book = summary["device_books"]["bucket-g0"]
    assert book["flops_per_step"] and book["flops_per_step"] > 0
    assert book["peak_memory_bytes"] and book["peak_memory_bytes"] > 0
    # Every stacked trial resolves its books through the bucket series.
    for tid in ("0", "1", "2"):
        t = summary["trials"][tid]
        assert t["device_series"] == "bucket-g0"
        assert "mfu" in t and "peak_memory_bytes" in t


def test_trace_has_memory_counter_track(tmp_path):
    _results, _summary, paths = _smoke_summary(tmp_path, num_groups=2)
    with open(paths["trace"]) as f:
        trace = json.load(f)
    counters = [
        e for e in trace["traceEvents"]
        if e.get("ph") == "C" and e["name"].startswith("device_memory[")
    ]
    assert counters, "memory samples must render as a counter track"
    assert all("bytes_in_use" in e["args"] for e in counters)


def test_device_cost_events_reach_the_stream(tmp_path):
    _results, summary, paths = _smoke_summary(tmp_path, num_groups=2)
    events = telemetry.read_events(paths["events"])
    costs = [e for e in events if e["kind"] == "device_cost"]
    assert costs, "each trial's compile site must emit a device_cost"
    d = costs[0]["data"]
    assert d["flops_per_lane_step"] > 0
    assert d["platform"] == "cpu"
