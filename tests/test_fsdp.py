"""ZeRO/FSDP-style param+optimizer sharding over the data axis
(parallel/fsdp.py) — absent from the reference (SURVEY.md §2c), nearly
free via GSPMD. Runs on 8 virtual CPU devices (tests/conftest.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.fsdp import fsdp_param_shardings
from multidisttorch_tpu.parallel.mesh import DATA_AXIS, setup_groups
from multidisttorch_tpu.train.steps import (
    create_train_state,
    make_train_step,
    state_shardings,
)

from jax.sharding import PartitionSpec as P


def test_sharding_rule_splits_large_leaves_only():
    (g,) = setup_groups(1)  # 8-wide data axis
    model = VAE(hidden_dim=32, latent_dim=8)
    params = model.init(
        {"params": jax.random.key(0), "reparam": jax.random.key(0)},
        jnp.zeros((1, 784), jnp.float32),
    )["params"]
    sh = fsdp_param_shardings(g, params)
    # (784, 32) kernel: largest divisible axis (784) sharded
    assert sh["fc1"]["kernel"].spec == P(DATA_AXIS, None)
    # (32,) bias: under min_size -> replicated
    assert sh["fc1"]["bias"].spec == P()
    # (8, 32) kernel (fc3): 256 elements < 1024 -> replicated
    assert sh["fc3"]["kernel"].spec == P()


def test_fsdp_state_is_sharded_including_adam_moments():
    (g,) = setup_groups(1)
    model = VAE(hidden_dim=32, latent_dim=8)
    state = create_train_state(
        g, model, optax.adam(1e-3), jax.random.key(0),
        param_shardings=fsdp_param_shardings(
            g,
            model.init(
                {"params": jax.random.key(0), "reparam": jax.random.key(0)},
                jnp.zeros((1, 784), jnp.float32),
            )["params"],
        ),
    )
    k = state.params["fc1"]["kernel"]
    assert k.shape == (784, 32)
    assert k.addressable_shards[0].data.shape == (98, 32)  # 784/8
    mu = state.opt_state[0].mu["fc1"]["kernel"]
    assert mu.addressable_shards[0].data.shape == (98, 32)


def test_fsdp_tp_composition_shards_both_axes():
    # Megatron + ZeRO-3: on a (4 data x 2 model) submesh the composed
    # rule adds data-axis sharding only on dims the TP spec leaves
    # free, and skips small leaves entirely.
    from multidisttorch_tpu.models.vae import vae_tp_shardings
    from multidisttorch_tpu.parallel.fsdp import fsdp_compose_shardings
    from multidisttorch_tpu.parallel.mesh import MODEL_AXIS

    (g,) = setup_groups(1, model_parallel=2)
    model = VAE(hidden_dim=32, latent_dim=8)
    params = model.init(
        {"params": jax.random.key(0), "reparam": jax.random.key(0)},
        jnp.zeros((1, 784), jnp.float32),
    )["params"]
    sh = fsdp_compose_shardings(g, params, vae_tp_shardings(g))
    # column-parallel fc1 (784, 32): model on dim 1 from TP, data added
    # on the free dim 0 (784 % 4 == 0)
    assert sh["fc1"]["kernel"].spec == P(DATA_AXIS, MODEL_AXIS)
    # row-parallel fc4 (32, 784): model on dim 0, data added on dim 1
    assert sh["fc4"]["kernel"].spec == P(MODEL_AXIS, DATA_AXIS)
    # small leaves keep their base spec untouched
    assert sh["fc3"]["kernel"].spec == vae_tp_shardings(g)["fc3"]["kernel"].spec
    assert sh["fc1"]["bias"].spec == vae_tp_shardings(g)["fc1"]["bias"].spec


def test_fsdp_tp_training_matches_tp_only():
    # The composition is a LAYOUT change, not a math change: training on
    # the same (data x model) submesh with and without the ZeRO layer
    # must produce the same losses.
    from multidisttorch_tpu.models.vae import vae_tp_shardings
    from multidisttorch_tpu.parallel.fsdp import fsdp_compose_shardings

    def losses(compose: bool, steps: int = 3):
        (g,) = setup_groups(1, model_parallel=2)
        model = VAE(hidden_dim=32, latent_dim=8)
        tx = optax.adam(1e-3)
        params = model.init(
            {"params": jax.random.key(0), "reparam": jax.random.key(0)},
            jnp.zeros((1, 784), jnp.float32),
        )["params"]
        sh = vae_tp_shardings(g)
        if compose:
            sh = fsdp_compose_shardings(g, params, sh)
        state = create_train_state(
            g, model, tx, jax.random.key(0), param_shardings=sh
        )
        step = make_train_step(g, model, tx, shardings=state_shardings(state))
        batch = jax.device_put(
            jnp.asarray(
                np.random.default_rng(0)
                .uniform(0, 1, (16, 784))
                .astype(np.float32)
            ),
            g.batch_sharding,
        )
        out = []
        for i in range(steps):
            state, metrics = step(state, batch, jax.random.key(i))
            out.append(float(metrics["loss_sum"]))
        return out

    np.testing.assert_allclose(losses(True), losses(False), rtol=1e-5)


def test_fsdp_training_matches_replicated_dp():
    def losses(fsdp: bool, steps: int = 4):
        (g,) = setup_groups(1)
        model = VAE(hidden_dim=32, latent_dim=8)
        tx = optax.adam(1e-3)
        if fsdp:
            params = model.init(
                {"params": jax.random.key(0), "reparam": jax.random.key(0)},
                jnp.zeros((1, 784), jnp.float32),
            )["params"]
            state = create_train_state(
                g, model, tx, jax.random.key(0),
                param_shardings=fsdp_param_shardings(g, params),
            )
            shardings = state_shardings(state)
        else:
            state = create_train_state(g, model, tx, jax.random.key(0))
            shardings = None
        step = make_train_step(g, model, tx, shardings=shardings)
        batch = jax.device_put(
            jnp.asarray(
                np.random.default_rng(0)
                .uniform(0, 1, (16, 784))
                .astype(np.float32)
            ),
            g.batch_sharding,
        )
        out = []
        for i in range(steps):
            state, m = step(
                state, batch, jax.random.fold_in(jax.random.key(7), i)
            )
            out.append(float(m["loss_sum"]))
        return out

    np.testing.assert_allclose(losses(False), losses(True), rtol=2e-4)


def test_fsdp_lm_training_matches_replicated():
    # The LM family through the same FSDP recipe: params + Adam moments
    # sharded over the data axis, identical training to replicated.
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.train.lm import create_lm_state, make_lm_train_step

    tokens_np = np.random.default_rng(3).integers(
        0, 32, (8, 16), dtype=np.int32
    )

    def losses(fsdp: bool, steps: int = 3):
        (g,) = setup_groups(1)
        model = TransformerLM(
            vocab_size=32, d_model=32, num_heads=2, num_layers=2, max_len=16
        )
        tx = optax.adam(1e-3)
        psh = None
        if fsdp:
            params = model.init(
                {"params": jax.random.key(0)}, jnp.zeros((1, 16), jnp.int32)
            )["params"]
            psh = fsdp_param_shardings(g, params)
        state = create_lm_state(
            g, model, tx, jax.random.key(0), example_len=16,
            param_shardings=psh,
        )
        sh = state_shardings(state) if fsdp else None
        if fsdp:
            # the embedding table is physically split over the data
            # axis (whichever dim the size rule picked)
            e = state.params["tok_embed"]["embedding"]
            assert DATA_AXIS in tuple(e.sharding.spec)
            import math

            assert math.prod(
                e.addressable_shards[0].data.shape
            ) * 8 == math.prod(e.shape)
        step = make_lm_train_step(g, model, tx, shardings=sh)
        toks = jax.device_put(jnp.asarray(tokens_np), g.batch_sharding)
        out = []
        for _ in range(steps):
            state, m = step(state, toks)
            out.append(float(m["loss"]))
        return out

    np.testing.assert_allclose(losses(False), losses(True), rtol=2e-4)
