"""PBT tests (BASELINE.md config 5): exploit/explore across submeshes."""

import jax
import numpy as np
import pytest

from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.hpo.pbt import PBTConfig, _set_lr, run_pbt
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import setup_groups


def _cfg(**kw):
    defaults = dict(
        population=4,
        generations=2,
        steps_per_generation=4,
        batch_size=16,
        hidden_dim=16,
        latent_dim=4,
        seed=0,
    )
    defaults.update(kw)
    return PBTConfig(**defaults)


def test_set_lr_mutates_without_recompile():
    import optax

    from multidisttorch_tpu.train.steps import create_train_state, make_train_step

    trial = setup_groups(8)[0]
    model = VAE(hidden_dim=16, latent_dim=4)
    tx = optax.inject_hyperparams(optax.adam)(learning_rate=1e-3)
    state = create_train_state(trial, model, tx, jax.random.key(0))
    step = make_train_step(trial, model, tx)
    batch = jax.numpy.asarray(synthetic_mnist(16, seed=0).images)
    state, _ = step(state, batch, jax.random.key(1))
    state = _set_lr(state, 5e-3)
    assert float(state.opt_state.hyperparams["learning_rate"]) == pytest.approx(5e-3)
    # same compiled step keeps working after the mutation
    state, m = step(state, batch, jax.random.key(2))
    assert np.isfinite(float(m["loss_sum"]))


def test_pbt_runs_and_improves(tmp_path):
    train = synthetic_mnist(128, seed=0)
    evals = synthetic_mnist(32, seed=1)
    result = run_pbt(
        _cfg(generations=3), train, evals, out_dir=str(tmp_path), verbose=False
    )
    assert result.best_member >= 0
    assert np.isfinite(result.best_eval_loss)
    assert len(result.history) == 3
    assert (tmp_path / "pbt.json").exists()
    # eval scores should not get worse over generations
    first = min(result.history[0]["scores"].values())
    last = min(result.history[-1]["scores"].values())
    assert last <= first


def test_pbt_swaps_model_family(tmp_path):
    # model_builder generalizes the population's architecture, same
    # contract as run_hpo: a ConvVAE population trains and scores
    # through the shared VAE-family steps.
    from multidisttorch_tpu.data.datasets import synthetic_cifar10
    from multidisttorch_tpu.models.conv_vae import ConvVAE

    train = synthetic_cifar10(64, seed=0)
    evals = synthetic_cifar10(16, seed=1)
    result = run_pbt(
        _cfg(population=2, generations=2, batch_size=8),
        train,
        evals,
        out_dir=str(tmp_path),
        verbose=False,
        model_builder=lambda cfg: ConvVAE(
            base_channels=4, latent_dim=cfg.latent_dim
        ),
    )
    assert np.isfinite(result.best_eval_loss)
    assert len(result.history) == 2


def test_pbt_exploit_transfers_weights():
    # Force an extreme population: one good lr, rest catastrophically
    # high; exploiters must copy the good member's weights + lr.
    train = synthetic_mnist(64, seed=0)
    evals = synthetic_mnist(32, seed=1)
    cfg = _cfg(
        population=2,
        generations=1,
        steps_per_generation=6,
        exploit_fraction=0.5,
        lr_min=1e-4,
        lr_max=1e-1,
    )
    result = run_pbt(cfg, train, evals, verbose=False)
    exploits = result.history[0]["exploits"]
    if exploits:  # exploit fires unless rankings tie
        assert exploits[0]["from"] != exploits[0]["to"]
        assert cfg.lr_min <= exploits[0]["new_lr"] <= cfg.lr_max


def test_pbt_population_group_mismatch():
    train = synthetic_mnist(64, seed=0)
    with pytest.raises(ValueError, match="population"):
        run_pbt(
            _cfg(population=2), train, train, groups=setup_groups(4)
        )
