"""tools/profile_dispatch.py protocol tests: the round-6 fields that
keep compile and device backpressure out of the dispatch percentiles
(docs/DISPATCH.md — the round-5 level-1 p99 anomaly's fix)."""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)

import profile_dispatch as pd  # noqa: E402


def test_measure_separates_compile_and_backpressure(monkeypatch):
    monkeypatch.setattr(pd, "CHUNK_STEPS", 2)
    r = pd.measure(1, rounds=3, trace_dir=None, queue_depth=1)
    # the attribution fields the r6 protocol promises
    assert {"compile_s", "backpressure_s_total", "queue_depth",
            "dispatch_ms_p50", "dispatch_ms_p99",
            "host_dispatch_share_of_wall",
            "backpressure_share_of_wall"} <= set(r)
    assert r["queue_depth"] == 1
    assert r["compile_s"] > 0  # compile happened, outside the window
    assert r["dispatches"] == 3
    assert r["backpressure_s_total"] >= 0
    # shares are fractions of the same wall clock
    assert 0 <= r["host_dispatch_share_of_wall"] <= 1.05
    assert 0 <= r["backpressure_share_of_wall"] <= 1.05
    assert r["samples_per_sec_per_trial"] > 0
