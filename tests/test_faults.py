"""Chaos drills: deterministic fault injection against run_hpo's trial
supervision — retry-with-resume, divergence classification, stacked
lane recovery, and the crash-safe sweep ledger. Every path here is the
CI face of the acceptance contract in docs/RESILIENCE.md."""

import json
import os

import numpy as np
import pytest

from multidisttorch_tpu.data.datasets import synthetic_mnist
from multidisttorch_tpu.faults import (
    CKPT_CORRUPT,
    CRASH,
    DATA_ERROR,
    DIVERGE,
    PREEMPT,
    SLOW,
    FaultPlan,
    FaultSpec,
    HostPreemption,
)
from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
from multidisttorch_tpu.hpo.ledger import LEDGER_NAME, SweepLedger
from multidisttorch_tpu.hpo.supervision import RetryPolicy

pytestmark = pytest.mark.chaos

# 128 rows / batch 16 = 8 optimizer steps per epoch, everywhere below.
STEPS_PER_EPOCH = 8


def _cfg(trial_id, **kw):
    defaults = dict(
        trial_id=trial_id,
        epochs=3,
        batch_size=16,
        hidden_dim=32,
        latent_dim=8,
        log_interval=10_000,
        seed=trial_id,
    )
    defaults.update(kw)
    return TrialConfig(**defaults)


@pytest.fixture(scope="module")
def data():
    return synthetic_mnist(128, seed=0)


def _sweep(configs, data, out_dir, **kw):
    base = dict(
        num_groups=1,
        out_dir=str(out_dir),
        verbose=False,
        save_images=False,
        resilient=True,
        retry=RetryPolicy(max_retries=2, backoff_base_s=0.01),
    )
    base.update(kw)
    return run_hpo(configs, data, None, **base)


def _events(out_dir, trial_id=None, status=None):
    evs = SweepLedger(str(out_dir)).load()
    if trial_id is not None:
        evs = [e for e in evs if e.get("trial_id") == trial_id]
    if status is not None:
        evs = [e for e in evs if e.get("status") == status]
    return evs


def test_fault_plan_roundtrip_and_validation():
    plan = FaultPlan.standard([0, 1, 2, 3, 4, 5], seed=7)
    again = FaultPlan.from_json(plan.to_json())
    assert again == plan
    assert {s.kind for s in plan.specs} == {
        CRASH, DATA_ERROR, CKPT_CORRUPT, SLOW, DIVERGE, PREEMPT
    }
    # the parity control: the last trial carries no faults
    assert not plan.for_trial(5)
    # determinism in the seed
    assert FaultPlan.standard([0, 1, 2], seed=7) == FaultPlan.standard(
        [0, 1, 2], seed=7
    )
    with pytest.raises(ValueError, match="unknown fault kind"):
        FaultSpec("meteor", 0, step=1)
    with pytest.raises(ValueError, match="epoch"):
        FaultSpec(CKPT_CORRUPT, 0)  # epoch-scoped kind needs epoch
    with pytest.raises(ValueError, match="step"):
        FaultSpec(CRASH, 0)  # step-scoped kind needs step


def test_failure_classification_contract():
    from multidisttorch_tpu.hpo.supervision import (
        DIVERGENCE,
        INFRA,
        PREEMPTION,
        classify_failure,
    )
    from multidisttorch_tpu.train.guards import DivergenceError

    assert classify_failure(RuntimeError("worker died")) == INFRA
    assert classify_failure(OSError("disk full")) == INFRA
    assert classify_failure(DivergenceError("loss", float("nan"))) == DIVERGENCE
    assert classify_failure(HostPreemption("gone")) == PREEMPTION
    # An expired agreement deadline = a lost peer: the submesh can no
    # longer be trusted, so this must NOT be an infra retry...
    from multidisttorch_tpu.parallel.cluster import AgreementTimeout

    assert classify_failure(AgreementTimeout("agreement expired")) == PREEMPTION
    # ...but a BARE TimeoutError is a transient I/O fault (socket.timeout
    # IS TimeoutError on 3.10+) and must stay retryable.
    import socket

    assert classify_failure(TimeoutError("nfs hiccup")) == INFRA
    assert classify_failure(socket.timeout("slow read")) == INFRA


def test_injected_crash_retried_resumes_bit_identical(tmp_path, data):
    # THE tentpole contract: a mid-epoch-2 crash retries from the
    # epoch-1 checkpoint and the final metrics are bit-identical to the
    # fault-free run (between-checkpoint faults cost replay, not
    # correctness).
    clean = _sweep([_cfg(0)], data, tmp_path / "clean")[0]
    plan = FaultPlan(specs=(FaultSpec(CRASH, 0, step=STEPS_PER_EPOCH + 3),))
    (r,) = _sweep([_cfg(0)], data, tmp_path / "chaos", fault_plan=plan)
    assert r.status == "completed"
    assert r.attempt == 2
    assert r.steps == 3 * STEPS_PER_EPOCH
    assert r.final_train_loss == clean.final_train_loss  # bitwise
    # ledger shows the attempt history: retrying -> completed
    assert [e["status"] for e in _events(tmp_path / "chaos", 0)
            if e["event"] == "attempt_end"] == ["retrying", "completed"]
    # the retry resumed from the epoch-1 checkpoint, not step 0
    done = _events(tmp_path / "chaos", 0, "completed")[0]
    assert done["summary"]["resumed_from_step"] == STEPS_PER_EPOCH


def test_data_error_recovered_and_slow_survives(tmp_path, data):
    clean = _sweep([_cfg(0)], data, tmp_path / "clean")[0]
    plan = FaultPlan(specs=(
        FaultSpec(DATA_ERROR, 0, step=STEPS_PER_EPOCH + 2),
        FaultSpec(SLOW, 0, step=2, delay_s=0.05),
    ))
    (r,) = _sweep([_cfg(0)], data, tmp_path / "chaos", fault_plan=plan)
    assert r.status == "completed" and r.attempt == 2
    assert r.final_train_loss == clean.final_train_loss
    assert "DataFault" in _events(tmp_path / "chaos", 0, "retrying")[0]["error"]


def test_divergence_is_terminal_not_retried(tmp_path, data):
    # NaN-poisoned batch -> genuinely non-finite loss through the real
    # compiled step -> classified terminal: status diverged, ONE
    # attempt, no infra retry burned, sweep alive for the other trial.
    plan = FaultPlan(specs=(FaultSpec(DIVERGE, 0, step=2),))
    results = _sweep(
        [_cfg(0), _cfg(1)], data, tmp_path, fault_plan=plan
    )
    by_id = {r.trial_id: r for r in results}
    assert by_id[0].status == "diverged"
    assert by_id[0].attempt == 1
    assert "non-finite" in by_id[0].error
    assert by_id[0].steps == STEPS_PER_EPOCH  # detected at epoch boundary
    assert by_id[1].status == "completed"
    assert np.isfinite(by_id[1].final_train_loss)
    assert not _events(tmp_path, 0, "retrying")


def test_retry_budget_exhaustion_fails_trial_only(tmp_path, data):
    # A permanent fault (max_fires > budget) exhausts retries: the
    # trial fails with its attempt history on record; the sweep
    # continues (resilient) and the healthy trial completes.
    plan = FaultPlan(specs=(
        FaultSpec(CRASH, 0, step=2, max_fires=10),
    ))
    results = _sweep(
        [_cfg(0), _cfg(1)], data, tmp_path,
        fault_plan=plan, retry=RetryPolicy(max_retries=1, backoff_base_s=0.01),
    )
    by_id = {r.trial_id: r for r in results}
    assert by_id[0].status == "failed"
    assert by_id[0].attempt == 2  # initial + 1 retry
    assert by_id[1].status == "completed"
    ends = [e["status"] for e in _events(tmp_path, 0)
            if e["event"] == "attempt_end"]
    assert ends == ["retrying", "failed"]


def test_resume_integrity_guard_not_defeated_by_retry(tmp_path, data):
    # The strict-resume config guard is a deliberate hard stop for a
    # HUMAN; supervision must not classify it infra and scan-retry over
    # the checkpoint the guard protected.
    _sweep([_cfg(0, epochs=1, lr=1e-3)], data, tmp_path)
    ckpt = tmp_path / "trial-0" / "state.msgpack"
    before = ckpt.read_bytes()
    # Non-resilient: the guard's ValueError surfaces to the user even
    # with a retry budget armed.
    with pytest.raises(ValueError, match="different\\s+hyperparameters"):
        _sweep(
            [_cfg(0, epochs=2, lr=5e-3)], data, tmp_path,
            resume=True, resilient=False,
        )
    assert ckpt.read_bytes() == before  # old weights untouched
    # Resilient: recorded as failed on attempt 1 — no retry consumed,
    # still no retraining over the guarded checkpoint.
    (r,) = _sweep(
        [_cfg(0, epochs=2, lr=5e-3)], data, tmp_path, resume=True
    )
    assert r.status == "failed" and r.attempt == 2  # numbering continues
    assert "different hyperparameters" in r.error
    assert not _events(tmp_path, 0, "retrying")
    assert ckpt.read_bytes() == before


def test_stacked_bucket_setup_failure_retried(tmp_path, data, monkeypatch):
    # A transient fault in bucket SETUP (loader init) must consult the
    # retry budget like the single-trial setup path — not permanently
    # fail all K member trials.
    import multidisttorch_tpu.hpo.driver as drv

    real = drv.StackedTrialDataIterator
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("transient loader init failure")
        return real(*a, **kw)

    monkeypatch.setattr(drv, "StackedTrialDataIterator", flaky)
    configs = [_cfg(i, epochs=1) for i in range(3)]
    results = _sweep(
        configs, data, tmp_path, stack_trials=True, stack_max_lanes=2
    )
    assert calls["n"] >= 2  # first bucket build failed, retry succeeded
    assert all(r.status == "completed" for r in results)


def test_failed_result_reports_executed_steps(tmp_path, data):
    # A budget-exhausted trial's TrialResult carries the work its final
    # attempt actually executed, not zero (parity with the diverged
    # branch; the ledger's progress summaries agree).
    plan = FaultPlan(specs=(
        FaultSpec(CRASH, 0, step=STEPS_PER_EPOCH + 2, max_fires=10),
    ))
    (r,) = _sweep(
        [_cfg(0)], data, tmp_path, fault_plan=plan,
        retry=RetryPolicy(max_retries=1, backoff_base_s=0.01),
    )
    assert r.status == "failed"
    # crashed mid-epoch-2 every attempt: one full epoch + 2 steps ran
    assert r.steps == STEPS_PER_EPOCH + 2
    failed_ev = _events(tmp_path, 0, "failed")[0]
    assert failed_ev["summary"]["steps_at_failure"] == STEPS_PER_EPOCH + 2


def test_no_retry_policy_preserves_plain_failure(tmp_path, data):
    # Without retry= the PR-1 semantics hold: one attempt, failed.
    plan = FaultPlan(specs=(FaultSpec(CRASH, 0, step=2),))
    (r,) = _sweep([_cfg(0)], data, tmp_path, fault_plan=plan, retry=None)
    assert r.status == "failed" and r.attempt == 1


def test_corrupt_checkpoint_scanned_past_on_retry(tmp_path, data):
    # Epoch-2's primary checkpoint rots AFTER its retention copy was
    # taken; a crash in epoch 3 forces a retry whose scan rejects the
    # corrupt primary (CRC) and resumes from the epoch-2 version copy
    # (keep_last=2) — losing nothing but the crashed epoch's partial
    # work, and staying bit-identical.
    clean = _sweep([_cfg(0)], data, tmp_path / "clean")[0]
    plan = FaultPlan(specs=(
        FaultSpec(CKPT_CORRUPT, 0, epoch=2),
        FaultSpec(CRASH, 0, step=2 * STEPS_PER_EPOCH + 3),
    ))
    (r,) = _sweep(
        [_cfg(0)], data, tmp_path / "chaos",
        fault_plan=plan, ckpt_keep_last=2,
    )
    assert r.status == "completed" and r.attempt == 2
    assert r.final_train_loss == clean.final_train_loss
    done = _events(tmp_path / "chaos", 0, "completed")[0]
    assert done["summary"]["resumed_from_step"] == 2 * STEPS_PER_EPOCH


def test_corrupt_only_checkpoint_retries_from_scratch(tmp_path, data):
    # keep_last=1 (default): the only checkpoint rots, the scan finds
    # nothing valid, and recovery degrades to a from-scratch retry —
    # degraded, never wedged.
    clean = _sweep([_cfg(0)], data, tmp_path / "clean")[0]
    plan = FaultPlan(specs=(
        FaultSpec(CKPT_CORRUPT, 0, epoch=1),
        FaultSpec(CRASH, 0, step=STEPS_PER_EPOCH + 3),
    ))
    (r,) = _sweep([_cfg(0)], data, tmp_path / "chaos", fault_plan=plan)
    assert r.status == "completed" and r.attempt == 2
    assert r.final_train_loss == clean.final_train_loss
    done = _events(tmp_path / "chaos", 0, "completed")[0]
    assert done["summary"]["resumed_from_step"] == 0


def test_preemption_propagates_and_restart_skips_completed(tmp_path, data):
    # The driver-death half: HostPreemption escapes run_hpo even under
    # resilient=True; the restarted sweep (same out_dir, resume=True)
    # skips the ledger-settled trial WITHOUT re-running it and finishes
    # only the interrupted one.
    from multidisttorch_tpu.faults.inject import FaultInjector

    plan = FaultPlan(specs=(
        FaultSpec(PREEMPT, 1, step=STEPS_PER_EPOCH + 2),
    ))
    injector = FaultInjector(plan)
    with pytest.raises(HostPreemption):
        _sweep([_cfg(0), _cfg(1)], data, tmp_path, fault_plan=injector)
    # trial 0 settled before the preemption (single group, FIFO order)
    settled = SweepLedger(str(tmp_path)).finished()
    assert len(settled) == 1

    results = _sweep(
        [_cfg(0), _cfg(1)], data, tmp_path,
        fault_plan=injector, resume=True,
    )
    by_id = {r.trial_id: r for r in results}
    assert by_id[0].status == "resumed_complete"
    assert by_id[0].attempt == 1  # never re-attempted after restart
    assert by_id[0].steps == 3 * STEPS_PER_EPOCH
    assert np.isfinite(by_id[0].final_train_loss)
    assert by_id[1].status == "completed"
    # the restarted attempt resumed trial 1 from its epoch-1 checkpoint
    done = _events(tmp_path, 1, "completed")[0]
    assert done["summary"]["resumed_from_step"] == STEPS_PER_EPOCH
    # and the interrupted attempt is on record
    assert _events(tmp_path, 1, "preempted")


def test_restart_reruns_nothing_when_everything_settled(tmp_path, data):
    _sweep([_cfg(0), _cfg(1)], data, tmp_path)
    ledger_size = os.path.getsize(tmp_path / LEDGER_NAME)
    results = _sweep([_cfg(0), _cfg(1)], data, tmp_path, resume=True)
    assert all(r.status == "resumed_complete" for r in results)
    assert all(r.steps == 3 * STEPS_PER_EPOCH for r in results)
    # pure ledger skip: no new attempts were even started
    starts = [e for e in _events(tmp_path)
              if e["event"] == "attempt_start"]
    assert len(starts) == 2
    assert os.path.getsize(tmp_path / LEDGER_NAME) == ledger_size


def test_ledger_tolerates_torn_tail(tmp_path, data):
    _sweep([_cfg(0)], data, tmp_path)
    path = tmp_path / LEDGER_NAME
    with open(path, "a") as f:
        f.write('{"event": "attempt_end", "trial_id": 0, "config_')  # torn
    led = SweepLedger(str(tmp_path))
    assert led.load()  # decodable prefix survives
    assert len(led.finished()) == 1  # settlement unaffected


def test_stacked_lane_fault_retires_and_refills(tmp_path, data):
    # Lane recovery: a crash scoped to one lane of a stacked bucket
    # retires that lane through mask-and-refill, the other lanes never
    # stop, and the retried trial completes from scratch in a refilled
    # lane. Fault-free lanes stay bit-identical to their own clean run.
    configs = [_cfg(i, epochs=2) for i in range(5)]
    clean = {
        r.trial_id: r
        for r in _sweep(
            configs, data, tmp_path / "clean",
            stack_trials=True, stack_max_lanes=4,
        )
    }
    assert any(r.stacked for r in clean.values())
    plan = FaultPlan(specs=(FaultSpec(CRASH, 2, step=STEPS_PER_EPOCH + 1),))
    results = _sweep(
        configs, data, tmp_path / "chaos",
        stack_trials=True, stack_max_lanes=4, fault_plan=plan,
    )
    by_id = {r.trial_id: r for r in results}
    assert [by_id[i].status for i in range(5)] == ["completed"] * 5
    assert by_id[2].attempt == 2
    assert by_id[2].final_train_loss == clean[2].final_train_loss
    for i in (0, 1, 3, 4):
        assert by_id[i].attempt == 1
        assert by_id[i].final_train_loss == clean[i].final_train_loss
    assert [e["status"] for e in _events(tmp_path / "chaos", 2)
            if e["event"] == "attempt_end"] == ["retrying", "completed"]


def test_stacked_lane_divergence_is_isolated_and_terminal(tmp_path, data):
    # NaN-poisoned lane batch: exactly that lane diverges (vmap keeps
    # lanes independent), the neighbors' losses stay finite and
    # bit-identical to their clean runs, nothing is retried.
    configs = [_cfg(i, epochs=2) for i in range(5)]
    clean = {
        r.trial_id: r.final_train_loss
        for r in _sweep(
            configs, data, tmp_path / "clean",
            stack_trials=True, stack_max_lanes=4,
        )
    }
    plan = FaultPlan(specs=(FaultSpec(DIVERGE, 1, step=2),))
    results = _sweep(
        configs, data, tmp_path / "chaos",
        stack_trials=True, stack_max_lanes=4, fault_plan=plan,
    )
    by_id = {r.trial_id: r for r in results}
    assert by_id[1].status == "diverged"
    assert by_id[1].attempt == 1
    for i in (0, 2, 3, 4):
        assert by_id[i].status == "completed"
        assert by_id[i].final_train_loss == clean[i]
    assert not _events(tmp_path / "chaos", 1, "retrying")


def test_backoff_does_not_block_other_trials(tmp_path, data):
    # Two trials, one group: trial 0 crashes and backs off for a long
    # window; trial 1 must run during that window, not behind it.
    import time

    plan = FaultPlan(specs=(FaultSpec(CRASH, 0, step=2),))
    t0 = time.time()
    results = _sweep(
        [_cfg(0, epochs=1), _cfg(1, epochs=1)], data, tmp_path,
        fault_plan=plan,
        retry=RetryPolicy(max_retries=1, backoff_base_s=1.5),
    )
    wall = time.time() - t0
    by_id = {r.trial_id: r for r in results}
    assert by_id[0].status == "completed" and by_id[0].attempt == 2
    assert by_id[1].status == "completed"
    # the 1.5s backoff overlapped trial 1's training; the sweep paid it
    # at most once (not serialized behind every queue scan)
    assert wall < 30


def test_fault_injection_off_is_bit_identical_to_clean(tmp_path, data):
    # An armed-but-empty injector must not perturb anything: same
    # losses, same steps, bitwise.
    clean = _sweep([_cfg(0)], data, tmp_path / "a")[0]
    armed = _sweep(
        [_cfg(0)], data, tmp_path / "b", fault_plan=FaultPlan()
    )[0]
    assert armed.final_train_loss == clean.final_train_loss
    assert armed.steps == clean.steps


def test_ledger_disabled_writes_nothing(tmp_path, data):
    _sweep([_cfg(0, epochs=1)], data, tmp_path, ledger=False)
    assert not os.path.exists(tmp_path / LEDGER_NAME)


def test_trial_metrics_json_unchanged_by_supervision(tmp_path, data):
    # The per-trial metrics.json contract survives the supervision
    # layer (downstream tooling parses it).
    plan = FaultPlan(specs=(FaultSpec(CRASH, 0, step=STEPS_PER_EPOCH + 1),))
    (r,) = _sweep([_cfg(0)], data, tmp_path, fault_plan=plan)
    with open(os.path.join(r.out_dir, "metrics.json")) as f:
        metrics = json.load(f)
    assert metrics["trial_id"] == 0
    assert len(metrics["history"]) == 3
