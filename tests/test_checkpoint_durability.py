"""Durable-checkpoint contract (train/checkpoint.py): CRC sidecars,
fsync'd atomic writes, keep-last-K retention, and restore_latest_valid
scanning back past torn/corrupt files."""

import json
import os

import jax
import numpy as np
import optax
import pytest
from flax import serialization

from multidisttorch_tpu.faults.inject import corrupt_file
from multidisttorch_tpu.models.vae import VAE
from multidisttorch_tpu.parallel.mesh import setup_groups
from multidisttorch_tpu.train.checkpoint import (
    checkpoint_candidates,
    restore_latest_valid,
    save_state,
    verify_checkpoint,
)
from multidisttorch_tpu.train.steps import build_train_state


def _state(step=0, seed=0):
    s = build_train_state(
        VAE(hidden_dim=16, latent_dim=4), optax.adam(1e-3), jax.random.key(seed)
    )
    import jax.numpy as jnp

    return s.replace(step=jnp.asarray(step, jnp.int32))


def _params_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    return all(np.array_equal(x, y) for x, y in zip(leaves_a, leaves_b))


def test_crc_sidecar_written_and_verified(tmp_path):
    path = str(tmp_path / "state.msgpack")
    save_state(_state(3), path, metadata={"step": 3})
    ok, meta, reason = verify_checkpoint(path)
    assert ok, reason
    assert meta["_integrity"]["crc32"] == __import__("zlib").crc32(
        open(path, "rb").read()
    )
    assert meta["_integrity"]["nbytes"] == os.path.getsize(path)

    corrupt_file(path)
    ok, _, reason = verify_checkpoint(path)
    assert not ok and "crc32 mismatch" in reason


def test_verify_rejects_torn_size_and_unreadable_sidecar(tmp_path):
    path = str(tmp_path / "state.msgpack")
    save_state(_state(1), path, metadata={"step": 1})
    with open(path, "ab") as f:
        f.write(b"xx")  # grew after the sidecar recorded its length
    ok, _, reason = verify_checkpoint(path)
    assert not ok and "size mismatch" in reason

    save_state(_state(1), path, metadata={"step": 1})
    with open(path + ".json", "w") as f:
        f.write("{not json")
    ok, _, reason = verify_checkpoint(path)
    assert not ok and "sidecar unreadable" in reason


def test_legacy_checkpoint_without_integrity_still_accepted(tmp_path):
    # Pre-CRC sidecars (or none at all) fall back to a structural
    # msgpack check — old checkpoints stay restorable.
    path = str(tmp_path / "state.msgpack")
    save_state(_state(2), path, metadata={"step": 2})
    meta = json.load(open(path + ".json"))
    del meta["_integrity"]
    with open(path + ".json", "w") as f:
        json.dump(meta, f)
    ok, _, reason = verify_checkpoint(path)
    assert ok, reason
    os.remove(path + ".json")
    ok, _, reason = verify_checkpoint(path)
    assert ok, reason


def test_keep_last_retention_prunes_old_versions(tmp_path):
    path = str(tmp_path / "state.msgpack")
    for step in (8, 16, 24, 32):
        save_state(_state(step), path, metadata={"step": step}, keep_last=2)
    cands = checkpoint_candidates(path)
    # primary + the 2 newest versions; steps 8 and 16 pruned
    assert cands[0] == path
    assert [os.path.basename(c) for c in cands[1:]] == [
        "state.msgpack.v0000000032",
        "state.msgpack.v0000000024",
    ]
    assert not os.path.exists(path + ".v0000000008")
    # every retained candidate verifies (sidecars versioned alongside)
    for c in cands:
        ok, _, reason = verify_checkpoint(c)
        assert ok, (c, reason)


def test_restore_latest_valid_scans_past_corruption(tmp_path):
    (g,) = setup_groups(1)
    path = str(tmp_path / "state.msgpack")
    s16, s24 = _state(16, seed=1), _state(24, seed=2)
    save_state(s16, path, metadata={"step": 16, "completed_epochs": 2},
               keep_last=2)
    save_state(s24, path, metadata={"step": 24, "completed_epochs": 3},
               keep_last=2)
    # Bit-rot the primary: its retained version is an independent COPY
    # (not a hard link — shared inodes would garble both names at
    # once), so recovery lands on the SAME generation's version first.
    corrupt_file(path)
    got = restore_latest_valid(_state(), path, g)
    assert got is not None
    restored, meta, used = got
    assert int(meta["step"]) == 24
    assert used.endswith(".v0000000024")
    assert _params_equal(jax.device_get(restored.params), s24.params)
    # Rot that version too: the scan falls through to the previous
    # generation.
    corrupt_file(path + ".v0000000024")
    restored, meta, used = restore_latest_valid(_state(), path, g)
    assert int(meta["step"]) == 16
    assert used.endswith(".v0000000016")
    assert int(jax.device_get(restored.step)) == 16
    assert _params_equal(jax.device_get(restored.params), s16.params)


def test_torn_write_between_state_and_sidecar_falls_back(tmp_path):
    # Satellite regression: a crash landing between the state replace
    # and the sidecar replace leaves new bytes under the old sidecar
    # (whose CRC describes the previous state). restore_latest_valid
    # must fall back cleanly to the retained previous generation — the
    # strict resume path raises on the same artifact.
    (g,) = setup_groups(1)
    path = str(tmp_path / "state.msgpack")
    s8 = _state(8, seed=1)
    save_state(s8, path, metadata={"step": 8, "completed_epochs": 1},
               keep_last=2)
    # Simulate save_state dying after its first os.replace: the state
    # file is replaced with epoch-2 bytes, the sidecar never follows.
    torn = serialization.to_bytes(jax.device_get(_state(16, seed=9)))
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(torn)
    os.replace(tmp, path)

    ok, _, reason = verify_checkpoint(path)
    assert not ok and "crc32 mismatch" in reason
    got = restore_latest_valid(_state(), path, g)
    assert got is not None
    restored, meta, used = got
    assert int(meta["step"]) == 8
    assert _params_equal(jax.device_get(restored.params), s8.params)


def test_restore_latest_valid_none_when_nothing_survives(tmp_path):
    path = str(tmp_path / "state.msgpack")
    save_state(_state(8), path, metadata={"step": 8})  # keep_last=1
    corrupt_file(path)
    (g,) = setup_groups(1)
    assert restore_latest_valid(_state(), path, g) is None
    assert restore_latest_valid(_state(), str(tmp_path / "absent"), g) is None


def test_restore_latest_valid_honors_accept_meta(tmp_path):
    (g,) = setup_groups(1)
    path = str(tmp_path / "state.msgpack")
    save_state(_state(8), path, metadata={"step": 8, "lr": 1e-3},
               keep_last=2)
    save_state(_state(16), path, metadata={"step": 16, "lr": 5e-2},
               keep_last=2)
    got = restore_latest_valid(
        _state(), path, g, accept_meta=lambda m: m.get("lr") == 1e-3
    )
    assert got is not None and int(got[1]["step"]) == 8


def test_save_state_fsyncs_before_replace(tmp_path, monkeypatch):
    # The durability half of the atomicity claim: data must hit the
    # disk BEFORE the rename makes it visible, or power loss can
    # resurrect a torn file through the new name.
    events = []
    real_fsync, real_replace = os.fsync, os.replace
    monkeypatch.setattr(os, "fsync", lambda fd: events.append("fsync"))
    monkeypatch.setattr(
        os, "replace",
        lambda a, b: (events.append("replace"), real_replace(a, b))[1],
    )
    save_state(_state(1), str(tmp_path / "s.msgpack"), metadata={"step": 1})
    # state write: fsync precedes its replace; sidecar likewise
    assert events.index("fsync") < events.index("replace")
    assert events.count("fsync") >= 2  # file syncs for state + sidecar

    events.clear()
    save_state(
        _state(2), str(tmp_path / "s.msgpack"), metadata={"step": 2},
        fsync=False,
    )
    assert "fsync" not in events  # the documented opt-out
