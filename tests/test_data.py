"""Data pipeline tests: datasets, sampler semantics (Q1/Q6 fixes)."""

import numpy as np
import pytest

from multidisttorch_tpu.data.datasets import (
    Dataset,
    load_mnist,
    synthetic_cifar10,
    synthetic_mnist,
)
from multidisttorch_tpu.data.sampler import TrialDataIterator
from multidisttorch_tpu.parallel.mesh import setup_groups


def test_synthetic_mnist_deterministic():
    a = synthetic_mnist(100, seed=0)
    b = synthetic_mnist(100, seed=0)
    np.testing.assert_array_equal(a.images, b.images)
    assert a.images.shape == (100, 784)
    assert a.images.min() >= 0.0 and a.images.max() <= 1.0
    assert a.synthetic


def test_synthetic_classes_distinguishable():
    ds = synthetic_mnist(500, seed=0)
    # class means must differ (classifier/VAE can learn structure)
    m0 = ds.images[ds.labels == 0].mean(axis=0)
    m5 = ds.images[ds.labels == 5].mean(axis=0)
    assert np.abs(m0 - m5).max() > 0.05


def test_load_mnist_falls_back_to_synthetic(tmp_path):
    ds = load_mnist(train=True, data_dir=str(tmp_path), synthetic_size=256)
    assert len(ds) == 256
    assert ds.images.shape == (256, 784)


def test_load_mnist_real_idx_fixture_end_to_end():
    # The real-file branch of load_mnist against a COMMITTED genuine
    # IDX pair (tests/fixtures/mnist/, gzipped), written by an
    # independent generator (tests/fixtures/gen_mnist_idx.py) that
    # shares no code with the parser — magic/header parse, gzip path,
    # dtype, /255 normalization, and image↔label pairing are all
    # checked against values recomputed from the generator's formula,
    # not against anything the loader itself produced. This branch had
    # zero executions on real committed files before this fixture.
    import gzip
    import os
    import struct

    fixtures = os.path.join(os.path.dirname(__file__), "fixtures")
    img_gz = os.path.join(fixtures, "mnist", "train-images-idx3-ubyte.gz")
    lbl_gz = os.path.join(fixtures, "mnist", "train-labels-idx1-ubyte.gz")

    # Independent header check: the committed bytes really are IDX.
    with gzip.open(img_gz, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        dims = struct.unpack(">III", f.read(12))
    assert (zero, dtype_code, ndim) == (0, 0x08, 3)
    assert dims == (64, 28, 28)
    with gzip.open(lbl_gz, "rb") as f:
        assert struct.unpack(">HBB", f.read(4)) == (0, 0x08, 1)
        assert struct.unpack(">I", f.read(4)) == (64,)

    ds = load_mnist(train=True, data_dir=fixtures, allow_download=False,
                    allow_synthetic=False)
    assert ds.name == "mnist"
    assert not ds.synthetic
    assert ds.images.shape == (64, 784)
    assert ds.images.dtype == np.float32
    assert ds.labels.dtype == np.int32

    # Values recomputed from the generator's formula — pixel
    # (7i+3r+5c)%256 scaled by /255, label i%10 — at spot coordinates
    # and in bulk.
    def pix(i, r, c):
        return ((7 * i + 3 * r + 5 * c) % 256) / 255.0

    for i, r, c in ((0, 0, 0), (3, 27, 27), (63, 14, 5), (17, 1, 26)):
        assert ds.images[i, r * 28 + c] == np.float32(pix(i, r, c))
    expect = np.array(
        [[pix(i, r, c) for r in range(28) for c in range(28)]
         for i in range(64)],
        np.float32,
    )
    np.testing.assert_array_equal(ds.images, expect)
    np.testing.assert_array_equal(ds.labels, np.arange(64) % 10)
    assert 0.0 <= ds.images.min() and ds.images.max() <= 1.0


def test_load_mnist_idx_roundtrip(tmp_path):
    # Write a tiny IDX pair and check the parser path (the real-MNIST path).
    import struct

    imgs = (np.arange(4 * 28 * 28) % 256).astype(np.uint8).reshape(4, 28, 28)
    labels = np.array([3, 1, 4, 1], np.uint8)
    with open(tmp_path / "train-images-idx3-ubyte", "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 3))
        f.write(struct.pack(">III", 4, 28, 28))
        f.write(imgs.tobytes())
    with open(tmp_path / "train-labels-idx1-ubyte", "wb") as f:
        f.write(struct.pack(">HBB", 0, 0x08, 1))
        f.write(struct.pack(">I", 4))
        f.write(labels.tobytes())
    ds = load_mnist(train=True, data_dir=str(tmp_path))
    assert ds.name == "mnist"
    assert not ds.synthetic
    assert ds.images.shape == (4, 784)
    np.testing.assert_allclose(ds.images.max(), 255 / 255.0)
    np.testing.assert_array_equal(ds.labels, [3, 1, 4, 1])


def test_synthetic_cifar_shape():
    ds = synthetic_cifar10(64, seed=0)
    assert ds.images.shape == (64, 32 * 32 * 3)


class TestTrialDataIterator:
    def test_batches_sharded_on_submesh(self):
        trial = setup_groups(2)[0]
        ds = synthetic_mnist(256, seed=0)
        it = TrialDataIterator(ds, trial, batch_size=32, seed=0)
        batch = next(iter(it.epoch(0)))
        assert batch.shape == (32, 784)
        assert batch.sharding.mesh == trial.mesh  # lands pre-sharded

    def test_epoch_reshuffle_fixes_q6(self):
        # Q6: reference iterates identical order every epoch. We must not.
        trial = setup_groups(8)[0]
        ds = synthetic_mnist(64, seed=0)
        it = TrialDataIterator(ds, trial, batch_size=16, seed=0)
        e0 = np.asarray(next(iter(it.epoch(0))))
        e1 = np.asarray(next(iter(it.epoch(1))))
        e0_again = np.asarray(next(iter(it.epoch(0))))
        assert not np.array_equal(e0, e1)  # different epochs differ
        np.testing.assert_array_equal(e0, e0_again)  # same epoch reproducible

    def test_full_dataset_per_trial_by_default_fixes_q1(self):
        trial = setup_groups(2)[0]
        ds = synthetic_mnist(128, seed=0)
        it = TrialDataIterator(ds, trial, batch_size=32, seed=0)
        assert it.samples_per_epoch == 128  # whole dataset, not 1/ngroups

    def test_legacy_cross_trial_sharding(self):
        # Reference behavior (Q1): trial g sees 1/ngroups of the data.
        groups = setup_groups(2)
        ds = synthetic_mnist(128, seed=0)
        its = [
            TrialDataIterator(
                ds, g, batch_size=32, shard_across_trials=True, num_trials=2
            )
            for g in groups
        ]
        assert all(it.samples_per_epoch == 64 for it in its)
        # shards are disjoint
        rows0 = {tuple(r) for b in its[0].epoch(0) for r in np.asarray(b)}
        rows1 = {tuple(r) for b in its[1].epoch(0) for r in np.asarray(b)}
        assert not rows0 & rows1

    def test_batch_must_divide_devices(self):
        trial = setup_groups(2)[0]  # 4 devices
        ds = synthetic_mnist(64, seed=0)
        with pytest.raises(ValueError, match="divide evenly"):
            TrialDataIterator(ds, trial, batch_size=30)

    def test_dataset_smaller_than_batch_raises(self):
        trial = setup_groups(8)[0]
        ds = synthetic_mnist(8, seed=0)
        with pytest.raises(ValueError, match="smaller than"):
            TrialDataIterator(ds, trial, batch_size=16)

    def test_with_labels(self):
        trial = setup_groups(8)[1]
        ds = synthetic_mnist(64, seed=0)
        it = TrialDataIterator(ds, trial, batch_size=16, with_labels=True)
        imgs, labels = next(iter(it.epoch(0)))
        assert imgs.shape == (16, 784)
        assert labels.shape == (16,)


class TestEpochChunks:
    def test_chunks_match_per_batch_epoch(self):
        # Same permutation, same batch boundaries: chunk[j] must equal
        # batch i0+j of the per-batch iterator for the same epoch.
        ds = synthetic_mnist(80, seed=3)
        trial = setup_groups(2)[0]
        it = TrialDataIterator(ds, trial, 16, seed=5, use_native=False)
        flat = [np.asarray(b) for b in it.epoch(2)]  # 5 batches
        chunked = list(it.epoch_chunks(2, 2))  # 2+2+tail 1
        assert [c[0] for c in chunked] == [0, 2, 4]
        assert [c[1].shape[0] for c in chunked] == [2, 2, 1]
        for i0, chunk in chunked:
            for j in range(chunk.shape[0]):
                np.testing.assert_array_equal(
                    np.asarray(chunk[j]), flat[i0 + j]
                )

    def test_chunks_native_matches_numpy(self):
        from multidisttorch_tpu.data import native

        if not native.available():
            pytest.skip("native fastloader not built")
        ds = synthetic_mnist(64, seed=4)
        trial = setup_groups(4)[1]
        a = TrialDataIterator(ds, trial, 16, seed=7, use_native=False)
        b = TrialDataIterator(ds, trial, 16, seed=7, use_native=True)
        for (ia, ca), (ib, cb) in zip(a.epoch_chunks(1, 3), b.epoch_chunks(1, 3)):
            assert ia == ib
            np.testing.assert_array_equal(np.asarray(ca), np.asarray(cb))

    def test_chunks_with_labels(self):
        ds = synthetic_mnist(48, seed=2)
        trial = setup_groups(8)[0]
        it = TrialDataIterator(
            ds, trial, 8, seed=1, with_labels=True, use_native=False
        )
        chunks = list(it.epoch_chunks(0, 4))
        assert len(chunks) == 2  # 6 batches -> 4 + tail 2
        i0, imgs, labels = chunks[0]
        assert imgs.shape[0] == 4 and labels.shape[0] == 4
        assert imgs.shape[1] == 8 and labels.shape[1] == 8


def test_stream_chunks_crosses_epoch_boundaries():
    # 80 rows / bs 16 = 5 batches per epoch; chunks of 3 must keep
    # coming past the epoch edge, matching the concatenated per-epoch
    # streams batch for batch.
    ds = synthetic_mnist(80, seed=9)
    trial = setup_groups(4)[2]
    it = TrialDataIterator(ds, trial, 16, seed=11, use_native=False)
    stream = it.stream_chunks(3)
    got = [np.asarray(next(stream)) for _ in range(4)]  # 12 batches
    want = [np.asarray(b) for b in it.epoch(0)] + [
        np.asarray(b) for b in it.epoch(1)
    ] + [np.asarray(b) for b in it.epoch(2)]
    flat_got = [batch for chunk in got for batch in chunk]
    for a, b in zip(flat_got, want):
        np.testing.assert_array_equal(a, b)


class TestEvalDataIterator:
    """Full-coverage pad-and-mask eval feed (reference test() consumes
    every row, /root/reference/vae-hpo.py:101-105)."""

    def test_covers_every_row_in_order_with_padding(self):
        from multidisttorch_tpu.data.sampler import EvalDataIterator

        ds = synthetic_mnist(20, seed=1)
        trial = setup_groups(4)[0]  # 2-device data axis
        it = EvalDataIterator(ds, trial, batch_size=8)
        assert it.num_batches == 3 and it.num_rows == 20
        seen, weight_total = [], 0.0
        for imgs, w in it.batches():
            imgs, w = np.asarray(imgs), np.asarray(w)
            assert imgs.shape[0] == 8 and w.shape == (8,)
            seen.append(imgs[w > 0])
            weight_total += w.sum()
            # padding rows are zero
            np.testing.assert_array_equal(imgs[w == 0], 0.0)
        assert weight_total == 20
        np.testing.assert_array_equal(np.concatenate(seen), ds.images)

    def test_smaller_than_one_batch(self):
        from multidisttorch_tpu.data.sampler import EvalDataIterator

        ds = synthetic_mnist(5, seed=2)
        trial = setup_groups(4)[1]
        it = EvalDataIterator(ds, trial, batch_size=16)
        batches = list(it.batches())
        assert len(batches) == 1
        imgs, w = batches[0]
        assert np.asarray(w).sum() == 5

    def test_with_labels(self):
        from multidisttorch_tpu.data.sampler import EvalDataIterator

        ds = synthetic_mnist(10, seed=3)
        trial = setup_groups(8)[0]
        it = EvalDataIterator(ds, trial, batch_size=8, with_labels=True)
        (i1, l1, w1), (i2, l2, w2) = list(it.batches())
        assert np.asarray(l1).shape == (8,)
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(l1)[np.asarray(w1) > 0],
                            np.asarray(l2)[np.asarray(w2) > 0]]),
            ds.labels,
        )

    def test_rejects_indivisible_batch_and_empty(self):
        from multidisttorch_tpu.data.sampler import EvalDataIterator

        ds = synthetic_mnist(10, seed=4)
        trial = setup_groups(4)[0]  # data axis 2
        with pytest.raises(ValueError, match="divide evenly"):
            EvalDataIterator(ds, trial, batch_size=7)
        empty = Dataset(
            images=np.zeros((0, 784), np.float32),
            labels=np.zeros((0,), np.int32),
            name="empty",
        )
        with pytest.raises(ValueError, match="empty"):
            EvalDataIterator(empty, trial, batch_size=8)


def test_chunk_size_validated_eagerly():
    # ADVICE r1: a bad k must raise at the call site, not at first next().
    ds = synthetic_mnist(32, seed=5)
    trial = setup_groups(8)[0]
    it = TrialDataIterator(ds, trial, 8, use_native=False)
    with pytest.raises(ValueError, match="chunk size"):
        it.epoch_chunks(0, 0)
    with pytest.raises(ValueError, match="chunk size"):
        it.stream_chunks(-1)


def test_token_corpus_windows_in_bounds(tmp_path):
    from multidisttorch_tpu.data import byte_corpus, synthetic_corpus

    p = tmp_path / "blob.bin"
    p.write_bytes(bytes(range(256)) * 8)
    c = byte_corpus(str(p))
    assert len(c) == 2048 and c.vocab_size == 256 and not c.synthetic

    rng = np.random.default_rng(0)
    b = c.batch(rng, 16, 64)
    assert b.shape == (16, 64) and b.dtype == np.int32
    assert b.min() >= 0 and b.max() < 256
    # windows really are contiguous slices of the stream
    row = b[3]
    assert ((row[1:] - row[:-1]) % 256 == 1).all()  # file is 0..255 cycle

    s = synthetic_corpus(n=1024, vocab_size=32, period=16, seed=1)
    assert s.synthetic and s.vocab_size == 32
    sb = s.batch(rng, 4, 32)
    assert sb.shape == (4, 32) and sb.max() < 32


def test_token_corpus_too_short_raises(tmp_path):
    from multidisttorch_tpu.data import byte_corpus

    p = tmp_path / "tiny.bin"
    p.write_bytes(b"abc")
    c = byte_corpus(str(p))
    with pytest.raises(ValueError, match="cannot fill"):
        c.batch(np.random.default_rng(0), 1, 8)
