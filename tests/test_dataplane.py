"""Production data plane (docs/DATA.md): dataset references and the
content-addressed cache, heterogeneous stacked lanes, the pipelined
sharded input path, and the per-series input-stall books.

The load-bearing contracts:

- K lanes reading K DIFFERENT datasets through one vmapped dispatch are
  bit-identical to K separate single-lane streams (the PR 1 parity
  contract extended across dataset boundaries);
- the pipelined input path is byte-for-byte the synchronous path, only
  overlapped;
- a corrupt cache entry is quarantined, never loaded;
- service admission NEVER blocks on a dataset load (the prefetch veto);
- the co-pack key carries the dataset's SHAPE CLASS, never its
  identity — no per-dataset bucket splitting.
"""

from __future__ import annotations

import gc
import json
import os
import threading
import time
import zlib

import numpy as np
import pytest

from multidisttorch_tpu.data import store as dstore
from multidisttorch_tpu.data.datasets import Dataset, synthetic_mnist
from multidisttorch_tpu.data.sampler import (
    StackedTrialDataIterator,
    TrialDataIterator,
)
from multidisttorch_tpu.data.store import (
    DatasetStore,
    parse_ref,
    probe_ref,
    register_provider,
    resolve_dataset,
)

pytestmark = pytest.mark.dataplane


@pytest.fixture(autouse=True)
def _fresh_memo():
    # The process-wide RAM memo is deliberately sticky; tests isolate.
    dstore.clear_memo()
    yield
    dstore.clear_memo()


@pytest.fixture(scope="module")
def trial():
    from multidisttorch_tpu.parallel.mesh import setup_groups

    return setup_groups(1)[0]


# --------------------------------------------------------------------
# refs + store
# --------------------------------------------------------------------


class TestRefs:
    def test_parse_variants(self):
        assert parse_ref("synthetic-mnist?rows=64&seed=3") == {
            "kind": "builtin",
            "name": "synthetic-mnist",
            "params": {"rows": "64", "seed": "3"},
        }
        assert parse_ref("builtin:synthetic-mnist")["kind"] == "builtin"
        assert parse_ref("file:/tmp/x.npz") == {
            "kind": "file", "path": "/tmp/x.npz", "name": "/tmp/x.npz",
        }
        assert parse_ref("/tmp/x.npz")["kind"] == "file"
        assert parse_ref("cas:" + "a" * 64)["digest"] == "a" * 64
        assert parse_ref("mnist@sha256:" + "B" * 64)["digest"] == "b" * 64
        with pytest.raises(ValueError):
            parse_ref("")
        with pytest.raises(ValueError):
            parse_ref("builtin:?rows=1")

    def test_cas_digest_rejects_path_traversal(self):
        # A tenant-supplied digest is joined into store paths — only
        # exactly 64 hex chars may pass.
        for bad in (
            "cas:../../../etc/passwd",
            "cas:" + "a" * 63,
            "cas:" + "g" * 64,
            "evil@sha256:../../x",
        ):
            with pytest.raises(ValueError, match="hex"):
                parse_ref(bad)
        assert parse_ref("cas:" + "A" * 64)["digest"] == "a" * 64

    def test_probe_builtin_and_unknown(self):
        assert probe_ref("synthetic-mnist?rows=96") == (784, 96)
        assert probe_ref("synthetic-cifar10?rows=8") == (3072, 8)
        with pytest.raises(ValueError):
            probe_ref("builtin:no-such-provider")

    def test_probe_file_reads_header_only(self, tmp_path):
        p = str(tmp_path / "d.npz")
        ds = synthetic_mnist(48, seed=2)
        np.savez(p, images=ds.images, labels=ds.labels)
        assert probe_ref(f"file:{p}") == (784, 48)

    def test_resolve_memo_returns_same_object(self):
        a = resolve_dataset("synthetic-mnist?rows=32&seed=1")
        b = resolve_dataset("synthetic-mnist?rows=32&seed=1")
        assert a is b  # identity feeds the fused-gather fast path


class TestStore:
    def test_put_get_roundtrip_and_counters(self, tmp_path):
        s = DatasetStore(str(tmp_path))
        ds = synthetic_mnist(40, seed=5)
        digest = s.put_dataset(ds, source_spec="spec-a")
        got = s.get("cas:" + digest)
        assert np.array_equal(got.images, ds.images)
        assert np.array_equal(got.labels, ds.labels)
        assert s.counters["hits"] == 1 and s.counters["misses"] == 0
        # spec-indexed hit after a fresh store over the same dir
        s2 = DatasetStore(str(tmp_path))
        got2 = s2.get("spec-a")
        assert np.array_equal(got2.images, ds.images)
        assert s2.counters["hits"] == 1

    def test_builtin_miss_caches_then_hits(self, tmp_path):
        s = DatasetStore(str(tmp_path))
        spec = "synthetic-mnist?rows=24&seed=9"
        s.get(spec)
        assert s.counters["misses"] == 1
        s._ram.clear()  # force the disk path
        s.get(spec)
        assert s.counters["hits"] == 1
        assert s.stats()["entries"] == 1

    def test_corrupt_entry_quarantined_and_rebuilt(self, tmp_path):
        s = DatasetStore(str(tmp_path))
        spec = "synthetic-mnist?rows=24&seed=4"
        ds = s.get(spec)
        digest = s._spec_digest[spec]
        npz_p, _, _ = s._paths(digest)
        with open(npz_p, "r+b") as f:  # bit-rot one byte mid-file
            f.seek(100)
            b = f.read(1)
            f.seek(100)
            f.write(bytes([b[0] ^ 0xFF]))
        s._ram.clear()
        got = s.get(spec)  # quarantine + rebuild from the provider
        assert np.array_equal(got.images, ds.images)
        assert s.counters["quarantined"] == 1
        qdir = tmp_path / dstore.QUARANTINE_DIR
        assert any(n.endswith(".npz") for n in os.listdir(qdir))
        # the rebuilt entry (same content, same digest) verifies clean
        s._ram.clear()
        again = s.get(spec)
        assert np.array_equal(again.images, ds.images)
        assert s.counters["quarantined"] == 1  # no second quarantine

    def test_corrupt_cas_with_no_source_raises(self, tmp_path):
        s = DatasetStore(str(tmp_path))
        digest = s.put_dataset(synthetic_mnist(16, seed=1))
        npz_p, crc_p, _ = s._paths(digest)
        with open(crc_p, "w") as f:
            f.write("00000000 1\n")
        with pytest.raises(ValueError):
            s.get("cas:" + digest)
        assert s.counters["quarantined"] == 1

    def test_lru_byte_budget_evicts_oldest(self, tmp_path):
        s = DatasetStore(str(tmp_path), byte_budget=1)  # everything over
        d1 = s.put_dataset(synthetic_mnist(16, seed=1))
        time.sleep(0.02)
        d2 = s.put_dataset(synthetic_mnist(16, seed=2))
        # budget of 1 byte keeps at most the newest write's eviction
        # pass result: the OLDER entry must be gone.
        assert s.entry_meta(d1) is None
        assert s.counters["evictions"] >= 1
        # ...but a put NEVER evicts its own just-landed entry, however
        # over-budget: an oversized dataset must still become READY
        # and place instead of livelocking prefetch→evict→re-prefetch.
        assert s.entry_meta(d2) is not None

    def test_oversized_dataset_still_reaches_ready(self, tmp_path):
        spec = "synthetic-mnist?rows=64&seed=12"
        s = DatasetStore(str(tmp_path), byte_budget=1)
        s.prefetch(spec)
        deadline = time.time() + 10
        while s.state(spec) == dstore.LOADING:
            assert time.time() < deadline
            time.sleep(0.01)
        assert s.state(spec) == dstore.READY  # soft-exceeds the budget
        assert s.stats()["entries"] == 1
        s.shutdown()

    def test_ingest_file_content_addressed(self, tmp_path):
        ds = synthetic_mnist(20, seed=7)
        p = str(tmp_path / "x.npz")
        np.savez(p, images=ds.images, labels=ds.labels)
        s = DatasetStore(str(tmp_path / "store"))
        digest = s.ingest_file(p)
        got = s.get("cas:" + digest)
        assert np.array_equal(got.images, ds.images)

    def test_prefetch_states(self, tmp_path):
        gate = threading.Event()

        def slow_build(params):
            gate.wait(timeout=10)
            return synthetic_mnist(16, seed=0)

        register_provider(
            "slow-test", slow_build, probe=lambda p: (784, 16)
        )
        try:
            s = DatasetStore(str(tmp_path))
            assert s.state("slow-test") == dstore.UNKNOWN
            s.prefetch("slow-test")
            assert s.state("slow-test") == dstore.LOADING
            gate.set()
            deadline = time.time() + 10
            while s.state("slow-test") != dstore.READY:
                assert time.time() < deadline
                time.sleep(0.01)
            s.shutdown()
        finally:
            dstore._PROVIDERS.pop("slow-test", None)

    def test_prefetch_failure_is_failed_not_raised(self, tmp_path):
        s = DatasetStore(str(tmp_path))
        s.prefetch("file:/no/such/file.npz")
        deadline = time.time() + 10
        while s.state("file:/no/such/file.npz") == dstore.LOADING:
            assert time.time() < deadline
            time.sleep(0.01)
        assert s.state("file:/no/such/file.npz") == dstore.FAILED
        assert s.prefetch_error("file:/no/such/file.npz") is not None
        assert s.counters["prefetch_failures"] == 1
        # Consuming the verdict resets to unknown: the next scheduler
        # pass re-prefetches in the background, nobody reloads inline.
        s.clear_job("file:/no/such/file.npz")
        assert s.state("file:/no/such/file.npz") == dstore.UNKNOWN
        s.shutdown()

    def test_prefetch_job_does_not_pin_the_dataset(self, tmp_path):
        spec = "synthetic-mnist?rows=16&seed=3"
        s = DatasetStore(str(tmp_path))
        s.prefetch(spec)
        deadline = time.time() + 10
        while s.state(spec) != dstore.READY:
            assert time.time() < deadline
            time.sleep(0.01)
        # The job future must NOT hold the Dataset (a persistent
        # daemon's RAM must stay bounded by the store's LRU).
        assert s._jobs[spec].result() is None
        s.shutdown()

    def test_touched_identical_file_recovers_to_hits(self, tmp_path):
        # A file touched WITHOUT content change misses once (the stat
        # changed), but the put must merge the new stat into the meta —
        # a stale stat would loop full re-read+re-hash misses forever.
        p = str(tmp_path / "t.npz")
        ds = synthetic_mnist(24, seed=3)
        np.savez(p, images=ds.images, labels=ds.labels)
        s = DatasetStore(str(tmp_path / "store"))
        s.get(f"file:{p}")
        assert s.counters["misses"] == 1
        os.utime(p, (time.time() + 5, time.time() + 5))
        s.get(f"file:{p}")  # one revalidation miss, stat re-recorded
        assert s.counters["misses"] == 2
        s.get(f"file:{p}")
        assert s.counters["misses"] == 2  # back to hits
        assert s.counters["hits"] >= 1
        # Two paths, same bytes: both specs index the one entry and hit.
        p2 = str(tmp_path / "t2.npz")
        import shutil

        shutil.copyfile(p, p2)
        s.get(f"file:{p2}")
        s.get(f"file:{p}")
        s.get(f"file:{p2}")
        assert s.stats()["entries"] == 1

    def test_half_landed_entry_self_heals(self, tmp_path):
        # Crash model: the payload rename is the COMMIT POINT, so a
        # crash can leave orphan sidecars (never a crc-less payload);
        # and a put over a degraded entry re-seals every piece.
        s = DatasetStore(str(tmp_path))
        ds = synthetic_mnist(16, seed=8)
        digest = s.put_dataset(ds, source_spec="spec-h")
        npz_p, crc_p, meta_p = s._paths(digest)
        os.unlink(crc_p)  # simulate the old npz-first crash shape
        s.put_dataset(ds, source_spec="spec-h")  # must repair, not skip
        assert os.path.exists(crc_p)
        s._ram.clear()
        got = s.get("cas:" + digest)
        assert np.array_equal(got.images, ds.images)

    def test_resolve_memo_revalidates_changed_file(self, tmp_path):
        p = str(tmp_path / "m.npz")
        a = synthetic_mnist(24, seed=1)
        b = synthetic_mnist(24, seed=2)
        np.savez(p, images=a.images, labels=a.labels)
        got = resolve_dataset(f"file:{p}")
        assert np.array_equal(got.images, a.images)
        time.sleep(0.02)
        np.savez(p, images=b.images, labels=b.labels)
        got2 = resolve_dataset(f"file:{p}")  # stale memo must not serve
        assert np.array_equal(got2.images, b.images)

    def test_ready_requires_residency_not_a_stale_future(self, tmp_path):
        spec = "synthetic-mnist?rows=16&seed=6"
        s = DatasetStore(str(tmp_path))
        s.prefetch(spec)
        deadline = time.time() + 10
        while s.state(spec) != dstore.READY:
            assert time.time() < deadline
            time.sleep(0.01)
        # RAM-evicted (disk entry intact): READY would make placement
        # parse the npz inline on the daemon loop — the verdict must
        # fall back to unknown, and the re-prefetch re-warms from disk
        # in the background.
        s._ram.clear()
        assert s.state(spec) == dstore.UNKNOWN
        s.prefetch(spec)
        deadline = time.time() + 10
        while s.state(spec) != dstore.READY:
            assert time.time() < deadline
            time.sleep(0.01)
        assert s.counters["hits"] >= 1  # re-warm was a disk HIT
        # Evicted everywhere (disk too): same unknown → full re-warm.
        digest = s._spec_digest[spec]
        s._ram.clear()
        for p in s._paths(digest):
            if os.path.exists(p):
                os.unlink(p)
        assert s.state(spec) == dstore.UNKNOWN
        s.shutdown()

    def test_file_ref_revalidates_changed_source(self, tmp_path):
        p = str(tmp_path / "d.npz")
        a = synthetic_mnist(32, seed=1)
        b = synthetic_mnist(32, seed=2)
        np.savez(p, images=a.images, labels=a.labels)
        s = DatasetStore(str(tmp_path / "store"))
        got = s.get(f"file:{p}")
        assert np.array_equal(got.images, a.images)
        time.sleep(0.02)  # distinct mtime
        np.savez(p, images=b.images, labels=b.labels)
        s._ram.clear()
        got2 = s.get(f"file:{p}")  # stale index entry must NOT serve
        assert np.array_equal(got2.images, b.images)
        assert s.counters["misses"] == 2


# --------------------------------------------------------------------
# heterogeneous stacked lanes + the pipelined input path
# --------------------------------------------------------------------


class TestHeterogeneousLanes:
    def test_hetero_lanes_match_single_lane_streams(self, trial):
        K = 3
        datasets = [synthetic_mnist(96, seed=10 + k) for k in range(K)]
        seeds = [0, 5, 9]
        it = StackedTrialDataIterator(
            datasets[0], trial, 16, seeds, datasets=datasets,
            use_native=False,
        )
        stacked = [np.asarray(b) for b in it.round_batches()]
        assert len(stacked) == 6
        for k in range(K):
            ref = TrialDataIterator(
                datasets[k], trial, 16, seed=seeds[k], use_native=False
            )
            for b, batch in enumerate(ref.epoch(1)):
                assert np.array_equal(stacked[b][k], np.asarray(batch))

    def test_pipeline_bit_parity_with_synchronous(self, trial):
        datasets = [synthetic_mnist(64, seed=20 + k) for k in range(2)]
        a = StackedTrialDataIterator(
            datasets[0], trial, 16, [1, 2], datasets=datasets,
            prefetch=False, use_native=False,
        )
        b = StackedTrialDataIterator(
            datasets[0], trial, 16, [1, 2], datasets=datasets,
            prefetch=True, prefetch_depth=3, use_native=False,
        )
        for _round in range(2):  # crossing a round boundary too
            # (materialize fully: a round's epoch advance rides the
            # generator's final next(), which zip would skip on one side)
            xs = [np.asarray(x) for x in a.round_batches()]
            ys = [np.asarray(y) for y in b.round_batches()]
            assert len(xs) == len(ys)
            for x, y in zip(xs, ys):
                assert np.array_equal(x, y)

    def test_set_lane_swaps_dataset_without_recompile_surface(self, trial):
        datasets = [synthetic_mnist(64, seed=30 + k) for k in range(3)]
        it = StackedTrialDataIterator(
            datasets[0], trial, 16, [0, 1], datasets=datasets[:2],
            use_native=False,
        )
        list(it.round_batches())
        it.set_lane(1, 7, dataset=datasets[2])
        got = [np.asarray(b) for b in it.round_batches()]
        ref = TrialDataIterator(
            datasets[2], trial, 16, seed=7, use_native=False
        )
        for b, batch in enumerate(ref.epoch(1)):
            assert np.array_equal(got[b][1], np.asarray(batch))

    def test_shape_class_mismatches_raise(self, trial):
        base = synthetic_mnist(64, seed=0)
        short = synthetic_mnist(32, seed=1)  # fewer batches/epoch
        with pytest.raises(ValueError, match="batches per epoch"):
            StackedTrialDataIterator(
                base, trial, 16, [0, 1], datasets=[base, short],
                use_native=False,
            )
        it = StackedTrialDataIterator(
            base, trial, 16, [0, 1], use_native=False
        )
        with pytest.raises(ValueError, match="batches per epoch"):
            it.set_lane(0, 3, dataset=short)
        wide = Dataset(
            images=np.zeros((64, 100), np.float32),
            labels=np.zeros((64,), np.int32),
            name="wide",
        )
        with pytest.raises(ValueError, match="feature dim"):
            it.set_lane(0, 3, dataset=wide)

    def test_prefetch_depth_env(self, trial, monkeypatch):
        monkeypatch.setenv("MDT_STACKED_PREFETCH_DEPTH", "5")
        it = StackedTrialDataIterator(
            synthetic_mnist(64, seed=0), trial, 16, [0], use_native=False
        )
        assert it._depth == 5
        monkeypatch.setenv("MDT_STACKED_PREFETCH_DEPTH", "bogus")
        it2 = StackedTrialDataIterator(
            synthetic_mnist(64, seed=0), trial, 16, [0], use_native=False
        )
        assert it2._depth == 2

    def test_abandoned_pipeline_neither_wedges_nor_leaks(self, trial):
        def worker_count() -> int:
            return sum(
                1
                for t in threading.enumerate()
                if t.name.startswith("mdt-stacked-prefetch")
            )

        base = worker_count()
        it = StackedTrialDataIterator(
            synthetic_mnist(256, seed=0), trial, 16, [0, 1],
            prefetch=True, prefetch_depth=3, use_native=False,
        )
        gen = it.round_batches()
        next(gen)  # worker is live, queue filling
        assert worker_count() >= base
        gen.close()  # abandon mid-round
        del gen, it
        gc.collect()
        deadline = time.time() + 5
        while worker_count() > base:
            assert time.time() < deadline, "prefetch worker leaked"
            time.sleep(0.05)

    def test_wait_hook_counts_blocked_time_and_bytes(self, trial):
        waits = []
        it = StackedTrialDataIterator(
            synthetic_mnist(64, seed=0), trial, 16, [0, 1],
            prefetch=False, use_native=False,
            wait_hook=lambda dt, nb: waits.append((dt, nb)),
        )
        list(it.round_batches())
        assert len(waits) == 4
        assert all(nb == 2 * 16 * 784 * 4 for _, nb in waits)
        assert all(dt >= 0 for dt, _ in waits)


# --------------------------------------------------------------------
# input-stall books (StepSeries wait book + event fold + summary)
# --------------------------------------------------------------------


class TestInputBooks:
    def test_step_series_wait_book(self):
        from multidisttorch_tpu.telemetry.metrics import StepSeries

        s = StepSeries(sample_every=0)
        s.mark()  # open
        time.sleep(0.01)
        s.mark()
        s.note_wait(0.004, 1000)
        s.note_wait(0.001, 500)
        snap = s.snapshot()
        assert snap["wait_s"] == pytest.approx(0.005)
        assert snap["input_bytes"] == 1500
        assert 0.0 < snap["input_bound_frac"] <= 1.0
        assert snap["input_bytes_per_s"] > 0

    def test_sweep_fold_input_wait_event(self):
        from multidisttorch_tpu.telemetry.export import SweepFold

        fold = SweepFold()
        fold.feed(
            {
                "kind": "input_wait",
                "ts": 1.0,
                "group_id": 0,
                "data": {
                    "key": "bucket-g0",
                    "wait_s": 0.5,
                    "bytes": 4096,
                    "wall_s": 10.0,
                },
            }
        )
        book = fold.input["bucket-g0"]
        assert book["input_bound_frac"] == 0.05
        assert book["bytes_per_s"] == pytest.approx(409.6)

    def test_run_summary_surfaces_input_block(self):
        from multidisttorch_tpu.telemetry import metrics as m
        from multidisttorch_tpu.telemetry.export import run_summary

        reg = m.configure()
        try:
            series = reg.step_series("bucket-g0")
            series.mark()
            time.sleep(0.005)
            series.mark()
            series.note_wait(0.002, 2048)
            out = run_summary([], registry=reg)
            assert "bucket-g0" in out["input"]
            assert out["input"]["bucket-g0"]["bytes"] == 2048
        finally:
            m.disable()

    def test_sweep_top_snapshot_carries_input(self):
        import tools.sweep_top as st
        from multidisttorch_tpu.telemetry.export import SweepFold

        fold = SweepFold()
        fold.feed(
            {
                "kind": "input_wait",
                "ts": 1.0,
                "group_id": 2,
                "data": {"wait_s": 1.0, "bytes": 10, "wall_s": 4.0},
            }
        )
        snap = st.snapshot(fold, "x")
        assert snap["input"]["bucket-g2"]["input_bound_frac"] == 0.25
        assert "bucket-g2" in st.render(fold, "x")


# --------------------------------------------------------------------
# driver: heterogeneous buckets end to end
# --------------------------------------------------------------------


BASE = dict(
    epochs=1, batch_size=32, hidden_dim=16, latent_dim=4,
    log_interval=1000,
)


class TestDriverHeterogeneous:
    def test_stacked_bucket_across_datasets_bitwise(self, tmp_path):
        from multidisttorch_tpu import telemetry
        from multidisttorch_tpu.hpo.driver import TrialConfig, run_hpo
        from multidisttorch_tpu.telemetry.events import read_events
        from multidisttorch_tpu.telemetry.export import run_summary

        train = synthetic_mnist(128, seed=0)
        spec = "synthetic-mnist?rows=128&seed=77"
        cfgs = [
            TrialConfig(trial_id=0, seed=0, **BASE),
            TrialConfig(trial_id=1, seed=1, dataset=spec, **BASE),
        ]
        tel_dir = str(tmp_path / "tel")
        with telemetry.telemetry_run(tel_dir):
            res = run_hpo(
                cfgs, train, None, num_groups=1,
                out_dir=str(tmp_path / "s"),
                stack_trials=True, save_images=False, verbose=False,
            )
            summary = run_summary(
                read_events(os.path.join(tel_dir, "events.jsonl"))
            )
        assert all(r.stacked for r in res)  # ONE bucket, two datasets
        # per-lane dataset provenance recorded, not the bucket's
        assert res[1].dataset == "synthetic-mnist"
        # Input-stall books: the bucket emitted per-round input_wait
        # events and the summary surfaces the wait book.
        book = summary["input"]["bucket-g0"]
        assert book["bytes"] > 0
        assert book["input_bound_frac"] is not None
        for i, cfg in enumerate(cfgs):
            (ref,) = run_hpo(
                [cfg], train, None, num_groups=1,
                out_dir=str(tmp_path / f"u{i}"),
                save_images=False, verbose=False,
            )
            assert res[i].final_train_loss == ref.final_train_loss

    def test_shape_class_still_splits_buckets(self, tmp_path):
        # Different ROUND LENGTH = different shape class = separate
        # placements (identity never splits; shape class must).
        from multidisttorch_tpu.hpo.driver import (
            TrialConfig,
            data_shape_sig,
            stack_bucket_key,
        )

        a = synthetic_mnist(128, seed=0)
        b = synthetic_mnist(64, seed=0)
        c1 = TrialConfig(trial_id=0, **BASE)
        c2 = TrialConfig(trial_id=1, **BASE)
        assert stack_bucket_key(c1) == stack_bucket_key(c2)
        assert data_shape_sig(a, 32) != data_shape_sig(b, 32)

    def test_dataset_field_rides_config_hash_and_resume_guard(self):
        from dataclasses import asdict

        from multidisttorch_tpu.hpo.driver import TrialConfig
        from multidisttorch_tpu.hpo.ledger import config_hash

        c1 = TrialConfig(trial_id=0, **BASE)
        c2 = TrialConfig(trial_id=0, dataset="synthetic-mnist?rows=64",
                         **BASE)
        assert config_hash(asdict(c1)) != config_hash(asdict(c2))


# --------------------------------------------------------------------
# service: admission probe, never-blocks, co-pack across datasets
# --------------------------------------------------------------------


def make_service(d, **kw):
    from multidisttorch_tpu.service.runtime import SweepService

    kw.setdefault("data_rows", 128)
    kw.setdefault("verbose", False)
    return SweepService(str(d), **kw)


def run_until(svc, cond, timeout_s=180.0):
    t0 = time.time()
    while time.time() - t0 < timeout_s:
        svc.tick()
        if cond():
            return True
    return False


class TestServiceDataPlane:
    def test_bad_dataset_ref_rejected_with_verdict(self, tmp_path):
        from multidisttorch_tpu.service.queue import SweepClient

        c = SweepClient(str(tmp_path))
        bad = c.submit({**BASE, "dataset": "builtin:no-such-provider"})
        wrong_dim = c.submit(
            {**BASE, "dataset": "synthetic-cifar10?rows=128"}
        )
        svc = make_service(tmp_path, n_slices=1, max_lanes=1)
        svc.tick()
        assert svc.settled[bad] == "rejected_invalid"
        assert svc.settled[wrong_dim] == "rejected_invalid"

    def test_admission_never_blocks_on_dataset_load(self, tmp_path):
        from multidisttorch_tpu.service.queue import SweepClient

        gate = threading.Event()

        def slow_build(params):
            gate.wait(timeout=60)
            return synthetic_mnist(128, seed=42)

        register_provider(
            "slow-svc-test", slow_build, probe=lambda p: (784, 128)
        )
        try:
            c = SweepClient(str(tmp_path))
            slow = c.submit({**BASE, "dataset": "slow-svc-test"})
            fast = c.submit({**BASE, "seed": 3})
            svc = make_service(tmp_path, n_slices=2, max_lanes=1)
            # Admission + scheduling proceed while the load hangs on
            # the gate: the slow submission is ADMITTED but never
            # PLACED, the fast one trains to completion meanwhile.
            t0 = time.time()
            svc.tick()
            assert time.time() - t0 < 30  # no synchronous load
            assert slow not in svc.settled
            assert run_until(svc, lambda: fast in svc.settled)
            from multidisttorch_tpu.service.queue import (
                fold_queue,
                load_queue,
            )

            folded = fold_queue(load_queue(str(tmp_path)))
            assert folded[slow]["state"] == "admitted"
            assert folded[slow]["placements"] == 0
            gate.set()  # dataset arrives; trial places and completes
            assert run_until(svc, lambda: slow in svc.settled)
            assert svc.settled[slow] == "completed"
            assert folded[fast]["ts"].get("placed") is not None
        finally:
            dstore._PROVIDERS.pop("slow-svc-test", None)

    def test_member_dataset_failure_does_not_fail_copacked_tenants(
        self, tmp_path
    ):
        from multidisttorch_tpu.service.queue import (
            SweepClient,
            fold_queue,
            load_queue,
        )

        gate = threading.Event()

        def doomed_build(params):
            gate.wait(timeout=60)
            raise OSError("tenant dataset source vanished")

        def fine_build(params):
            gate.wait(timeout=60)
            return synthetic_mnist(128, seed=43)

        register_provider("doomed-ds", doomed_build,
                          probe=lambda p: (784, 128))
        register_provider("fine-ds", fine_build,
                          probe=lambda p: (784, 128))
        try:
            ca = SweepClient(str(tmp_path), tenant="alice")
            cb = SweepClient(str(tmp_path), tenant="bob")
            bad = ca.submit({**BASE, "seed": 0, "dataset": "doomed-ds"})
            good = cb.submit({**BASE, "seed": 1, "dataset": "fine-ds"})
            svc = make_service(tmp_path, n_slices=2, max_lanes=4)
            svc.tick()  # admit + prefetch; both LOADING → nothing places
            gate.set()
            for spec, want in (
                ("doomed-ds", dstore.FAILED), ("fine-ds", dstore.READY),
            ):
                deadline = time.time() + 30
                while svc.store.state(spec) != want:
                    assert time.time() < deadline
                    time.sleep(0.01)
            # Both now pass can_start and co-select into ONE placement;
            # the doomed member must fail ALONE with its real error
            # while bob's trial trains to completion on the block.
            assert run_until(
                svc, lambda: {bad, good} <= set(svc.settled)
            )
            assert svc.settled[bad] == "failed"
            assert svc.settled[good] == "completed"
            folded = fold_queue(load_queue(str(tmp_path)))
            assert "vanished" in folded[bad]["error"]
            assert folded[bad]["placements"] == 0  # never placed
            assert folded[good]["ts"].get("placed") is not None
        finally:
            dstore._PROVIDERS.pop("doomed-ds", None)
            dstore._PROVIDERS.pop("fine-ds", None)

    def test_shape_drift_after_probe_fails_only_its_member(self, tmp_path):
        from multidisttorch_tpu.service.queue import (
            SweepClient,
            fold_queue,
            load_queue,
        )

        p = str(tmp_path / "drift.npz")
        a = synthetic_mnist(128, seed=50)
        np.savez(p, images=a.images, labels=a.labels)
        ca = SweepClient(str(tmp_path), tenant="alice")
        cb = SweepClient(str(tmp_path), tenant="bob")
        drift = ca.submit({**BASE, "seed": 0, "dataset": f"file:{p}"})
        good = cb.submit(
            {**BASE, "seed": 1,
             "dataset": "synthetic-mnist?rows=128&seed=51"}
        )
        svc = make_service(tmp_path, n_slices=2, max_lanes=4)
        svc.tick()  # admit + prefetch (probed 128 rows = 4 batches)
        for spec in (f"file:{p}", "synthetic-mnist?rows=128&seed=51"):
            deadline = time.time() + 30
            while svc.store.state(spec) != dstore.READY:
                assert time.time() < deadline
                time.sleep(0.01)
        # The file grows to a different shape class AFTER the probe:
        # placement re-ingests the new content, detects the drift, and
        # must fail alice ALONE — bob keeps the co-packed placement.
        time.sleep(0.02)
        big = synthetic_mnist(256, seed=52)
        np.savez(p, images=big.images, labels=big.labels)
        assert run_until(
            svc, lambda: {drift, good} <= set(svc.settled)
        )
        assert svc.settled[drift] == "failed"
        assert svc.settled[good] == "completed"
        folded = fold_queue(load_queue(str(tmp_path)))
        assert "changed shape class" in folded[drift]["error"]

    def test_recovery_reports_real_dataset_probe_failure(self, tmp_path):
        from multidisttorch_tpu.service.queue import SweepClient

        register_provider(
            "ephemeral-ds",
            lambda p: synthetic_mnist(128, seed=0),
            probe=lambda p: (784, 128),
        )
        try:
            c = SweepClient(str(tmp_path))
            sid = c.submit({**BASE, "dataset": "ephemeral-ds"})
            svc = make_service(tmp_path, n_slices=1, max_lanes=1)
            svc.tick()  # admitted under the provider
            assert sid not in svc.settled or True
        finally:
            dstore._PROVIDERS.pop("ephemeral-ds", None)
        # Restart WITHOUT the provider: recovery must reject with the
        # real probe failure, not a generic "does not parse".
        svc2 = make_service(tmp_path, n_slices=1, max_lanes=1)
        if sid in svc2.settled:
            from multidisttorch_tpu.service.queue import (
                fold_queue,
                load_queue,
            )

            rec = fold_queue(load_queue(str(tmp_path)))[sid]
            assert rec["status"] == "rejected_invalid"
            assert "ephemeral-ds" in rec["error"]

    def test_copack_across_datasets_no_bucket_splitting(self, tmp_path):
        from multidisttorch_tpu.service.queue import (
            SweepClient,
            fold_queue,
            load_queue,
        )

        ca = SweepClient(str(tmp_path), tenant="alice")
        cb = SweepClient(str(tmp_path), tenant="bob")
        s1 = ca.submit(
            {**BASE, "seed": 0,
             "dataset": "synthetic-mnist?rows=128&seed=7"}
        )
        s2 = cb.submit(
            {**BASE, "seed": 1,
             "dataset": "synthetic-mnist?rows=128&seed=8"}
        )
        svc = make_service(tmp_path, n_slices=2, max_lanes=4)
        # First tick admits + starts both prefetches; wait for READY so
        # the subsequent scheduling pass sees both placeable at once
        # (the veto is per-entry, so an earlier-ready entry may
        # otherwise legitimately place alone).
        svc.tick()
        for spec in (
            "synthetic-mnist?rows=128&seed=7",
            "synthetic-mnist?rows=128&seed=8",
        ):
            deadline = time.time() + 30
            while svc.store.state(spec) != dstore.READY:
                assert time.time() < deadline
                time.sleep(0.01)
        assert run_until(
            svc, lambda: {s1, s2} <= set(svc.settled)
        )
        assert svc.settled[s1] == svc.settled[s2] == "completed"
        folded = fold_queue(load_queue(str(tmp_path)))
        # ONE stacked placement, two tenants, two datasets.
        assert folded[s1]["last_placement"]["lanes"] == 2
        assert folded[s2]["last_placement"]["lanes"] == 2
        assert folded[s1]["last_placement"]["stacked"] is True
        books = svc.books()
        assert books["dataset_cache"]["prefetches"] >= 2

    def test_service_books_carry_dataset_cache(self, tmp_path):
        svc = make_service(tmp_path, n_slices=1, max_lanes=1)
        books = svc.books()
        assert set(books["dataset_cache"]) >= {
            "hits", "misses", "evictions", "quarantined", "bytes",
        }


class TestSubmitCLI:
    def test_sweep_submit_dataset_flag(self, tmp_path, capsys):
        import tools.sweep_submit as ss

        rc = ss.main(
            [
                str(tmp_path),
                "--tenant", "alice",
                "--epochs", "1",
                "--dataset", "synthetic-mnist?rows=64&seed=1",
                "--json",
            ]
        )
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        sid = out["submitted"][0]
        spool = os.path.join(str(tmp_path), "intake", sid + ".json")
        with open(spool) as f:
            sub = json.load(f)
        assert sub["config"]["dataset"] == "synthetic-mnist?rows=64&seed=1"
