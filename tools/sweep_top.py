#!/usr/bin/env python
"""Live sweep console: tail the telemetry event JSONL and render
per-trial status, step rates, retries, and sweep goodput.

    python tools/sweep_top.py <telemetry-dir-or-events.jsonl> [--follow]

Works on a LIVE run (``--follow`` re-reads new lines each interval and
redraws — the sink is flushed per event, so a running sweep streams)
or on a finished one (one-shot render). It only reads the JSONL — it
never initializes a jax backend or touches the accelerator, so it can
run next to a live sweep.

Enable telemetry on the sweep side with ``MDT_TELEMETRY=1
MDT_TELEMETRY_DIR=<dir>`` or ``telemetry.telemetry_run(<dir>)`` — see
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Allow running straight from a checkout (tools/ is not a package).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.telemetry.console import (  # noqa: E402
    clear_screen,
    fmt_duration,
    fmt_rate,
    fmt_table,
    fmt_ts,
    status_glyph,
)
from multidisttorch_tpu.telemetry.events import EVENTS_NAME  # noqa: E402
from multidisttorch_tpu.telemetry.export import SweepFold  # noqa: E402


def resolve_events_path(path: str) -> str:
    if os.path.isdir(path):
        return os.path.join(path, EVENTS_NAME)
    return path


def render(state: SweepFold, path: str) -> str:
    lines = []
    span = (
        (state.last_ts - state.first_ts)
        if state.first_ts is not None
        else None
    )
    head = [
        f"sweep_top  {path}",
        f"events {state.events}"
        + (f"  span {fmt_duration(span)}" if span is not None else "")
        + (f"  last {fmt_ts(state.last_ts)}" if state.last_ts else ""),
    ]
    if state.sweep:
        head.append(
            "configs {configs}  groups {groups}  stacked {stacked}".format(
                configs=state.sweep.get("configs", "?"),
                groups=state.sweep.get("groups", "?"),
                stacked=state.sweep.get("stacked", False),
            )
        )
    goodput = state.goodput
    head.append(
        "goodput "
        + (f"{goodput:.3f}" if goodput is not None else "-")
        + f"  (useful {state.useful} / executed {state.executed} steps)"
        + ("  [sweep finished]" if state.done else "")
    )
    lines.extend(head)
    lines.append("")
    rows = []
    for tid in sorted(state.trials):
        t = state.trials[tid]
        wall = (
            t["last_ts"] - t["first_ts"]
            if t["first_ts"] is not None and t["last_ts"] is not None
            else None
        )
        rate = t["step"] / wall if wall and t["step"] else None
        rows.append(
            [
                tid,
                status_glyph(t["status"]),
                t["attempts"] or "-",
                t["epoch"] or "-",
                t["step"] or "-",
                fmt_rate(rate, "/s") if rate else "-",
                f"{t['train_loss']:.4f}" if t["train_loss"] is not None
                else "-",
                f"{t['test_loss']:.4f}" if t["test_loss"] is not None
                else "-",
                t["retries"],
                t["faults"],
                t["lane"] if t["lane"] is not None else "-",
                fmt_duration(wall),
            ]
        )
    lines.append(
        fmt_table(
            rows,
            ["trial", "status", "att", "epoch", "steps", "step rate",
             "train loss", "test loss", "retries", "faults", "lane",
             "wall"],
        )
    )
    return "\n".join(lines)


def follow_lines(path: str, state: SweepFold, offset: int) -> int:
    """Feed decodable complete lines past ``offset``; returns the new
    offset. A torn tail (no trailing newline yet) is left for the next
    poll — the live analog of read_events' torn-tail tolerance."""
    try:
        with open(path) as f:
            f.seek(offset)
            chunk = f.read()
    except OSError:
        return offset
    if not chunk:
        return offset
    consumed = 0
    for line in chunk.splitlines(keepends=True):
        if not line.endswith("\n"):
            break  # torn tail: wait for the writer to finish the line
        consumed += len(line)
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(ev, dict):
            state.feed(ev)
    return offset + consumed


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="live console over a sweep's telemetry event JSONL"
    )
    parser.add_argument(
        "path",
        help="telemetry dir (containing events.jsonl) or the JSONL file",
    )
    parser.add_argument(
        "-f", "--follow", action="store_true",
        help="keep tailing and redraw every --interval seconds",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--max-refreshes", type=int, default=0,
        help="stop after N redraws (0 = until interrupted/sweep end; "
        "mostly for tests)",
    )
    args = parser.parse_args(argv)

    path = resolve_events_path(args.path)
    if not os.path.exists(path) and not args.follow:
        print(f"no event file at {path}", file=sys.stderr)
        return 1
    state = SweepFold()
    offset = follow_lines(path, state, 0)
    if not args.follow:
        print(render(state, path))
        return 0
    refreshes = 0
    try:
        while True:
            print(clear_screen() + render(state, path), flush=True)
            refreshes += 1
            if state.done:
                break
            if args.max_refreshes and refreshes >= args.max_refreshes:
                break
            time.sleep(args.interval)
            offset = follow_lines(path, state, offset)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
