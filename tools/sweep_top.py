#!/usr/bin/env python
"""Live sweep console: tail the telemetry event JSONL and render
per-trial status, step rates, retries, and sweep goodput.

    python tools/sweep_top.py <telemetry-dir-or-events.jsonl> [--follow]
    python tools/sweep_top.py <run-dir> --fleet [--follow]

Works on a LIVE run (``--follow`` re-reads new lines each interval and
redraws — the sink is flushed per event, so a running sweep streams)
or on a finished one (one-shot render). It only reads the JSONL — it
never initializes a jax backend or touches the accelerator, so it can
run next to a live sweep.

``--fleet`` turns the console into the FLEET view over an elastic
multi-host run directory (docs/OBSERVABILITY.md "Fleet"): every
per-host/per-world shard under ``{run_dir}/telemetry`` is merged on
the skew-corrected fleet clock (``telemetry/fleet.py``) and the render
adds per-host health (lease age vs the heartbeat deadline), the world
history with its shrink reasons, the restart-tax breakdown of every
world transition, and each migrated trial's lineage across worlds.

Enable telemetry on the sweep side with ``MDT_TELEMETRY=1
MDT_TELEMETRY_DIR=<dir>`` or ``telemetry.telemetry_run(<dir>)`` — see
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Allow running straight from a checkout (tools/ is not a package).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.telemetry.console import (  # noqa: E402
    clear_screen,
    fmt_bytes,
    fmt_duration,
    fmt_mfu,
    fmt_rate,
    fmt_table,
    fmt_ts,
    host_health,
    status_glyph,
)
from multidisttorch_tpu.telemetry.events import EVENTS_NAME  # noqa: E402
from multidisttorch_tpu.telemetry.export import SweepFold  # noqa: E402


def resolve_events_path(path: str) -> str:
    if os.path.isdir(path):
        return os.path.join(path, EVENTS_NAME)
    return path


def live_mfu(state: SweepFold, tid: int, rate) -> "float | None":
    """Best-effort live MFU for one trial: its device_cost book's
    per-lane-step FLOPs x its own step rate over the submesh peak.
    None off-TPU (no peak) or before the cost book lands."""
    key = state.series_key_of(tid)
    book = state.device.get(key) if key else None
    if not book or not rate:
        return None
    flops = book.get("flops_per_lane_step")
    peak = book.get("peak_flops_per_chip")
    ndev = book.get("devices") or 1
    if not flops or not peak:
        return None
    return flops * rate / (peak * ndev)


def snapshot(state: SweepFold, path: str) -> dict:
    """Machine-readable one-shot fold of the event stream — the same
    accounting the rendered console shows, JSON-shaped so CI and
    scripts can consume it without screen-scraping (``--json``)."""
    return {
        "path": path,
        "events": state.events,
        "first_ts": state.first_ts,
        "last_ts": state.last_ts,
        "sweep": state.sweep,
        "done": state.done,
        "useful_steps": state.useful,
        "executed_steps": state.executed,
        "goodput": state.goodput,
        "anomalies": state.anomalies,
        "trials": {k: state.trials[k] for k in sorted(state.trials)},
        "device_books": {k: state.device[k] for k in sorted(state.device)},
        "compile_books": {
            k: state.compile_books[k] for k in sorted(state.compile_books)
        },
        "compiles": state.compiles,
        "compile_s_total": state.compile_s_total,
        "cache_hits": state.cache_hits,
        "precompile": dict(sorted(state.precompile.items())),
        "admissions": state.admissions,
        # Population view (hpo/pbt.py's pbt_* events): mode, K, and the
        # per-generation best/median loss, exploit count, and rank
        # churn — {} when the stream carries no PBT run.
        "pbt": state.pbt,
        # Input-stall books (docs/DATA.md): per-series wait seconds,
        # input-bound fraction, and host->device bytes/sec folded off
        # the stacked feed's input_wait events.
        "input": state.input,
    }


def input_frac(state: SweepFold, tid: int) -> "float | None":
    """Trial ``tid``'s input-bound fraction: its step series' (or its
    bucket's) folded input_wait book, None when the stream carries no
    input accounting."""
    t = state.trials.get(tid)
    keys = [f"trial-{tid}"]
    key = state.series_key_of(tid)
    if key:
        keys.append(key)
    if t and t.get("group") is not None:
        keys.append(f"bucket-g{t['group']}")
    for k in keys:
        book = state.input.get(k)
        if book and book.get("input_bound_frac") is not None:
            return book["input_bound_frac"]
    return None


def render(state: SweepFold, path: str) -> str:
    lines = []
    span = (
        (state.last_ts - state.first_ts)
        if state.first_ts is not None
        else None
    )
    head = [
        f"sweep_top  {path}",
        f"events {state.events}"
        + (f"  span {fmt_duration(span)}" if span is not None else "")
        + (f"  last {fmt_ts(state.last_ts)}" if state.last_ts else ""),
    ]
    if state.sweep:
        head.append(
            "configs {configs}  groups {groups}  stacked {stacked}".format(
                configs=state.sweep.get("configs", "?"),
                groups=state.sweep.get("groups", "?"),
                stacked=state.sweep.get("stacked", False),
            )
        )
    goodput = state.goodput
    head.append(
        "goodput "
        + (f"{goodput:.3f}" if goodput is not None else "-")
        + f"  (useful {state.useful} / executed {state.executed} steps)"
        + ("  [sweep finished]" if state.done else "")
    )
    lines.extend(head)
    lines.append("")
    rows = []
    for tid in sorted(state.trials):
        t = state.trials[tid]
        wall = (
            t["last_ts"] - t["first_ts"]
            if t["first_ts"] is not None and t["last_ts"] is not None
            else None
        )
        rate = t["step"] / wall if wall and t["step"] else None
        key = state.series_key_of(tid)
        book = state.device.get(key, {}) if key else {}
        in_frac = input_frac(state, tid)
        rows.append(
            [
                tid,
                status_glyph(t["status"]),
                t["attempts"] or "-",
                t["epoch"] or "-",
                t["step"] or "-",
                fmt_rate(rate, "/s") if rate else "-",
                f"{t['train_loss']:.4f}" if t["train_loss"] is not None
                else "-",
                f"{t['test_loss']:.4f}" if t["test_loss"] is not None
                else "-",
                t["retries"],
                t["faults"],
                t["lane"] if t["lane"] is not None else "-",
                f"{in_frac * 100:.1f}%" if in_frac is not None else "-",
                fmt_mfu(live_mfu(state, tid, rate)),
                fmt_bytes(book.get("peak_bytes")),
                # Analytic per-device optimizer bytes (memory books,
                # docs/PARALLEL.md): the ZeRO win, CPU included; "z"
                # marks the sharded-update mode.
                (
                    fmt_bytes(t["optimizer_state_bytes"])
                    + ("z" if t.get("zero_update") else "")
                    if t.get("optimizer_state_bytes") is not None
                    else "-"
                ),
                t.get("anomalies", 0) or "-",
                (
                    f"{t['admission_s']:.2f}s"
                    if t.get("admission_s") is not None
                    else "-"
                ),
                t.get("compile_outcome") or "-",
                fmt_duration(wall),
            ]
        )
    lines.append(
        fmt_table(
            rows,
            ["trial", "status", "att", "epoch", "steps", "step rate",
             "train loss", "test loss", "retries", "faults", "lane",
             "in%", "mfu", "peak mem", "opt mem", "anom", "admit",
             "compile", "wall"],
        )
    )
    if state.input:
        # Input-stall books (docs/DATA.md): how long each stacked feed
        # sat blocked on its host gather, and the host->device rate.
        lines.append("")
        irows = []
        for key in sorted(state.input):
            b = state.input[key]
            irows.append(
                [
                    key,
                    f"{b.get('wait_s', 0.0):.2f}s",
                    (
                        f"{b['input_bound_frac'] * 100:.1f}%"
                        if b.get("input_bound_frac") is not None
                        else "-"
                    ),
                    fmt_bytes(b.get("bytes")),
                    (
                        fmt_bytes(b["bytes_per_s"]) + "/s"
                        if b.get("bytes_per_s") is not None
                        else "-"
                    ),
                ]
            )
        lines.append(
            fmt_table(
                irows,
                ["input series", "wait", "in-bound", "bytes", "rate"],
            )
        )
    if state.compile_books:
        # Per-program compile books (docs/COMPILE.md): where the
        # sweep's compile-seconds went, how they were paid (farm
        # thread vs inline admission), and how often the registry
        # served an executable instead of XLA.
        lines.append("")
        lines.append(
            "compile  total {n} ({s:.2f}s)  registry hits {h}".format(
                n=state.compiles,
                s=state.compile_s_total,
                h=state.cache_hits,
            )
            + (
                "  farm " + " ".join(
                    f"{k}:{v}"
                    for k, v in sorted(state.precompile.items())
                )
                if state.precompile
                else ""
            )
        )
        crows = []
        for prog in sorted(state.compile_books):
            b = state.compile_books[prog]
            crows.append(
                [
                    prog,
                    b.get("source") or "-",
                    b["compiles"],
                    f"{b['compile_s']:.2f}s",
                    b["hits"],
                    "ok" if b.get("ok", True) else "FAILED",
                ]
            )
        lines.append(
            fmt_table(
                crows,
                ["program", "source", "compiles", "compile s",
                 "hits", "status"],
            )
        )
    if state.pbt.get("generations"):
        # Population view (docs/PBT.md): one row per PBT generation,
        # folded from the pbt_gen events either mode emits.
        lines.append("")
        lines.append(
            "population  mode {mode}  K={k}  exploits {x}".format(
                mode=state.pbt.get("mode", "?"),
                k=state.pbt.get("population", "?"),
                x=state.pbt.get("exploit_total", 0),
            )
        )
        prows = []
        gens = state.pbt["generations"]
        for g in sorted(gens):
            row = gens[g]
            prows.append(
                [
                    g,
                    row.get("best_lane", "-"),
                    (
                        f"{row['best_loss']:.4f}"
                        if row.get("best_loss") is not None
                        else "-"
                    ),
                    (
                        f"{row['median_loss']:.4f}"
                        if row.get("median_loss") is not None
                        else "-"
                    ),
                    row.get("exploit_count", 0),
                    (
                        f"{row['rank_churn']:.2f}"
                        if row.get("rank_churn") is not None
                        else "-"
                    ),
                    (
                        f"{row['lr_min']:.2e}/{row['lr_median']:.2e}"
                        f"/{row['lr_max']:.2e}"
                        if row.get("lr_min") is not None
                        else "-"
                    ),
                ]
            )
        lines.append(
            fmt_table(
                prows,
                ["gen", "best lane", "best loss", "median loss",
                 "exploits", "churn", "lr min/med/max"],
            )
        )
    return "\n".join(lines)


def fleet_state(run_dir: str) -> tuple[SweepFold, dict, bool]:
    """Merge the run's shards on the fleet clock and fold them: the
    SAME SweepFold the single-stream console uses (so the trial table
    reads identically), the fleet summary (hosts, worlds, tax,
    lineage), and the FLEET-level done verdict. A merged stream holds
    one sweep_end per controller, so the single-stream ``state.done``
    flips on the FIRST finished host while others still train — under
    a supervisor, done means the final world ended complete; without
    one (no world events), the single-stream flag is all there is."""
    from multidisttorch_tpu.telemetry import fleet as _fleet

    merged = _fleet.merge_fleet(run_dir)
    summary = _fleet.fleet_summary(run_dir, merged=merged)
    state = SweepFold()
    supervised = done = False
    for ev in merged["events"]:
        state.feed(ev)
        if ev.get("kind") == "world_end":
            supervised = True
            if (ev.get("data") or {}).get("outcome") == "complete":
                done = True
        elif ev.get("kind") == "world_start":
            supervised = True
            done = False  # a new world reopens the sweep
    return state, summary, (done if supervised else state.done)


def render_fleet(
    state: SweepFold,
    summary: dict,
    run_dir: str,
    *,
    deadline_s: float = 3.0,
) -> str:
    lines = [
        f"sweep_top --fleet  {run_dir}",
        "events {events}  shards {shards}  torn {torn}  "
        "worlds {worlds}  goodput {gp}".format(
            events=summary["events"],
            shards=len(summary["shards"]),
            torn=summary["torn_lines_total"],
            worlds=len(summary["worlds"]),
            gp=(
                f"{summary['goodput']:.3f}"
                if summary["goodput"] is not None
                else "-"
            ),
        ),
        "",
        "hosts",
    ]
    rows = []
    import time as _time

    now = _time.time()
    for slot_s, h in sorted(
        summary["hosts"].items(), key=lambda kv: int(kv[0])
    ):
        # Age from the corrected lease timestamp at RENDER time — the
        # follow loop renders a cached summary between shard changes,
        # and a dead fleet (no shard ever changes again) must still age
        # toward STALE on screen. lease_age_s is the build-time value
        # kept for --json consumers.
        if h.get("lease_ts_fleet") is not None:
            age = round(now - h["lease_ts_fleet"], 3)
        else:
            age = h.get("lease_age_s")
        skew = (summary["skew"].get(slot_s) or {}).get(
            "applied_offset_s", 0.0
        )
        rows.append(
            [
                slot_s,
                host_health(h.get("lease_status"), age, deadline_s),
                fmt_duration(age) if age is not None else "-",
                h["events"],
                ",".join(str(w) for w in h.get("worlds", [])) or "-",
                fmt_duration(
                    (h["last_ts"] - h["first_ts"])
                    if h.get("first_ts") is not None
                    else None
                ),
                fmt_duration(now - h["last_ts"])
                if h.get("last_ts")
                else "-",
                f"{skew:+.3f}s" if skew else "-",
            ]
        )
    lines.append(
        fmt_table(
            rows,
            ["host", "health", "lease age", "events", "worlds", "span",
             "quiet", "skew"],
            indent="  ",
        )
    )
    lines.extend(["", "worlds"])
    wrows = [
        [
            w.get("epoch"),
            ",".join(str(h) for h in w.get("hosts", [])),
            ",".join(str(h) for h in w.get("lost", [])) or "-",
            w.get("reason") or "-",
            fmt_ts(w.get("ts")),
        ]
        for w in summary["worlds"]
    ]
    lines.append(
        fmt_table(
            wrows, ["epoch", "hosts", "lost", "reason", "formed"],
            indent="  ",
        )
    )
    if summary["restart_tax"]:
        lines.extend(["", "restart tax (per world transition)"])
        trows = []
        for t in summary["restart_tax"]:
            trows.append(
                [
                    t.get("world_epoch"),
                    t.get("trigger") or "-",
                    ",".join(str(h) for h in (t.get("lost") or [])) or "-",
                    fmt_duration(t.get("detect_s")),
                    fmt_duration(t.get("drain_s")),
                    fmt_duration(t.get("relaunch_s")),
                    fmt_duration(t.get("restore_s")),
                    fmt_duration(t.get("first_useful_step_s")),
                    fmt_duration(t.get("total_s")),
                ]
            )
        lines.append(
            fmt_table(
                trows,
                ["world", "trigger", "lost", "detect", "drain",
                 "relaunch", "restore", "first step", "total"],
                indent="  ",
            )
        )
    # fleet.migrated_trials (via the summary) is the one authority on
    # what counts as a migration vs mere lineage
    migrated = {
        tid: summary["lineage"][tid]
        for tid in summary.get("migrated_trials", [])
        if tid in summary["lineage"]
    }
    if migrated:
        lines.extend(["", "trial lineage (migrated trials)"])
        for tid, chain in sorted(migrated.items(), key=lambda kv: int(kv[0])):
            lines.append(
                f"  trial {tid}: "
                + " -> ".join(
                    f"w{c['world']}@h{c['host']}" for c in chain
                )
            )
    lines.append("")
    lines.append(render(state, run_dir))
    return "\n".join(lines)


def follow_lines(path: str, state: SweepFold, offset: int) -> int:
    """Feed decodable complete lines past ``offset``; returns the new
    offset. A torn tail (no trailing newline yet) is left for the next
    poll — the live analog of read_events' torn-tail tolerance."""
    try:
        with open(path) as f:
            f.seek(offset)
            chunk = f.read()
    except OSError:
        return offset
    if not chunk:
        return offset
    consumed = 0
    for line in chunk.splitlines(keepends=True):
        if not line.endswith("\n"):
            break  # torn tail: wait for the writer to finish the line
        consumed += len(line)
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(ev, dict):
            state.feed(ev)
    return offset + consumed


class ServiceFollow:
    """Incremental fold over a sweep SERVICE directory: the
    submission-queue journal and the telemetry events are read from
    persistent byte offsets across refreshes (complete lines only —
    the daemon-side pattern), so a console left following a long-lived
    daemon never re-parses its whole history per redraw. A file
    shorter than its offset (a rewrite under us) resets that fold."""

    def __init__(self, service_dir: str):
        self.service_dir = service_dir
        self.qfold: dict = {}
        self.qoffset = 0
        self.state = SweepFold()
        self.eoffset = 0
        # Incident ledger fold (telemetry/incident.py): same persistent
        # byte-offset discipline — the ledger is append-only, so new
        # complete lines replay onto the standing fold.
        self.ifold: dict = {}
        self.ioffset = 0

    def _guard_shrink(self, path: str, offset: int, reset) -> int:
        try:
            if os.path.getsize(path) < offset:
                reset()
                return 0
        except OSError:
            pass
        return offset

    def refresh(self):
        from multidisttorch_tpu.service.queue import (
            fold_queue_into,
            queue_path,
            read_jsonl_from,
        )

        qp = queue_path(self.service_dir)
        self.qoffset = self._guard_shrink(
            qp, self.qoffset, self.qfold.clear
        )
        recs, self.qoffset = read_jsonl_from(qp, self.qoffset)
        fold_queue_into(self.qfold, recs)
        books = {}
        bpath = os.path.join(self.service_dir, "service_books.json")
        try:
            with open(bpath) as f:
                books = json.load(f)
        except (OSError, json.JSONDecodeError):
            pass
        epath = os.path.join(self.service_dir, "telemetry", EVENTS_NAME)
        if os.path.exists(epath):
            def reset_state():
                self.state = SweepFold()

            self.eoffset = self._guard_shrink(
                epath, self.eoffset, reset_state
            )
            self.eoffset = follow_lines(epath, self.state, self.eoffset)
        from multidisttorch_tpu.telemetry import incident as tincident

        ipath = os.path.join(
            self.service_dir, "telemetry", tincident.INCIDENTS_NAME
        )
        self.ioffset = self._guard_shrink(
            ipath, self.ioffset, self.ifold.clear
        )
        irecs, self.ioffset = read_jsonl_from(ipath, self.ioffset)
        if irecs:
            tincident.fold_incidents_into(self.ifold, irecs)
        return self.qfold, books, self.state, self.ifold


def service_state(service_dir: str):
    """One-shot fold of a sweep SERVICE directory (the follow loop
    keeps a persistent :class:`ServiceFollow` instead)."""
    return ServiceFollow(service_dir).refresh()


def fabric_panel(service_dir: str, *, deadline_s: float = 3.0) -> str:
    """Shard + replica health over a fabric root (docs/SERVICE.md
    "Service fabric"): per-shard owner/epoch/lease verdict from the
    fenced lease streams, per-replica liveness from the membership
    heartbeats."""
    from multidisttorch_tpu.parallel import membership
    from multidisttorch_tpu.service import fabric

    health = fabric.fabric_health(
        service_dir, lease_deadline_s=deadline_s
    )
    lines = [f"service fabric  {service_dir}", ""]
    rows = []
    for k in sorted(health["shards"]):
        s = health["shards"][k]
        rows.append(
            [
                f"shard-{k}",
                s.get("replica", "-"),
                s.get("epoch", "-"),
                fmt_duration(s.get("lease_age_s"))
                if s.get("lease_age_s") is not None
                else "-",
                s["state"].upper()
                if s["state"] in ("stale", "unclaimed")
                else s["state"],
            ]
        )
    if rows:
        lines.append(
            fmt_table(rows, ["shard", "owner", "epoch", "lease", "state"])
        )
    view = membership.MembershipView(service_dir)
    now = time.time()
    reps = []
    for slot, rec in sorted(view.hosts().items()):
        age = now - float(rec.get("ts", 0.0))
        if rec.get("status") == membership.LEFT:
            verdict = "left"
        elif age > deadline_s:
            verdict = "STALE"
        else:
            verdict = "alive"
        reps.append(
            [f"replica-{slot}", rec.get("pid", "-"),
             fmt_duration(age), verdict]
        )
    if reps:
        lines.append("")
        lines.append(
            fmt_table(reps, ["replica", "pid", "beat", "health"])
        )
    lines.append("")
    return "\n".join(lines)


def render_slo_panel(slo: dict) -> str:
    """The SLO/error-budget scoreboard (docs/OBSERVABILITY.md
    "Tracing & SLOs"): one row per (objective, label) with compliance
    vs target, budget spent, and the multi-window burn rates — ALERT
    when both windows burn past their factors."""
    rows = []
    for name, evals in sorted((slo.get("slos") or {}).items()):
        for ev in evals:
            burn = ev.get("burn") or {}
            burn_s = " ".join(
                f"{w}s:{b['burn']}" for w, b in sorted(burn.items())
            )
            comp = ev.get("compliance")
            rows.append(
                [
                    name + (f"[{ev['label']}]" if ev.get("label") else ""),
                    f"{comp:.4f}" if comp is not None else "-",
                    f"{ev.get('objective'):.2f}",
                    f"{ev.get('budget_spent_frac', 0):.2f}",
                    burn_s or "-",
                    "ALERT" if ev.get("alerting") else (
                        "ok" if ev.get("met") else "MISS"
                    ),
                ]
            )
    head = "slo  " + ("(budget spent = error budget consumed, 1.0 = gone)")
    table = fmt_table(
        rows, ["objective", "compliance", "target", "spent", "burn", ""]
    )
    return head + "\n" + table


def _fmt_ctl_s(s) -> str:
    """Sub-millisecond-friendly duration for control-plane phase
    times (fmt_duration floors at ms; these are often microseconds)."""
    if s is None:
        return "-"
    s = float(s)
    if s < 1e-3:
        return f"{s * 1e6:.1f}us"
    if s < 1.0:
        return f"{s * 1e3:.2f}ms"
    return f"{s:.3f}s"


def render_ctl_panel(ctl: dict) -> str:
    """Control-plane flight books (docs/OBSERVABILITY.md
    "Control-plane books"): per-phase wall share, p50/p99 with the
    histogram's bucket-bound ceiling, and work-touched accounting —
    examined vs mutated, whose ratio exposes O(pool) scans that only
    changed O(1) entries."""
    passes = ctl.get("passes") or {}
    wt = ctl.get("work_touched") or {}
    eff = wt.get("scan_efficiency")
    head = (
        f"ctl  passes {passes.get('count', 0)}"
        f"  {fmt_rate(passes.get('per_s'))}"
        f"  pass p99 {_fmt_ctl_s(passes.get('p99_s'))}"
        f"  examined {wt.get('examined', 0)}"
        f"  mutated {wt.get('mutated', 0)}"
        f"  scan-eff {f'{eff:.4f}' if eff is not None else '-'}"
    )
    rows = []
    for name, b in (ctl.get("phases") or {}).items():
        bounds = (b.get("bucket_err") or {}).get("p99_s") or (None, None)
        p_eff = b.get("scan_efficiency")
        rows.append(
            [
                name,
                b.get("calls", 0),
                f"{100.0 * b.get('wall_frac', 0.0):.1f}%",
                _fmt_ctl_s(b.get("p50_s")),
                _fmt_ctl_s(b.get("p99_s")),
                (
                    f"<={_fmt_ctl_s(bounds[1])}"
                    if bounds[1] is not None
                    else "-"
                ),
                b.get("examined", 0),
                b.get("mutated", 0),
                f"{p_eff:.4f}" if isinstance(p_eff, float) else "-",
            ]
        )
    return head + "\n" + fmt_table(
        rows,
        [
            "phase", "calls", "wall", "p50", "p99", "p99-bound",
            "examined", "mutated", "eff",
        ],
    )


def render_incidents_panel(incidents: dict) -> str:
    """Root-cause scoreboard over the service's incident ledger
    (docs/INCIDENTS.md): open incidents first (newest activity on
    top), then the most recently resolved — verdict, subject, dedup
    count, flap count, age, and the trials cited in the evidence."""
    if not incidents:
        return ""
    now = time.time()

    def age(inc):
        ts = inc.get("last_ts")
        return fmt_duration(now - float(ts)) if ts else "-"

    def affected(inc):
        tids = sorted(
            {
                ev.get("trial_id")
                for ev in (inc.get("evidence") or ())
                if isinstance(ev, dict) and ev.get("trial_id") is not None
            },
            key=str,
        )
        if not tids:
            return "-"
        cell = ",".join(str(t) for t in tids[:4])
        return cell + ("…" if len(tids) > 4 else "")

    opens = [
        i for i in incidents.values() if i.get("status") == "open"
    ]
    closed = [
        i for i in incidents.values() if i.get("status") != "open"
    ]
    opens.sort(key=lambda i: -(i.get("last_ts") or 0.0))
    closed.sort(key=lambda i: -(i.get("last_ts") or 0.0))
    rows = []
    for inc in (opens + closed)[:8]:
        rows.append(
            [
                str(inc.get("id")),
                str(inc.get("kind")),
                str(inc.get("subject")),
                str(inc.get("status")),
                inc.get("count", 1),
                inc.get("flaps", 0),
                age(inc),
                affected(inc),
            ]
        )
    lines = [
        f"incidents  open {len(opens)}  resolved {len(closed)}",
        fmt_table(
            rows,
            ["incident", "verdict", "subject", "status", "count",
             "flaps", "age", "trials"],
        ),
        "",
    ]
    return "\n".join(lines)


def render_service(
    folded, books, state, service_dir: str, incidents=None
) -> str:
    """Tenant/queue panel over a service directory (docs/SERVICE.md):
    queue depth by state, per-tenant goodput + fair-share vs weight,
    scheduling-latency books, the fragmentation gauge, defrag +
    preemption accounting and the deadline scoreboard, then the
    per-trial table of whatever telemetry shows."""
    from multidisttorch_tpu.service.queue import QueueStats

    now = time.time()
    lines = [f"sweep service  {service_dir}", ""]
    stats = QueueStats.of(folded)
    lines.append(
        "queue  "
        + (
            "  ".join(
                f"{s} {n}" for s, n in sorted(stats.by_state.items())
            )
            or "empty"
        )
    )
    frag = books.get("fragmentation") or {}
    if frag:
        lines.append(
            f"slices free {frag.get('free_slices')}  largest run "
            f"{frag.get('largest_free_run')}  fragmentation "
            f"{frag.get('now')} (max {frag.get('max')})"
        )
    dfr = books.get("defrag") or {}
    if dfr.get("events"):
        lines.append(
            f"defrag  events {dfr['events']}  moved slices "
            f"{dfr.get('moved_slices')}  unblocked "
            f"{len(dfr.get('unblocked') or [])}"
        )
    pre = books.get("preemption") or {}
    if pre.get("events"):
        lines.append(
            f"preempt  events {pre['events']}  evictions "
            f"{pre.get('evictions')}  slices "
            f"{pre.get('evicted_slices')}  unblocked "
            f"{len(pre.get('unblocked') or [])}"
        )
    ck = books.get("checkpoint") or {}
    if ck.get("saves") or ck.get("pending_persists"):
        # The checkpoint data plane (docs/RESILIENCE.md "Checkpoint
        # format v2"): delta ratio = bytes actually written / total
        # state bytes saved (1.0 = no dedup win), drain split =
        # slices-freed (snapshot) vs durable (persist) latency.
        dr = ck.get("delta_ratio")
        lines.append(
            f"ckpt  fmt {ck.get('format', '?')}  saves "
            f"{ck.get('saves', 0)}  written "
            f"{fmt_bytes(ck.get('bytes_written'))}"
            f"/{fmt_bytes(ck.get('bytes_total'))}"
            f"  delta {dr if dr is not None else '-'}"
            f"  ram-restores {ck.get('restores_ram', 0)}"
            + (
                f"  persisting {ck['pending_persists']}"
                if ck.get("pending_persists")
                else ""
            )
        )
        for label, key in (
            ("drain-snapshot", "drain_snapshot"),
            ("drain-persist", "drain_persist"),
        ):
            h = ck.get(key) or {}
            if h.get("count"):
                lines.append(
                    f"{label}  n {h['count']}  p50 "
                    f"{fmt_duration(h.get('p50_s'))}  p99 "
                    f"{fmt_duration(h.get('p99_s'))}  max "
                    f"{fmt_duration(h.get('max_s'))}"
                )
    dl = books.get("deadline") or {}
    if dl.get("hits") or dl.get("misses") or dl.get("pending"):
        lines.append(
            f"deadline  hits {dl.get('hits', 0)}  misses "
            f"{dl.get('misses', 0)}  hit-rate "
            f"{dl.get('hit_rate') if dl.get('hit_rate') is not None else '-'}"
            f"  pending {dl.get('pending', 0)}"
        )
    for label, key in (
        ("queue-wait", "queue_wait"),
        ("placement", "placement_latency"),
    ):
        h = books.get(key) or {}
        if h.get("count"):
            # p99 exemplar: the worst-offender submission behind the
            # percentile — `sweep_trace <dir> <id>` renders its trace.
            ex = h.get("p99_exemplar") or {}
            ex_s = f"  worst {ex['id']}" if ex.get("id") else ""
            lines.append(
                f"{label}  n {h['count']}  p50 "
                f"{fmt_duration(h.get('p50_s'))}  p99 "
                f"{fmt_duration(h.get('p99_s'))}  max "
                f"{fmt_duration(h.get('max_s'))}{ex_s}"
            )
    ctl = books.get("ctl") or {}
    if ctl.get("enabled"):
        lines.append("")
        lines.append(render_ctl_panel(ctl))
    slo = books.get("slo") or {}
    if slo.get("slos"):
        lines.append("")
        lines.append(render_slo_panel(slo))
    lines.append("")
    tenants = books.get("tenants") or {}
    fair = books.get("fair_share") or {}
    names = sorted(set(tenants) | set(fair) | set(stats.by_tenant))
    if names:
        rows = []
        for t in names:
            tb = tenants.get(t) or {}
            fb = fair.get(t) or {}
            by = stats.by_tenant.get(t) or {}
            rows.append(
                [
                    t,
                    fb.get("weight", "-"),
                    by.get("pending", 0) + by.get("admitted", 0),
                    by.get("placed", 0),
                    by.get("settled", 0),
                    tb.get("useful_steps", "-"),
                    tb.get("goodput") if tb.get("goodput") is not None
                    else "-",
                    fb.get("contended_share") if
                    fb.get("contended_share") is not None else "-",
                    fb.get("ratio_to_weight") if
                    fb.get("ratio_to_weight") is not None else "-",
                ]
            )
        lines.append(
            fmt_table(
                rows,
                ["tenant", "w", "queued", "run", "done", "useful",
                 "goodput", "share", "share/w"],
            )
        )
        lines.append("")
    # Waiting/running submissions, oldest first.
    live = [
        r for r in folded.values()
        if r["state"] in ("pending", "admitted", "placed")
    ]
    if live:
        rows = []
        for r in sorted(live, key=lambda r: r.get("submit_ts") or 0.0):
            # Deadline column: time remaining on the submission's tag
            # (negative = already overdue), "-" for best-effort.
            dl_s = r.get("deadline_s")
            if dl_s is not None and r.get("submit_ts"):
                remaining = r["submit_ts"] + float(dl_s) - now
                dl_cell = (
                    f"-{fmt_duration(-remaining)}"
                    if remaining < 0
                    else fmt_duration(remaining)
                )
            else:
                dl_cell = "-"
            rows.append(
                [
                    r["submission_id"][:24],
                    r.get("tenant", "?"),
                    r.get("priority", "-"),
                    r["state"],
                    r.get("size", 1),
                    fmt_duration(now - r["submit_ts"])
                    if r.get("submit_ts") else "-",
                    dl_cell,
                ]
            )
        lines.append(
            fmt_table(
                rows,
                ["submission", "tenant", "pri", "state", "size", "age",
                 "deadline"],
            )
        )
        lines.append("")
    if incidents:
        lines.append(render_incidents_panel(incidents))
    if state.trials:
        lines.append(render(state, service_dir))
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="live console over a sweep's telemetry event JSONL"
    )
    parser.add_argument(
        "path",
        help="telemetry dir (containing events.jsonl) or the JSONL "
        "file; with --fleet, the elastic RUN dir (containing "
        "telemetry/ and membership/)",
    )
    parser.add_argument(
        "-f", "--follow", action="store_true",
        help="keep tailing and redraw every --interval seconds",
    )
    parser.add_argument(
        "--fleet", action="store_true",
        help="fleet view over an elastic multi-host run dir: merge "
        "every per-host/per-world shard on the skew-corrected fleet "
        "clock and add host health, world history, restart tax, and "
        "migration lineage (docs/OBSERVABILITY.md \"Fleet\")",
    )
    parser.add_argument(
        "--service", action="store_true",
        help="tenant/queue view over a sweep SERVICE directory "
        "(docs/SERVICE.md): submission-queue depth by state, per-tenant "
        "goodput and fair-share vs weight, queue-wait/placement-latency "
        "books, the fragmentation gauge, defrag/preemption accounting "
        "and the deadline scoreboard, plus the usual per-trial table "
        "when telemetry is on; over a FABRIC root, adds per-shard "
        "owner/epoch/lease health and replica heartbeats and renders "
        "every shard's panel",
    )
    parser.add_argument(
        "--deadline", type=float, default=3.0,
        help="heartbeat staleness (s) behind the fleet view's host "
        "health verdict — match the supervisor's --heartbeat-deadline",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="one-shot machine-readable snapshot of the fold (trials, "
        "goodput, device books) instead of the rendered console — for "
        "CI and scripts; mutually exclusive with --follow",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--max-refreshes", type=int, default=0,
        help="stop after N redraws (0 = until interrupted/sweep end; "
        "mostly for tests)",
    )
    args = parser.parse_args(argv)
    if args.json and args.follow:
        parser.error("--json is one-shot; it cannot combine with --follow")

    if args.service:
        if not os.path.isdir(args.path):
            print(f"--service expects a service directory, got {args.path}",
                  file=sys.stderr)
            return 1

        from multidisttorch_tpu.service import fabric as _fabric

        fabric_cfg = _fabric.read_fabric_config(args.path)
        shard_dirs = (
            {
                k: _fabric.shard_dir(args.path, k)
                for k in range(int(fabric_cfg["n_shards"]))
            }
            if fabric_cfg
            else {None: args.path}
        )

        def render_all(states) -> str:
            parts = []
            if fabric_cfg:
                parts.append(
                    fabric_panel(args.path, deadline_s=args.deadline)
                )
            for k, (folded, books, state, incidents) in states.items():
                d = shard_dirs[k]
                parts.append(
                    render_service(
                        folded, books, state, d, incidents=incidents
                    )
                )
            return "\n".join(parts)

        def service_shot():
            states = {
                k: service_state(d) for k, d in shard_dirs.items()
            }
            if args.json:
                snap = {
                    "service_dir": args.path,
                    "shards": {},
                }
                if fabric_cfg:
                    snap["fabric"] = _fabric.fabric_health(
                        args.path, lease_deadline_s=args.deadline
                    )
                for k, (
                    folded, books, state, incidents
                ) in states.items():
                    snap["shards"][str(k) if k is not None else "_"] = {
                        "queue": folded,
                        "books": books,
                        "incidents": incidents,
                        "trials": {
                            t: state.trials[t]
                            for t in sorted(state.trials)
                        },
                    }
                if not fabric_cfg:
                    # Pre-fabric shape, kept for scripts: the single
                    # service's fold at top level.
                    only = snap["shards"]["_"]
                    snap.update(only)
                print(json.dumps(snap, default=str))
            else:
                print(render_all(states))

        if not args.follow:
            service_shot()
            return 0
        refreshes = 0
        fols = {k: ServiceFollow(d) for k, d in shard_dirs.items()}
        try:
            while True:
                states = {k: f.refresh() for k, f in fols.items()}
                print(
                    clear_screen() + render_all(states),
                    flush=True,
                )
                refreshes += 1
                if args.max_refreshes and refreshes >= args.max_refreshes:
                    break
                time.sleep(args.interval)
        except KeyboardInterrupt:
            pass
        return 0

    if args.fleet:
        if not os.path.isdir(args.path):
            print(f"--fleet expects a run directory, got {args.path}",
                  file=sys.stderr)
            return 1

        def one_shot():
            state, summary, _done = fleet_state(args.path)
            if args.json:
                # The machine-readable fleet snapshot: the summary
                # (hosts/worlds/tax/lineage) plus the same per-trial
                # fold the single-stream --json emits.
                summary = dict(summary)
                summary["trials"] = {
                    k: state.trials[k] for k in sorted(state.trials)
                }
                print(json.dumps(summary, default=str))
            else:
                print(
                    render_fleet(
                        state, summary, args.path,
                        deadline_s=args.deadline,
                    )
                )
            return state

        if not args.follow:
            one_shot()
            return 0

        def fleet_sig():
            # Cheap change detector for the follow loop: (path, size,
            # mtime) of every shard plus the membership sideband. The
            # merge itself is O(total events) — append-only shards
            # mean an unchanged signature makes a re-merge pure waste,
            # so idle refreshes only re-render (lease ages still age).
            from multidisttorch_tpu.telemetry import fleet as _fleet

            paths = _fleet.discover_shards(args.path)
            mdir = os.path.join(args.path, "membership")
            if os.path.isdir(mdir):
                paths = paths + [
                    os.path.join(mdir, n) for n in sorted(os.listdir(mdir))
                ]
            sig = []
            for p in paths:
                try:
                    st = os.stat(p)
                    sig.append((p, st.st_size, st.st_mtime))
                except OSError:
                    sig.append((p, -1, -1.0))
            return tuple(sig)

        refreshes = 0
        state = summary = None
        fleet_done = False
        last_sig = None
        try:
            while True:
                sig = fleet_sig()
                if state is None or sig != last_sig:
                    state, summary, fleet_done = fleet_state(args.path)
                    last_sig = sig
                print(
                    clear_screen()
                    + render_fleet(
                        state, summary, args.path,
                        deadline_s=args.deadline,
                    ),
                    flush=True,
                )
                refreshes += 1
                if fleet_done:
                    break
                if args.max_refreshes and refreshes >= args.max_refreshes:
                    break
                time.sleep(args.interval)
        except KeyboardInterrupt:
            pass
        return 0

    path = resolve_events_path(args.path)
    if not os.path.exists(path) and not args.follow:
        print(f"no event file at {path}", file=sys.stderr)
        return 1
    state = SweepFold()
    offset = follow_lines(path, state, 0)
    if args.json:
        print(json.dumps(snapshot(state, path), default=str))
        return 0
    if not args.follow:
        print(render(state, path))
        return 0
    refreshes = 0
    try:
        while True:
            print(clear_screen() + render(state, path), flush=True)
            refreshes += 1
            if state.done:
                break
            if args.max_refreshes and refreshes >= args.max_refreshes:
                break
            time.sleep(args.interval)
            offset = follow_lines(path, state, offset)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
