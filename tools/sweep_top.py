#!/usr/bin/env python
"""Live sweep console: tail the telemetry event JSONL and render
per-trial status, step rates, retries, and sweep goodput.

    python tools/sweep_top.py <telemetry-dir-or-events.jsonl> [--follow]

Works on a LIVE run (``--follow`` re-reads new lines each interval and
redraws — the sink is flushed per event, so a running sweep streams)
or on a finished one (one-shot render). It only reads the JSONL — it
never initializes a jax backend or touches the accelerator, so it can
run next to a live sweep.

Enable telemetry on the sweep side with ``MDT_TELEMETRY=1
MDT_TELEMETRY_DIR=<dir>`` or ``telemetry.telemetry_run(<dir>)`` — see
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Allow running straight from a checkout (tools/ is not a package).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.telemetry.console import (  # noqa: E402
    clear_screen,
    fmt_bytes,
    fmt_duration,
    fmt_mfu,
    fmt_rate,
    fmt_table,
    fmt_ts,
    status_glyph,
)
from multidisttorch_tpu.telemetry.events import EVENTS_NAME  # noqa: E402
from multidisttorch_tpu.telemetry.export import SweepFold  # noqa: E402


def resolve_events_path(path: str) -> str:
    if os.path.isdir(path):
        return os.path.join(path, EVENTS_NAME)
    return path


def live_mfu(state: SweepFold, tid: int, rate) -> "float | None":
    """Best-effort live MFU for one trial: its device_cost book's
    per-lane-step FLOPs x its own step rate over the submesh peak.
    None off-TPU (no peak) or before the cost book lands."""
    key = state.series_key_of(tid)
    book = state.device.get(key) if key else None
    if not book or not rate:
        return None
    flops = book.get("flops_per_lane_step")
    peak = book.get("peak_flops_per_chip")
    ndev = book.get("devices") or 1
    if not flops or not peak:
        return None
    return flops * rate / (peak * ndev)


def snapshot(state: SweepFold, path: str) -> dict:
    """Machine-readable one-shot fold of the event stream — the same
    accounting the rendered console shows, JSON-shaped so CI and
    scripts can consume it without screen-scraping (``--json``)."""
    return {
        "path": path,
        "events": state.events,
        "first_ts": state.first_ts,
        "last_ts": state.last_ts,
        "sweep": state.sweep,
        "done": state.done,
        "useful_steps": state.useful,
        "executed_steps": state.executed,
        "goodput": state.goodput,
        "anomalies": state.anomalies,
        "trials": {k: state.trials[k] for k in sorted(state.trials)},
        "device_books": {k: state.device[k] for k in sorted(state.device)},
    }


def render(state: SweepFold, path: str) -> str:
    lines = []
    span = (
        (state.last_ts - state.first_ts)
        if state.first_ts is not None
        else None
    )
    head = [
        f"sweep_top  {path}",
        f"events {state.events}"
        + (f"  span {fmt_duration(span)}" if span is not None else "")
        + (f"  last {fmt_ts(state.last_ts)}" if state.last_ts else ""),
    ]
    if state.sweep:
        head.append(
            "configs {configs}  groups {groups}  stacked {stacked}".format(
                configs=state.sweep.get("configs", "?"),
                groups=state.sweep.get("groups", "?"),
                stacked=state.sweep.get("stacked", False),
            )
        )
    goodput = state.goodput
    head.append(
        "goodput "
        + (f"{goodput:.3f}" if goodput is not None else "-")
        + f"  (useful {state.useful} / executed {state.executed} steps)"
        + ("  [sweep finished]" if state.done else "")
    )
    lines.extend(head)
    lines.append("")
    rows = []
    for tid in sorted(state.trials):
        t = state.trials[tid]
        wall = (
            t["last_ts"] - t["first_ts"]
            if t["first_ts"] is not None and t["last_ts"] is not None
            else None
        )
        rate = t["step"] / wall if wall and t["step"] else None
        key = state.series_key_of(tid)
        book = state.device.get(key, {}) if key else {}
        rows.append(
            [
                tid,
                status_glyph(t["status"]),
                t["attempts"] or "-",
                t["epoch"] or "-",
                t["step"] or "-",
                fmt_rate(rate, "/s") if rate else "-",
                f"{t['train_loss']:.4f}" if t["train_loss"] is not None
                else "-",
                f"{t['test_loss']:.4f}" if t["test_loss"] is not None
                else "-",
                t["retries"],
                t["faults"],
                t["lane"] if t["lane"] is not None else "-",
                fmt_mfu(live_mfu(state, tid, rate)),
                fmt_bytes(book.get("peak_bytes")),
                t.get("anomalies", 0) or "-",
                fmt_duration(wall),
            ]
        )
    lines.append(
        fmt_table(
            rows,
            ["trial", "status", "att", "epoch", "steps", "step rate",
             "train loss", "test loss", "retries", "faults", "lane",
             "mfu", "peak mem", "anom", "wall"],
        )
    )
    return "\n".join(lines)


def follow_lines(path: str, state: SweepFold, offset: int) -> int:
    """Feed decodable complete lines past ``offset``; returns the new
    offset. A torn tail (no trailing newline yet) is left for the next
    poll — the live analog of read_events' torn-tail tolerance."""
    try:
        with open(path) as f:
            f.seek(offset)
            chunk = f.read()
    except OSError:
        return offset
    if not chunk:
        return offset
    consumed = 0
    for line in chunk.splitlines(keepends=True):
        if not line.endswith("\n"):
            break  # torn tail: wait for the writer to finish the line
        consumed += len(line)
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(ev, dict):
            state.feed(ev)
    return offset + consumed


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="live console over a sweep's telemetry event JSONL"
    )
    parser.add_argument(
        "path",
        help="telemetry dir (containing events.jsonl) or the JSONL file",
    )
    parser.add_argument(
        "-f", "--follow", action="store_true",
        help="keep tailing and redraw every --interval seconds",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="one-shot machine-readable snapshot of the fold (trials, "
        "goodput, device books) instead of the rendered console — for "
        "CI and scripts; mutually exclusive with --follow",
    )
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--max-refreshes", type=int, default=0,
        help="stop after N redraws (0 = until interrupted/sweep end; "
        "mostly for tests)",
    )
    args = parser.parse_args(argv)
    if args.json and args.follow:
        parser.error("--json is one-shot; it cannot combine with --follow")

    path = resolve_events_path(args.path)
    if not os.path.exists(path) and not args.follow:
        print(f"no event file at {path}", file=sys.stderr)
        return 1
    state = SweepFold()
    offset = follow_lines(path, state, 0)
    if args.json:
        print(json.dumps(snapshot(state, path), default=str))
        return 0
    if not args.follow:
        print(render(state, path))
        return 0
    refreshes = 0
    try:
        while True:
            print(clear_screen() + render(state, path), flush=True)
            refreshes += 1
            if state.done:
                break
            if args.max_refreshes and refreshes >= args.max_refreshes:
                break
            time.sleep(args.interval)
            offset = follow_lines(path, state, offset)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
