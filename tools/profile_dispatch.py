"""Measure single-host dispatch contention across concurrent trials.

SURVEY §7 calls host-side dispatch the "hard part" of the north-star
metric (>= 90% per-trial efficiency at 8 concurrent trials): every
trial's jit steps are enqueued from ONE Python host loop
(``hpo/driver.py``'s cooperative round-robin), so even with disjoint
submeshes the host can become the serializing resource. The hardware
half of the question needs >= 2 real chips; THIS half — where the
per-trial host time goes as concurrency rises — is measurable on the
8-virtual-CPU-device mesh today (VERDICT r4 item 5).

Protocol, per concurrency level N (1, 2, 4, 8):

- carve N disjoint submeshes, one flagship-VAE trial on each
  (scan-fused ``make_multi_step`` — the production dispatch shape);
- warm up every trial's compile;
- timed region: K rounds of round-robin dispatch. For every ``step()``
  call record the HOST time it takes to RETURN (async dispatch cost:
  arg validation/donation + enqueue — the serialized-on-the-host part),
  then block on all trials once and record the wall-clock.

Reported per N: mean/p99 per-dispatch host cost, aggregate dispatch
seconds, wall-clock, and dispatch share of wall — if the dispatch share
approaches 1, the host loop (not the devices) caps trial concurrency.
Set ``--trace DIR`` to wrap the LARGEST level's whole timed region in
``jax.profiler.trace`` for timeline evidence (TensorBoard/Perfetto) —
tracing perturbs that level's numbers, so take clean measurements from
a separate untraced pass.

CPU caveat, stated on the artifact: virtual CPU devices run the actual
math on the same host cores, so ``wall_s`` mixes compute contention
into the denominator; the *dispatch-cost* columns (host enqueue time)
are the transferable signal, device-kind-independent by construction.

Usage:
  env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python tools/profile_dispatch.py [--rounds 30] [--trace /tmp/trace]

Prints one JSON object; findings are summarized in docs/DISPATCH.md.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time

import numpy as np

# Allow `python tools/profile_dispatch.py` from the repo root without
# installation (mirrors bench.py's import situation).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

BATCH = 128
CHUNK_STEPS = 100  # optimizer updates fused per dispatch (bench parity);
# --chunk-steps 1 reproduces the reference's one-dispatch-per-batch
# loop shape (vae-hpo.py:67-74), the configuration where host dispatch
# CAN become the serializing resource.


def _setup_trials(n: int):
    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import create_train_state, make_multi_step

    groups = setup_groups(n)
    model = VAE(hidden_dim=400, latent_dim=20)
    tx = optax.adam(1e-3)
    batches_np = (
        np.random.default_rng(0)
        .uniform(0, 1, (CHUNK_STEPS, BATCH, 784))
        .astype(np.float32)
    )
    trials = []
    for g in groups:
        state = create_train_state(g, model, tx, jax.random.key(g.group_id))
        step = make_multi_step(g, model, tx)
        batches = jax.device_put(
            jnp.asarray(batches_np), g.sharding(None, "data")
        )
        trials.append({"g": g, "state": state, "step": step, "batches": batches})
    return trials


def measure(
    n: int, rounds: int, trace_dir: str | None, queue_depth: int = 2
) -> dict:
    trials = _setup_trials(n)
    key = jax.random.key(1)

    # Warmup pass 1 — COMPILE, timed on its own. Round-5's level-1
    # artifact carried a 5053 ms dispatch p99 that was really this cost
    # plus queue backpressure bleeding into the timed window; the
    # sweep's one-off compile cost now lands in its own field instead of
    # inflating a percentile it doesn't belong to.
    t0 = time.perf_counter()
    for t in trials:
        t["state"], _ = t["step"](t["state"], t["batches"], key)
    for t in trials:
        jax.block_until_ready(t["state"].params)
    compile_s = time.perf_counter() - t0

    # Warmup pass 2 — steady state: donation paths and executable
    # caches warm, device queues empty when the timed window opens.
    for t in trials:
        t["state"], _ = t["step"](
            t["state"], t["batches"], jax.random.fold_in(key, 2**20)
        )
    for t in trials:
        jax.block_until_ready(t["state"].params)

    # Timed window with BOUNDED in-flight work: at most `queue_depth`
    # un-awaited chunks per trial. Without the bound, dispatch number
    # `depth+1` blocks inside step() until the device drains — time the
    # DEVICE owes showing up in the HOST-cost column (the round-5 p99
    # anomaly's second half). The block now happens on a retained
    # metrics handle OUTSIDE the dispatch timer and is reported as
    # backpressure, which is what it is.
    from collections import deque

    dispatch_ns = []
    backpressure_ns = 0
    pending: dict[int, deque] = {i: deque() for i in range(len(trials))}
    ctx = (
        jax.profiler.trace(trace_dir)
        if trace_dir
        else contextlib.nullcontext()
    )
    t_wall = time.perf_counter()
    with ctx:
        for r in range(rounds):
            for i, t in enumerate(trials):  # the driver's round-robin shape
                t0 = time.perf_counter_ns()
                t["state"], m = t["step"](
                    t["state"], t["batches"], jax.random.fold_in(key, r)
                )
                dispatch_ns.append(time.perf_counter_ns() - t0)
                q = pending[i]
                q.append(m["loss_sum"])
                if len(q) > queue_depth:
                    tb = time.perf_counter_ns()
                    jax.block_until_ready(q.popleft())
                    backpressure_ns += time.perf_counter_ns() - tb
        tb = time.perf_counter_ns()
        for t in trials:
            jax.block_until_ready(t["state"].params)
        backpressure_ns += time.perf_counter_ns() - tb
    wall = time.perf_counter() - t_wall

    d_ms = np.asarray(dispatch_ns, dtype=np.float64) / 1e6
    agg_dispatch_s = float(d_ms.sum()) / 1e3
    return {
        "num_trials": n,
        "rounds": rounds,
        "queue_depth": queue_depth,
        "compile_s": round(compile_s, 3),
        "dispatches": len(dispatch_ns),
        "dispatch_ms_mean": round(float(d_ms.mean()), 3),
        "dispatch_ms_p50": round(float(np.percentile(d_ms, 50)), 3),
        "dispatch_ms_p99": round(float(np.percentile(d_ms, 99)), 3),
        "dispatch_s_total": round(agg_dispatch_s, 3),
        # Time spent waiting on devices at the bounded queue edge —
        # device-owed time, attributed to its owner instead of to the
        # dispatch percentiles.
        "backpressure_s_total": round(backpressure_ns / 1e9, 3),
        "wall_s": round(wall, 3),
        # The serialized-host share: while step() has not returned, NO
        # other trial can be fed. This is the quantity that must stay
        # << 1 for the >= 0.90 north-star to be reachable at all.
        "host_dispatch_share_of_wall": round(agg_dispatch_s / wall, 3),
        "backpressure_share_of_wall": round(
            backpressure_ns / 1e9 / wall, 3
        ),
        "samples_per_sec_per_trial": round(
            rounds * CHUNK_STEPS * BATCH / wall, 1
        ),
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--rounds", type=int, default=30)
    p.add_argument("--levels", type=int, nargs="*", default=[1, 2, 4, 8])
    p.add_argument("--chunk-steps", type=int, default=None,
                   help="override CHUNK_STEPS (1 = the reference's "
                   "dispatch-per-batch shape)")
    p.add_argument("--trace", default=None,
                   help="capture a jax.profiler trace of the LARGEST "
                   "level into this directory (adds overhead — run a "
                   "separate untraced pass for clean numbers)")
    p.add_argument("--queue-depth", type=int, default=2,
                   help="max un-awaited chunks in flight per trial; the "
                   "bound keeps device backpressure out of the "
                   "dispatch-time columns (reported separately)")
    args = p.parse_args()
    if args.chunk_steps:
        global CHUNK_STEPS
        CHUNK_STEPS = args.chunk_steps

    ndev = len(jax.devices())
    levels = [n for n in args.levels if n <= ndev]
    out = {
        "platform": jax.default_backend(),
        "n_devices": ndev,
        "chunk_steps": CHUNK_STEPS,
        "batch": BATCH,
        "cpu_caveat": (
            "virtual CPU devices share host cores: wall_s includes "
            "compute contention; dispatch_* columns are the "
            "transferable host-side signal"
        ) if jax.default_backend() == "cpu" else None,
        "levels": [
            measure(
                n, args.rounds,
                args.trace if n == max(levels) else None,
                queue_depth=args.queue_depth,
            )
            for n in levels
        ],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
