#!/usr/bin/env python
"""Incident plane CLI: list, inspect, autopsy, resolve.

    python tools/incident.py <root>                        # list incidents
    python tools/incident.py <root> --json                 # machine form
    python tools/incident.py <root> show inc-0001          # one incident
    python tools/incident.py <root> report inc-0001        # causal autopsy
    python tools/incident.py <root> report inc-0001 --out autopsy/
    python tools/incident.py <root> resolve inc-0001 --reason "mitigated"
    python tools/incident.py <root> sweep                  # quarantine torn bundles

``<root>`` is any directory holding telemetry (a run dir, a service
dir, a fabric root): every ``incidents.jsonl`` below it is folded.
``report`` walks the durable surfaces (event shards, ledger, lease /
topology / steal streams, span trees, fired faults, ctlprof books,
anomaly captures) and exports the bundle — report JSON, merged Perfetto
slice, affected-trace list, next to the fire-time flight-ring dump.
``sweep`` renames ``*.partial`` bundle dirs (a crash between dump and
publish) to ``*.quarantined`` so nothing mistakes them for whole
bundles. docs/INCIDENTS.md is the verdict cookbook.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.telemetry import incident as tincident  # noqa: E402


def fmt_age(ts, now=None) -> str:
    if ts is None:
        return "?"
    dt = (time.time() if now is None else now) - float(ts)
    if dt < 0:
        dt = 0.0
    if dt < 120:
        return f"{dt:.0f}s"
    if dt < 7200:
        return f"{dt / 60:.0f}m"
    return f"{dt / 3600:.1f}h"


def render_list(folded: dict) -> str:
    if not folded:
        return "no incidents on record"
    lines = [
        f"{'id':<10}{'kind':<18}{'subject':<22}{'status':<10}"
        f"{'count':>6}{'flaps':>6}  {'age':>6}"
    ]
    for iid in sorted(folded):
        inc = folded[iid]
        lines.append(
            f"{iid:<10}{str(inc.get('kind')):<18}"
            f"{str(inc.get('subject')):<22}{str(inc.get('status')):<10}"
            f"{inc.get('count', 1):>6}{inc.get('flaps', 0):>6}  "
            f"{fmt_age(inc.get('last_ts')):>6}"
        )
    return "\n".join(lines)


def render_show(inc: dict) -> str:
    lines = [
        f"{inc['id']}  {inc.get('kind')}  [{inc.get('subject')}]  "
        f"{inc.get('status')}",
        f"  first {inc.get('first_ts')}  last {inc.get('last_ts')}  "
        f"count {inc.get('count')}  flaps {inc.get('flaps')}",
    ]
    if inc.get("resolved_reason"):
        lines.append(f"  resolved: {inc['resolved_reason']}")
    if inc.get("detail"):
        lines.append(f"  detail: {json.dumps(inc['detail'], default=str)}")
    for ev in inc.get("evidence") or ():
        lines.append(
            f"  evidence: {ev.get('kind')} ts={ev.get('ts')} "
            f"{json.dumps(ev.get('data') or {}, default=str)[:140]}"
        )
    if inc.get("ledger"):
        lines.append(f"  ledger: {inc['ledger']}")
    return "\n".join(lines)


def _lookup(root: str, iid: str) -> dict:
    folded = tincident.load_incidents(root)
    if iid not in folded:
        raise SystemExit(
            f"unknown incident {iid!r}; known: {sorted(folded) or 'none'}"
        )
    return folded[iid]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="incident ledger viewer + causal autopsy",
    )
    parser.add_argument("root", help="run dir / service dir / fabric root")
    parser.add_argument(
        "cmd", nargs="?", default="list",
        choices=("list", "show", "report", "resolve", "sweep"),
    )
    parser.add_argument("incident", nargs="?", default=None)
    parser.add_argument("--out", default=None, help="report bundle dir")
    parser.add_argument(
        "--window", type=float, default=120.0,
        help="autopsy timeline pad seconds around the incident",
    )
    parser.add_argument("--reason", default="operator resolve")
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    if args.cmd in ("show", "report", "resolve") and not args.incident:
        parser.error(f"{args.cmd} needs an incident id")

    if args.cmd == "list":
        folded = tincident.load_incidents(args.root)
        if args.json:
            print(json.dumps(folded, indent=1, default=str))
        else:
            print(render_list(folded))
        return 0

    if args.cmd == "show":
        inc = _lookup(args.root, args.incident)
        print(
            json.dumps(inc, indent=1, default=str)
            if args.json
            else render_show(inc)
        )
        return 0

    if args.cmd == "report":
        report = tincident.build_incident_report(
            args.root, args.incident, args.out, window_s=args.window
        )
        if args.json:
            print(json.dumps(report, indent=1, default=str))
        else:
            print(
                f"verdict: {report['verdict']}  "
                f"[{report['subject']}]"
            )
            print(
                "corroborating surfaces: "
                + (", ".join(report["corroborating_surfaces"]) or "none")
            )
            print(
                f"timeline: {len(report['timeline'])} records"
                + (
                    f" ({report['timeline_elided']} elided)"
                    if report.get("timeline_elided")
                    else ""
                )
            )
            print(f"affected traces: {len(report['affected_traces'])}")
            if report.get("bundle_dir"):
                print(f"bundle: {report['bundle_dir']}")
        return 0

    if args.cmd == "resolve":
        inc = _lookup(args.root, args.incident)
        if inc.get("status") == tincident.RESOLVED:
            print(f"{args.incident} already resolved")
            return 0
        ledger = inc.get("ledger")
        if not ledger:
            raise SystemExit(f"{args.incident} has no ledger on disk")
        tincident._fsync_append(
            ledger,
            {
                "rec": "resolve",
                "id": inc["id"],
                "ts": time.time(),
                "reason": args.reason,
                "count": inc.get("count", 1),
                "flaps": inc.get("flaps", 0),
            },
        )
        print(f"{args.incident} resolved: {args.reason}")
        return 0

    if args.cmd == "sweep":
        swept: list = []
        for led in tincident.discover_incident_ledgers(args.root):
            swept.extend(
                tincident.sweep_partial_bundles(os.path.dirname(led))
            )
        if args.json:
            print(json.dumps({"quarantined": swept}))
        else:
            for p in swept:
                print(f"quarantined {p}")
            print(f"{len(swept)} partial bundle(s) quarantined")
        return 0

    return 2


if __name__ == "__main__":
    raise SystemExit(main())
