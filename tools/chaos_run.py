#!/usr/bin/env python
"""Chaos drill CLI: run the standard fault schedule against the HPO
driver's supervision stack and report recovery + goodput.

    JAX_PLATFORMS=cpu python tools/chaos_run.py \
        --out artifacts/bench_chaos_cpu.json

Runs entirely on CPU (8 virtual devices) with a CI-sized sweep: every
infra fault in ``FaultPlan.standard`` must be recovered automatically
(retry-with-resume, lane refill, ledger restart after the simulated
preemption), the injected divergence must settle as a terminal
``diverged`` result, and goodput (useful/executed optimizer steps) is
the recovery-overhead headline. ``bench.py --chaos`` wraps the same
protocol (``multidisttorch_tpu/faults/harness.py``) with the bench's
artifact conventions; this CLI is the standalone, plan-tweakable form.

A custom plan can be drilled with ``--plan my_plan.json`` (the
``FaultPlan.to_json`` format) — see docs/RESILIENCE.md for how to write
one.
"""

import argparse
import json
import os
import sys
import tempfile

# Allow running straight from a checkout (tools/ is not a package).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(
        description="deterministic fault-injection drill for run_hpo "
        "supervision (see docs/RESILIENCE.md)"
    )
    parser.add_argument(
        "--out", default=None,
        help="write the full JSON report here (default: stdout only)",
    )
    parser.add_argument(
        "--work-dir", default=None,
        help="sweep scratch dir (default: a fresh temp dir)",
    )
    parser.add_argument("--trials", type=int, default=6)
    parser.add_argument("--epochs", type=int, default=4)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--stacked", action="store_true",
        help="drill the trial-stacking path instead (lane fault -> "
        "mask-and-refill recovery; preemption excluded: stacked sweeps "
        "do not resume)",
    )
    parser.add_argument(
        "--no-preempt", action="store_true",
        help="skip the simulated host preemption + driver restart",
    )
    parser.add_argument(
        "--plan", default=None,
        help="drill a custom FaultPlan JSON file (FaultPlan.to_json "
        "format; trial_ids must be 0..trials-1) instead of the "
        "standard schedule. Report-only: the goodput >= 0.8 acceptance "
        "gate applies to the standard schedule only",
    )
    parser.add_argument(
        "--multihost", action="store_true",
        help="run the ELASTIC multi-host drill instead: N worker "
        "processes under tools/sweep_supervisor.py, a host_lost/wedge "
        "fault on one host mid-sweep, supervised world-shrink restart, "
        "ledger-driven trial migration (docs/RESILIENCE.md \"Elastic "
        "multi-host\")",
    )
    parser.add_argument("--mh-hosts", type=int, default=3)
    parser.add_argument("--mh-devs-per-host", type=int, default=2)
    parser.add_argument(
        "--mh-kind", choices=("host_lost", "wedge"), default="host_lost",
        help="the injected host fault: host_lost = instant os._exit "
        "(SIGKILL semantics); wedge = the host stalls with its "
        "heartbeat suspended and survivors must exit with a named "
        "WedgedCollective within the watchdog deadline",
    )
    parser.add_argument("--mh-victim", type=int, default=1)
    parser.add_argument(
        "--fabric", action="store_true",
        help="run the service-fabric failover drill instead: 2 fabric "
        "replica daemons armed with a seeded FaultPlan whose "
        "daemon_lost spec SIGKILLs the victim replica on its dispatch "
        "clock; the survivor must adopt the orphaned shard (lease-"
        "fenced epoch claim + journal replay) and settle every "
        "submission (docs/SERVICE.md \"Service fabric\")",
    )
    parser.add_argument(
        "--fabric-victim", type=int, default=1, choices=(0, 1),
        help="which of the two replicas the daemon_lost spec targets",
    )
    parser.add_argument(
        "--fabric-step", type=int, default=12,
        help="the victim's cumulative dispatch count at which "
        "daemon_lost fires",
    )
    parser.add_argument(
        "--mh-groups", default="per_host",
        help="submesh carve for the drill: 'per_host' (default; "
        "bit-parity applies, and the wedge surfaces at the bounded "
        "end-of-sweep sideband barrier) or an integer group count "
        "(e.g. 1 = one group spanning all hosts — needs a backend "
        "with cross-process XLA computations, i.e. NOT the CPU "
        "backend this tool forces)",
    )
    parser.add_argument(
        "--mh-agree-timeout", type=float, default=15.0,
        help="MDT_AGREE_TIMEOUT_S for the workers: the wedge-watchdog "
        "deadline the WedgedCollective exit is asserted against",
    )
    parser.add_argument(
        "--telemetry-dir", default=None,
        help="write the chaos run's telemetry (events.jsonl, Perfetto "
        "trace.json, metrics.prom, summary.json) here instead of "
        "{work_dir}/telemetry — what CI uploads as artifacts; open the "
        "trace at https://ui.perfetto.dev (docs/OBSERVABILITY.md)",
    )
    args = parser.parse_args()

    # 8 virtual CPU devices (the test harness topology) so 2 submesh
    # groups exist even on a laptop; must land before backend init.
    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    work_dir = args.work_dir or tempfile.mkdtemp(prefix="chaos_run_")

    if args.fabric:
        from multidisttorch_tpu.service.fabric_drill import (
            run_fabric_chaos,
        )

        report = run_fabric_chaos(
            work_dir,
            victim=args.fabric_victim,
            step=args.fabric_step,
            seed=args.seed,
        )
        headline = {
            "metric": "fabric_chaos_zero_lost_after_daemon_lost",
            "value": 1.0 if report["zero_lost"] else 0.0,
            "unit": "all submissions settled across a SIGKILLed "
            "replica + shard adoption",
            "victim_sigkilled": report["victim_sigkilled"],
            "fault_fired": report["fault_fired"],
            "survivor_claimed_victims_shard": report[
                "survivor_claimed_victims_shard"
            ],
            "completed": report["completed"],
            "submissions": report["submissions"],
            "detail": report,
        }
        print(json.dumps(headline))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(headline, f, indent=2)
            os.replace(tmp, args.out)
            print(f"report written to {args.out}", file=sys.stderr)
        return 0 if report["ok"] else 1

    if args.multihost:
        from multidisttorch_tpu.faults.harness import run_chaos_mh_bench

        report = run_chaos_mh_bench(
            work_dir,
            hosts=args.mh_hosts,
            devs_per_host=args.mh_devs_per_host,
            trials=args.trials,
            epochs=args.epochs,
            kind=args.mh_kind,
            victim=args.mh_victim,
            groups_mode=args.mh_groups,
            agree_timeout_s=args.mh_agree_timeout,
            # Wedge: the survivors' bounded end-of-sweep barrier must
            # trip (the asserted WedgedCollective exit) BEFORE the
            # supervisor's staleness verdict — so the lease deadline is
            # deliberately lazy for that kind.
            heartbeat_deadline_s=45.0 if args.mh_kind == "wedge" else 3.0,
        )
        ok = (
            report["all_trials_settled"]
            and report["goodput"] >= 0.8
            and report["worlds_formed"] >= 2
            and report["hosts_lost"] == [args.mh_victim]
            and (
                report["recovered_bit_identical"] in (True, None)
            )
            # membership telemetry: the shrink is a traced, typed story
            and report["membership"]["host_lost_traced"]
            and report["membership"]["world_shrunk_traced"]
            # the watchdog acceptance: a wedge must surface as a NAMED
            # WedgedCollective exit, never a silent hang/timeout
            and (
                args.mh_kind != "wedge"
                or report["wedged_collective_exits"] >= 1
            )
            # fleet observability gates (ISSUE 6, docs/OBSERVABILITY.md
            # "Fleet"): the merged timeline spans every host, every
            # fired fault appears in it, and the world transition has a
            # non-null restart-tax breakdown. faults_fired >= 1 keeps
            # the cross-check honest: all_faults_traced over an empty
            # (missing/unreadable) fired-log is vacuously true.
            and report["fleet"]["all_hosts_traced"]
            and report["fleet"]["faults_fired"] >= 1
            and report["fleet"]["all_faults_traced"]
            and report["fleet"]["restart_tax_nonnull"]
        )
        headline = {
            "metric": "chaos_mh_goodput_useful_over_executed_steps",
            "value": report["goodput"],
            "unit": "fraction",
            "vs_baseline": round(report["goodput"] / 0.8, 3),
            "kind": args.mh_kind,
            "hosts": f"{args.mh_hosts}->{report['hosts_final']}",
            "all_trials_settled": report["all_trials_settled"],
            "recovered_bit_identical": report["recovered_bit_identical"],
            "wedged_collective_exits": report["wedged_collective_exits"],
            "all_hosts_traced": report["fleet"]["all_hosts_traced"],
            "all_faults_traced": report["fleet"]["all_faults_traced"],
            "restart_tax_nonnull": report["fleet"]["restart_tax_nonnull"],
            "detail": report,
        }
        print(json.dumps(headline))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(headline, f, indent=2)
            os.replace(tmp, args.out)
            print(f"report written to {args.out}", file=sys.stderr)
        return 0 if ok else 1

    from multidisttorch_tpu.faults.harness import run_chaos_bench

    plan = None
    if args.plan is not None:
        from multidisttorch_tpu.faults.plan import FaultPlan

        with open(args.plan) as f:
            plan = FaultPlan.from_json(f.read())
        bad_ids = {
            s.trial_id for s in plan.specs
        } - set(range(args.trials))
        if bad_ids:
            parser.error(
                f"--plan targets trial ids {sorted(bad_ids)} outside this "
                f"sweep's 0..{args.trials - 1} (adjust --trials or the plan)"
            )

    report = run_chaos_bench(
        work_dir,
        trials=args.trials,
        epochs=args.epochs,
        seed=args.seed,
        include_preempt=not args.no_preempt,
        stacked=args.stacked,
        plan=plan,
        telemetry_dir=args.telemetry_dir,
    )

    tel = report.get("telemetry") or {}
    ok = (
        report["all_infra_faults_recovered"]
        and report["final_metrics_bit_identical"]
        # the goodput bar is the STANDARD schedule's acceptance; a
        # custom plan is report-only there (its author owns the bar)
        and (plan is not None or report["goodput"] >= 0.8)
        # the observability acceptance: every fired fault appears as a
        # tagged event in a monotonic, Perfetto-loadable trace
        and tel.get("all_faults_traced", False)
        and tel.get("trace_monotonic", False)
        # the device-books acceptance (ISSUE 4): the exported summary
        # carries per-trial MFU (or explicit null-with-reason) and
        # peak-memory fields
        and tel.get("device_books_in_summary", False)
    )
    headline = {
        "metric": "chaos_goodput_useful_over_executed_steps",
        "value": report["goodput"],
        "unit": "fraction",
        "vs_baseline": round(report["goodput"] / 0.8, 3),
        "all_infra_faults_recovered": report["all_infra_faults_recovered"],
        "final_metrics_bit_identical": report["final_metrics_bit_identical"],
        "restarts_after_preemption": report["restarts_after_preemption"],
        "telemetry_trace": tel.get("trace"),
        "all_faults_traced": tel.get("all_faults_traced"),
        "device_books_in_summary": tel.get("device_books_in_summary"),
        "anomalies_traced": tel.get("anomalies_traced"),
        "profiler_captures": tel.get("profiler_captures"),
        "detail": report,
    }
    print(json.dumps(headline))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(headline, f, indent=2)
        os.replace(tmp, args.out)
        print(f"report written to {args.out}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
