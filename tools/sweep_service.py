#!/usr/bin/env python
"""The sweep service daemon CLI (docs/SERVICE.md).

    python tools/sweep_service.py <service-dir> --slices 4 \
        --tenant-weight alice=2 --tenant-weight bob=1 --retry 2

Runs :class:`multidisttorch_tpu.service.runtime.SweepService` over the
given directory until stopped. All state is durable under the
directory, so a killed daemon (SIGKILL included) restarts with zero
lost submissions — ``kill -9; restart`` is the CI drill, not a
disaster.

Signals follow ``run_hpo``'s drain contract (docs/RESILIENCE.md): the
first SIGTERM/SIGINT drains — in-flight checkpoint writes land, live
attempts are recorded ``preempted``, submissions are journaled
``unplaced`` (they re-place on restart), books are written — and the
process exits ``cluster.PREEMPTION_EXIT_CODE`` (75). A second signal
kills immediately. Under ``tools/sweep_supervisor.py`` (launch with
``--hosts 1 -- python tools/sweep_service.py …``) that exit code means
"relaunch me": the supervisor re-forms the world and the daemon
resumes from its journal — the service's elastic-restart story. With
``MDT_HOST_SLOT`` set (the supervisor sets it) the daemon heartbeats a
membership lease so a wedged daemon is detected without collectives.

**Fabric mode** (docs/SERVICE.md "Service fabric"): ``--fabric`` runs
one :class:`~multidisttorch_tpu.service.fabric.FabricReplica` instead
of a bare single-controller daemon —

    python tools/sweep_service.py <service-dir> --fabric \\
        --replica 0 --n-shards 2 --slices 2

The replica claims orphaned tenant shards through epoch-fenced leases,
runs one fenced ``SweepService`` per owned shard, and adopts a dead
peer's shard (journal replay + checkpoint re-homing) when its lease
goes stale. ``--replica`` defaults to ``MDT_HOST_SLOT``, so N replicas
under the elastic supervisor (``sweep_supervisor.py --hosts N --
python tools/sweep_service.py <dir> --fabric --n-shards N …``) each
take a host slot. ``--fault-plan`` arms the seeded chaos machinery
(``daemon_lost`` SIGKILLs this replica on its dispatch clock — the
drillable failover of ``tools/chaos_run.py --fabric``).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _parse_kv(pairs, cast, what):
    out = {}
    for p in pairs or []:
        if "=" not in p:
            raise SystemExit(f"--{what} expects NAME=VALUE, got {p!r}")
        k, v = p.split("=", 1)
        out[k] = cast(v)
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="persistent multi-tenant sweep daemon"
    )
    parser.add_argument("service_dir")
    parser.add_argument(
        "--slices", type=int, default=None,
        help="carve the device world into this many unit slices "
        "(default: one slice per device)",
    )
    parser.add_argument("--max-lanes", type=int, default=4,
                        help="stacked co-pack width per submesh")
    parser.add_argument(
        "--tenant-weight", action="append", metavar="NAME=W",
        help="fair-share weight for a tenant (repeatable)",
    )
    parser.add_argument(
        "--tenant-quota", action="append", metavar="NAME=N",
        help="max pending submissions per tenant (repeatable)",
    )
    parser.add_argument("--max-total-pending", type=int, default=4096)
    parser.add_argument("--data-rows", type=int, default=512,
                        help="rows of the service's training dataset")
    parser.add_argument("--starvation", type=float, default=3.0,
                        help="seconds a blocked trial waits before "
                        "defragmentation is considered")
    parser.add_argument("--no-defrag", action="store_true")
    parser.add_argument("--retry", type=int, default=2,
                        help="infra retry budget per trial (0 disables)")
    parser.add_argument("--precompile", action="store_true",
                        help="warm admitted trials' executables on the "
                        "AOT farm before placement (docs/COMPILE.md)")
    parser.add_argument("--fabric", action="store_true",
                        help="run as a service-fabric replica (shard "
                        "leases, fenced ownership, orphan adoption — "
                        "docs/SERVICE.md)")
    parser.add_argument("--replica", type=int, default=None,
                        help="this replica's stable id (default: "
                        "MDT_HOST_SLOT, else 0)")
    parser.add_argument("--n-shards", type=int, default=2,
                        help="fabric shard count (every replica and "
                        "client must agree; first writer pins it)")
    parser.add_argument("--lease-deadline", type=float, default=3.0,
                        help="seconds without a lease renewal before a "
                        "shard counts orphaned and is adopted")
    parser.add_argument("--split-queue-depth", type=int, default=None,
                        help="fabric: split an owned shard whose "
                        "pending queue reaches this depth (default: "
                        "splits off — static topology)")
    parser.add_argument("--split-min-interval", type=float, default=2.0,
                        help="fabric: seconds between split attempts "
                        "by this replica")
    parser.add_argument("--steal-threshold", type=int, default=None,
                        help="fabric: steal queued work for an idle "
                        "owned shard from a peer shard whose backlog "
                        "reaches this depth (default: stealing off)")
    parser.add_argument("--steal-batch", type=int, default=2,
                        help="fabric: max submissions per steal grant")
    parser.add_argument("--fault-plan", default=None,
                        help="arm a FaultPlan JSON against this "
                        "replica's dispatch clock (daemon_lost etc.; "
                        "fired log under {service_dir}/fabric/)")
    parser.add_argument("--exit-when-drained", action="store_true",
                        help="exit once queue+spool+submeshes are idle "
                        "(CI/bench mode; default: keep serving)")
    parser.add_argument("--idle-grace", type=float, default=1.0)
    parser.add_argument("--max-wall", type=float, default=None)
    parser.add_argument("--verbose", action="store_true")
    args = parser.parse_args(argv)

    # Telemetry + membership BEFORE jax-heavy imports: the daemon's
    # observability must exist even if backend init wedges.
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.parallel import membership

    slot = os.environ.get("MDT_HOST_SLOT")
    if not telemetry.enabled():
        # One sink per replica process: two fabric replicas over the
        # same root used to open (and truncate) the SAME events.jsonl
        # and interleave destructively — the per-replica subdir keeps
        # each stream whole, and the trace/fleet discovery rule
        # (events*.jsonl at any depth under telemetry/) finds both.
        tel_dir = os.path.join(args.service_dir, "telemetry")
        rep = (
            args.replica
            if args.replica is not None
            else (int(slot) if slot is not None else None)
        )
        if args.fabric and rep is not None:
            tel_dir = os.path.join(tel_dir, f"replica-{int(rep)}")
        telemetry.configure(tel_dir)
    if slot is None and args.fabric and args.replica is not None:
        # A fabric replica always heartbeats: the console's replica
        # health and the supervisor's staleness verdict both read the
        # membership lease, launcher or not.
        slot = str(args.replica)
    if slot is not None:
        membership.start_heartbeat(
            args.service_dir,
            int(slot),
            world_epoch=int(os.environ.get("MDT_WORLD_EPOCH", "0") or 0),
        )

    from multidisttorch_tpu.hpo.supervision import (
        RetryPolicy,
        exit_code_for,
    )
    from multidisttorch_tpu.parallel.cluster import PREEMPTION_EXIT_CODE
    from multidisttorch_tpu.service.runtime import SweepService
    from multidisttorch_tpu.service.scheduler import TenantPolicy

    weights = _parse_kv(args.tenant_weight, float, "tenant-weight")
    quotas = _parse_kv(args.tenant_quota, int, "tenant-quota")
    policies = {
        name: TenantPolicy(
            weight=weights.get(name, 1.0),
            max_pending=quotas.get(name, 256),
        )
        for name in set(weights) | set(quotas)
    }
    svc_kwargs = dict(
        n_slices=args.slices,
        max_lanes=args.max_lanes,
        policies=policies,
        max_total_pending=args.max_total_pending,
        data_rows=args.data_rows,
        starvation_s=args.starvation,
        defrag_enabled=not args.no_defrag,
        retry=RetryPolicy(max_retries=args.retry) if args.retry else None,
        verbose=args.verbose,
        precompile=args.precompile,
    )
    if args.fabric:
        from multidisttorch_tpu.service.fabric import FabricReplica

        replica = (
            args.replica
            if args.replica is not None
            else int(os.environ.get("MDT_HOST_SLOT", "0") or 0)
        )
        injector = None
        if args.fault_plan:
            from multidisttorch_tpu.faults.inject import FaultInjector
            from multidisttorch_tpu.faults.plan import FaultPlan

            with open(args.fault_plan) as f:
                plan = FaultPlan.from_json(f.read())
            injector = FaultInjector(
                plan,
                host_slot=replica,
                fired_log=os.path.join(
                    args.service_dir, "fabric", f"fired-{replica}.jsonl"
                ),
            )
        svc = FabricReplica(
            args.service_dir,
            replica=replica,
            n_shards=args.n_shards,
            lease_deadline_s=args.lease_deadline,
            injector=injector,
            split_queue_depth=args.split_queue_depth,
            split_min_interval_s=args.split_min_interval,
            steal_threshold=args.steal_threshold,
            steal_batch=args.steal_batch,
            **svc_kwargs,
        )
    else:
        svc = SweepService(args.service_dir, **svc_kwargs)

    def on_signal(signum, frame):
        if svc._stop:  # second signal: the operator means it
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        svc.stop()

    for s in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(s, on_signal)
        except (ValueError, OSError):
            pass

    try:
        report = svc.serve(
            max_wall_s=args.max_wall,
            exit_when_drained=args.exit_when_drained,
            idle_grace_s=args.idle_grace,
        )
    except BaseException as e:  # noqa: BLE001 — exit-code contract
        membership.stop_heartbeat()
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise
        print(
            f"sweep service died: {type(e).__name__}: {e}",
            file=sys.stderr,
        )
        return exit_code_for(e)
    membership.stop_heartbeat()
    print(json.dumps(
        {k: report[k] for k in ("outcome", "wall_s")}
        | {"settled": len(report["settled"])}
    ))
    if report["outcome"] == "preempted":
        return PREEMPTION_EXIT_CODE
    return 0


if __name__ == "__main__":
    sys.exit(main())
