#!/usr/bin/env python
"""Submit trials to a running sweep service (docs/SERVICE.md).

    python tools/sweep_submit.py <service-dir> --tenant alice \
        --lr 1e-3 --epochs 3 --hidden-dim 400 [--count 4] [--wait]

The transport is the durable file spool (``service/queue.py``): a
submission is committed the moment this command prints its id — the
daemon (``tools/sweep_service.py``) picks it up on its next intake
scan, and a daemon that is down picks it up when it starts. ``--wait``
blocks until every submitted trial settles (or the deadline passes)
and exits non-zero if any failed.

No JAX import anywhere on this path: submitting must work from hosts
with no accelerator runtime at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.service.queue import SweepClient  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="submit trials to a sweep service directory"
    )
    parser.add_argument("service_dir")
    parser.add_argument("--tenant", default="default")
    parser.add_argument(
        "--priority", type=int, default=1,
        help="priority lane (0 served strictly before 1 before 2)",
    )
    parser.add_argument(
        "--size", type=int, default=1,
        help="submesh footprint in slices (contiguous; >1 = large-shape)",
    )
    parser.add_argument(
        "--deadline", type=float, default=None,
        help="deadline in seconds from submission: EDF-orders the "
        "trial inside your fair share and may checkpoint-drain "
        "preempt best-effort lanes within the anti-thrash budget "
        "(docs/SERVICE.md \"Deadlines\"); hits/misses land in the "
        "books — an overdue trial is never killed",
    )
    parser.add_argument(
        "--count", type=int, default=1,
        help="submit N copies with seeds seed, seed+1, ...",
    )
    parser.add_argument(
        "--wait", action="store_true",
        help="block until every submission settles; exit 1 on failures",
    )
    parser.add_argument("--wait-timeout", type=float, default=600.0)
    parser.add_argument("--json", action="store_true",
                        help="machine-readable submission receipt")
    # TrialConfig knobs (hpo/driver.py defaults apply when omitted).
    parser.add_argument("--epochs", type=int, default=None)
    parser.add_argument("--batch-size", type=int, default=None)
    parser.add_argument("--lr", type=float, default=None)
    parser.add_argument("--beta", type=float, default=None)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--hidden-dim", type=int, default=None)
    parser.add_argument("--latent-dim", type=int, default=None)
    parser.add_argument("--fused-steps", type=int, default=None)
    parser.add_argument(
        "--dataset", default=None,
        help="per-submission dataset reference (docs/DATA.md): "
        "'synthetic-mnist?rows=512&seed=3', 'file:<path>.npz', or "
        "'cas:<sha256>' — resolved against the service's "
        "content-addressed cache at admission; omitted = the "
        "service's shared dataset",
    )
    args = parser.parse_args(argv)

    cfg = {}
    for field, value in (
        ("epochs", args.epochs),
        ("batch_size", args.batch_size),
        ("lr", args.lr),
        ("beta", args.beta),
        ("hidden_dim", args.hidden_dim),
        ("latent_dim", args.latent_dim),
        ("fused_steps", args.fused_steps),
        ("dataset", args.dataset),
    ):
        if value is not None:
            cfg[field] = value

    client = SweepClient(args.service_dir, tenant=args.tenant)
    ids = []
    traces = {}
    for k in range(args.count):
        sid = client.submit(
            {**cfg, "seed": args.seed + k},
            priority=args.priority,
            size=args.size,
            deadline_s=args.deadline,
        )
        ids.append(sid)
        # The trace id minted with the submission: the handle
        # `tools/sweep_trace.py` (and the Perfetto export) joins a
        # whole lifecycle on (docs/OBSERVABILITY.md "Tracing & SLOs").
        traces[sid] = client.last_submission.trace_id
    if args.json:
        print(
            json.dumps(
                {
                    "submitted": ids,
                    "tenant": args.tenant,
                    "traces": traces,
                }
            )
        )
    else:
        for s in ids:
            print(f"{s}  trace={traces[s]}")
    if not args.wait:
        return 0
    final = client.wait(ids, timeout_s=args.wait_timeout)
    bad = {
        s: r
        for s, r in final.items()
        if r.get("status") not in ("completed", "diverged")
    }
    if args.json:
        print(json.dumps({"final": final}, default=str))
    else:
        for s, r in sorted(final.items()):
            print(f"{s}: {r.get('state')}/{r.get('status')}")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
