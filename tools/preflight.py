#!/usr/bin/env python
"""Backend preflight CLI: probe an accelerator, print ONE classified
verdict, never hang.

    python tools/preflight.py                      # default backend
    python tools/preflight.py --platform cpu       # explicit platform
    python tools/preflight.py --platform tpu --json

Runs the banked BENCH_r04/r05 TPU triage as a structured probe
(``multidisttorch_tpu/utils/preflight.py``): bounded out-of-process
init (on failure: /proc leaked-plugin scan + one delayed retry),
device enumeration, a tiny compile+execute canary, and
``memory_stats()`` — folded to one verdict
from the closed taxonomy in docs/OBSERVABILITY.md ("Fleet" section).
Every stage has a hard timeout and the probing happens in
subprocesses, so a wedged backend yields ``wedged_*`` (diagnosed) and
an absent one yields ``backend_absent`` (fast) — this tool's exit is
ALWAYS bounded.

Exit code: 0 when the verdict is usable (``healthy`` /
``transient_recovered``), 3 otherwise. With ``--telemetry-dir`` the
probe additionally streams ``preflight_*`` events to a JSONL sink
(the same events the elastic supervisor emits when it preflights a
world — see tools/sweep_supervisor.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# Allow running straight from a checkout (tools/ is not a package).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.utils import preflight  # noqa: E402


def render(report: dict) -> str:
    lines = [
        f"preflight  platform={report['platform_requested']}  "
        f"verdict={report['verdict']}  usable={report['usable']}  "
        f"({report['elapsed_s']:.1f}s)",
        f"  reason: {report['verdict_reason']}",
    ]
    for st in report["stages"]:
        ok = "ok" if st.get("ok") else "FAIL"
        extra = ""
        if st["stage"] == "plugin_scan":
            extra = (
                f" holders={st.get('holders')} "
                f"plugin_procs={st.get('plugin_processes')} "
                f"listeners={st.get('loopback_listeners')}"
            )
        elif st["stage"] == "enumerate":
            extra = (
                f" {st.get('n_devices')}x {st.get('device_kind')} "
                f"({st.get('platform')})"
            )
        elif st["stage"] == "canary" and st.get("ok"):
            extra = f" value={st.get('canary_value')}"
        elif st["stage"] == "compile_cache":
            extra = (
                f" verdict={st.get('cache_verdict')} "
                f"scanned={st.get('scanned')} "
                f"rejected={st.get('rejected')}"
            )
        el = st.get("elapsed_s")
        lines.append(
            f"  {st['stage']:<12} {ok:<4}"
            + (f" {el:.1f}s" if el is not None else "")
            + extra
        )
    if report.get("memory_stats"):
        ms = report["memory_stats"]
        lines.append(
            "  memory_stats: "
            + ", ".join(f"{k}={v}" for k, v in sorted(ms.items())[:4])
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="classified, bounded backend preflight probe "
        "(docs/OBSERVABILITY.md \"Fleet\")"
    )
    parser.add_argument(
        "--platform", default=None,
        help="probe this JAX platform (subprocess JAX_PLATFORMS); "
        "default: the default backend, axon TPU plugin included",
    )
    parser.add_argument("--json", action="store_true",
                        help="print the full report as one JSON object")
    parser.add_argument("--out", default=None,
                        help="also write the JSON report here")
    parser.add_argument("--init-timeout", type=float,
                        default=preflight.PREFLIGHT_TIMEOUT_S)
    parser.add_argument("--retry-timeout", type=float,
                        default=preflight.RETRY_TIMEOUT_S)
    parser.add_argument("--retry-delay", type=float,
                        default=preflight.RETRY_DELAY_S)
    parser.add_argument("--canary-timeout", type=float,
                        default=preflight.CANARY_TIMEOUT_S)
    parser.add_argument("--no-canary", action="store_true",
                        help="skip the compile+execute canary stage")
    parser.add_argument("--no-scan", action="store_true",
                        help="skip the /proc leaked-plugin scan")
    parser.add_argument(
        "--compile-cache", action="store_true",
        help="also probe the quarantined persistent executable cache: "
        "CRC sidecar scan + one subprocess canary protocol run "
        "(docs/COMPILE.md); the cache verdict rides the report, "
        "orthogonal to backend usability",
    )
    parser.add_argument(
        "--cache-dir", default=None,
        help="cache directory for --compile-cache "
        "(default: the shared utils/compile_cache.py resolution)",
    )
    parser.add_argument(
        "--telemetry-dir", default=None,
        help="stream preflight_* events to {dir}/events.jsonl",
    )
    args = parser.parse_args(argv)

    if args.telemetry_dir:
        from multidisttorch_tpu import telemetry

        telemetry.configure(args.telemetry_dir)
    report = preflight.run_preflight(
        args.platform,
        init_timeout_s=int(args.init_timeout),
        retry_timeout_s=int(args.retry_timeout),
        retry_delay_s=int(args.retry_delay),
        canary=not args.no_canary,
        canary_timeout_s=int(args.canary_timeout),
        scan=not args.no_scan,
        compile_cache=args.compile_cache,
        compile_cache_dir=args.cache_dir,
    )
    if args.telemetry_dir:
        from multidisttorch_tpu import telemetry

        telemetry.disable()
    if args.json:
        print(json.dumps(report, default=str))
    else:
        print(render(report))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2, default=str)
        os.replace(tmp, args.out)
    return 0 if report["usable"] else 3


if __name__ == "__main__":
    sys.exit(main())
