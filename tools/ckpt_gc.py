#!/usr/bin/env python
"""Chunk-store GC: refcount reconciliation + orphan sweep over v2
checkpoint directories (docs/RESILIENCE.md "Checkpoint format v2").

    python tools/ckpt_gc.py <run-or-service-dir>            # sweep all
    python tools/ckpt_gc.py <dir> --dry-run                 # report only
    python tools/ckpt_gc.py <dir> --grace 60 --json

Walks every ``chunks/`` store under the given tree (one per trial
directory; pipelined stage manifests share their trial's store),
rebuilds each store's ``refs.json`` from the manifests that actually
exist — a save crashed between its chunk writes and its manifest
replace leaks counts, never corrupts — and unlinks chunks no live
manifest references. ``--grace`` (seconds, default 300) protects an
IN-FLIGHT save on a live directory: its chunks land before its
manifest, so anything younger than the grace is kept. Safe to run
against a live service; destructive only to unreferenced chunks.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.train import ckpt_store  # noqa: E402


def find_ckpt_dirs(root: str) -> list[str]:
    """Every directory under ``root`` holding a ``chunks/`` store."""
    out = []
    for dirpath, dirnames, _ in os.walk(root):
        if ckpt_store.CHUNKS_DIRNAME in dirnames:
            out.append(dirpath)
        # Never descend INTO a chunk store (thousands of fanout dirs).
        dirnames[:] = [
            d for d in dirnames if d != ckpt_store.CHUNKS_DIRNAME
        ]
    return sorted(out)


def sweep_tree(
    root: str, *, grace_s: float = 300.0, dry_run: bool = False
) -> dict:
    reports = []
    totals = {
        "dirs": 0,
        "orphans_removed": 0,
        "orphan_bytes_freed": 0,
        "leaked_refs_reconciled": 0,
        "kept_in_grace": 0,
    }
    for d in find_ckpt_dirs(root):
        if dry_run:
            store = ckpt_store.ChunkStore(
                os.path.join(d, ckpt_store.CHUNKS_DIRNAME)
            )
            live: set = set()
            for p in ckpt_store.live_manifest_files(d):
                m = ckpt_store.read_manifest_file(p)
                if m is not None:
                    live |= ckpt_store.manifest_digests(m)
            on_disk = store.all_chunks()
            rep = {
                "dir": d,
                "chunks_on_disk": len(on_disk),
                "live_chunks": len(live),
                "orphans_removed": 0,
                "orphans_found": len(set(on_disk) - live),
                "orphan_bytes_freed": 0,
                "kept_in_grace": 0,
                "leaked_refs_reconciled": 0,
                "dry_run": True,
            }
        else:
            rep = ckpt_store.sweep_ckpt_dir(d, grace_s=grace_s)
            if rep is None:
                continue
        reports.append(rep)
        totals["dirs"] += 1
        for k in (
            "orphans_removed",
            "orphan_bytes_freed",
            "leaked_refs_reconciled",
            "kept_in_grace",
        ):
            totals[k] += rep.get(k, 0)
    return {"root": root, "totals": totals, "reports": reports}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="v2 checkpoint chunk-store GC "
        "(docs/RESILIENCE.md)"
    )
    parser.add_argument("root", help="run/service/trial directory")
    parser.add_argument(
        "--grace",
        type=float,
        default=300.0,
        help="keep unreferenced chunks younger than this many seconds "
        "(in-flight save protection; default 300)",
    )
    parser.add_argument(
        "--dry-run", action="store_true",
        help="report orphans without removing anything",
    )
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    out = sweep_tree(args.root, grace_s=args.grace, dry_run=args.dry_run)
    if args.json:
        print(json.dumps(out, indent=1))
    else:
        t = out["totals"]
        print(
            f"ckpt-gc {args.root}: {t['dirs']} chunk stores, "
            f"{t['orphans_removed']} orphan chunks removed "
            f"({t['orphan_bytes_freed']} bytes), "
            f"{t['leaked_refs_reconciled']} leaked refs reconciled, "
            f"{t['kept_in_grace']} kept in grace"
            + ("  [dry run]" if args.dry_run else "")
        )
        for rep in out["reports"]:
            extra = (
                f"  orphans_found {rep['orphans_found']}"
                if rep.get("dry_run")
                else f"  removed {rep['orphans_removed']}"
            )
            print(
                f"  {rep['dir']}: {rep['chunks_on_disk']} chunks, "
                f"{rep['live_chunks']} live, {rep['manifests'] if 'manifests' in rep else '?'} "
                f"manifests{extra}"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
