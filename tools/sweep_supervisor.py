#!/usr/bin/env python
"""Elastic multi-host sweep supervisor: launch N worker hosts, detect a
lost or wedged host, and re-form a SMALLER world that finishes the
sweep against the ledger.

    python tools/sweep_supervisor.py --hosts 3 --run-dir out/sweep \
        -- python tools/elastic_worker.py chaos_sweep out/sweep

The reference's multi-node contract is all-or-nothing: one dead rank
hangs every surviving barrier until an external timeout, and nothing
restarts anything (SURVEY.md §5). Production pod training treats
preemption and slice loss as routine: detect, re-initialize a smaller
world, resume from checkpoint. This supervisor is that loop, built on
three framework contracts (docs/RESILIENCE.md "Elastic multi-host"):

- **Membership** (``parallel/membership.py``): each worker heartbeats a
  lease file under ``{run_dir}/membership/``; a stale lease on a
  still-running process means "wedged" — detected WITHOUT collectives.
- **Exit codes**: a worker that dies because the *world* failed around
  it (preemption, ``WedgedCollective``, SIGTERM drain) exits
  ``cluster.PREEMPTION_EXIT_CODE`` (75) and is re-admitted; any other
  non-zero exit (or a stale lease) marks the host slot LOST.
- **Ledger-driven restart**: the relaunched world runs
  ``run_hpo(resume="scan")`` — settled trials are skipped, in-flight
  trials resume from their last valid (agreed) checkpoint. Between
  worlds the supervisor compacts the attempt history
  (``SweepLedger.compact``) so restart storms don't grow the ledger
  without bound.

The supervisor is also a **telemetry emitter** (docs/OBSERVABILITY.md
"Fleet"): it opens its own event stream under
``{run_dir}/telemetry/sup`` (unless the caller already configured
one), emits ``world_start``/``world_end`` around every world it forms,
and measures the **restart tax** of every shrink live — ``detect``
(the victim's last heartbeat → the supervisor's trigger), ``drain``
(teardown of the old world), ``relaunch`` (the replacement world
spawned) — as a ``restart_tax`` event the fleet merge completes with
the restore/first-useful-step phases it can only see in the workers'
streams. Before forming the first world it can run the backend
**preflight** (``utils/preflight.py``): a wedged backend then aborts
the launch with a classified verdict instead of wedging N workers. On
exit it folds every shard into the merged fleet artifacts
(``telemetry/fleet/``: merged events + Perfetto fleet trace +
``fleet_summary.json``).

**Service-fabric worlds** (docs/SERVICE.md "Service fabric"): launch N
fabric replicas as the worker command —

    python tools/sweep_supervisor.py --hosts 2 --run-dir out/svc \
        -- python tools/sweep_service.py out/svc --fabric --n-shards 2

each replica reads its ``MDT_HOST_SLOT`` as its replica id, heartbeats
the same membership lease the supervisor watches, and claims its home
shard through the fabric's epoch-fenced leases. The division of labor:
the FABRIC keeps serving through a replica death (a survivor adopts
the orphaned shard within the lease deadline — zero lost submissions,
no supervisor involvement), while the SUPERVISOR resurrects the dead
process into the next world so the fleet converges back to one shard
per replica. A relaunched replica whose shard was adopted meanwhile
simply finds no orphan to claim until the adopter drains or dies —
the fence makes the handoff race-free.

Worker environment per world (the framework's own OpenMPI-style
detection, ``parallel/cluster.py``): ``OMPI_COMM_WORLD_SIZE/RANK``
over the SURVIVING slots, a fresh ``MASTER_PORT`` per world (no
TIME_WAIT collisions), plus ``MDT_HOST_SLOT`` (the stable host
identity across worlds), ``MDT_WORLD_EPOCH``, and
``MDT_ELASTIC_RUN_DIR``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.parallel.cluster import (  # noqa: E402
    PREEMPTION_EXIT_CODE,
)
from multidisttorch_tpu.parallel.membership import (  # noqa: E402
    MembershipView,
    emit_event,
    record_world,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ElasticSupervisor:
    """One sweep's supervision loop: worlds of worker processes, shrunk
    on host loss until the sweep completes (all workers exit 0) or no
    hosts remain.

    ``worker_argv`` is launched once per host per world; everything
    world-specific arrives via environment. ``boot_grace_s`` suppresses
    staleness verdicts while a freshly-launched worker is still
    bringing up its runtime (no lease yet, or an old world's lease).
    """

    def __init__(
        self,
        worker_argv: list[str],
        run_dir: str,
        nhosts: int,
        *,
        devs_per_host: int = 2,
        heartbeat_deadline_s: float = 3.0,
        poll_s: float = 0.2,
        boot_grace_s: float = 60.0,
        drain_grace_s: float = 20.0,
        max_worlds: int = 8,
        world_timeout_s: float = 600.0,
        env_extra: Optional[dict] = None,
        compact_ledger: bool = True,
        log_dir: Optional[str] = None,
        preflight: bool = False,
        preflight_platform: Optional[str] = None,
        preflight_timeout_s: float = 60.0,
        export_fleet: bool = True,
    ):
        self.worker_argv = list(worker_argv)
        self.run_dir = run_dir
        self.nhosts = int(nhosts)
        self.devs_per_host = int(devs_per_host)
        self.heartbeat_deadline_s = float(heartbeat_deadline_s)
        self.poll_s = float(poll_s)
        self.boot_grace_s = float(boot_grace_s)
        self.drain_grace_s = float(drain_grace_s)
        self.max_worlds = int(max_worlds)
        self.world_timeout_s = float(world_timeout_s)
        self.env_extra = dict(env_extra or {})
        self.compact_ledger = compact_ledger
        self.log_dir = log_dir or os.path.join(run_dir, "logs")
        self.preflight = preflight
        self.preflight_platform = preflight_platform
        self.preflight_timeout_s = float(preflight_timeout_s)
        self.export_fleet = export_fleet
        self.view = MembershipView(run_dir)
        self.worlds: list[dict] = []  # report timeline
        self.restart_taxes: list[dict] = []  # live-measured phases
        self.preflight_report: Optional[dict] = None
        self.fleet: Optional[dict] = None  # exported artifact paths

    # -- world lifecycle ---------------------------------------------

    def _launch_world(self, epoch: int, slots: list[int]) -> dict:
        os.makedirs(self.log_dir, exist_ok=True)
        port = _free_port()
        procs: dict[int, dict] = {}
        for rank, slot in enumerate(sorted(slots)):
            env = dict(os.environ)
            env.pop("PALLAS_AXON_POOL_IPS", None)  # no TPU plugin below
            env.update(
                OMPI_COMM_WORLD_SIZE=str(len(slots)),
                OMPI_COMM_WORLD_RANK=str(rank),
                MASTER_ADDR="127.0.0.1",
                MASTER_PORT=str(port),
                MH_DEVS_PER_PROC=str(self.devs_per_host),
                MDT_HOST_SLOT=str(slot),
                MDT_WORLD_EPOCH=str(epoch),
                MDT_ELASTIC_RUN_DIR=self.run_dir,
                **self.env_extra,
            )
            log_path = os.path.join(self.log_dir, f"w{epoch}-h{slot}.log")
            log_f = open(log_path, "w")
            p = subprocess.Popen(
                self.worker_argv,
                env=env,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                text=True,
            )
            procs[slot] = {
                "proc": p,
                "log": log_path,
                "log_f": log_f,
                "started": time.time(),
                "exit": None,
                "killed_by_us": False,
            }
        return procs

    def _poll_exits(self, procs: dict) -> None:
        for info in procs.values():
            if info["exit"] is None:
                rc = info["proc"].poll()
                if rc is not None:
                    info["exit"] = rc
                    info["log_f"].close()

    def _stale_slots(self, procs: dict, epoch: int) -> list[int]:
        """Running workers whose lease went stale — the wedge verdict.

        Epoch-aware: once a worker has beaten in THIS world, staleness
        applies immediately (a wedged host stops mid-run, long after
        boot). A worker with no current-world lease yet is judged only
        after the boot grace — its newest record may be a dead world's
        tail, not evidence about this one."""
        now = time.time()
        leases = self.view.hosts()
        stale = []
        for slot, info in procs.items():
            if info["exit"] is not None:
                continue
            rec = leases.get(slot)
            current = (
                rec is not None
                and int(rec.get("world_epoch", -1)) == epoch
                and rec.get("status") != "left"
            )
            if current:
                if now - float(rec.get("ts", 0.0)) > self.heartbeat_deadline_s:
                    stale.append(slot)
            elif now - info["started"] > self.boot_grace_s:
                stale.append(slot)
        return sorted(stale)

    def _shutdown_world(self, procs: dict) -> None:
        """Drain-then-kill every still-running worker: SIGTERM triggers
        run_hpo's graceful drain (pending checkpoints land, ledger
        records the preemption), SIGKILL reaps whatever ignores it."""
        running = [i for i in procs.values() if i["exit"] is None]
        for info in running:
            info["killed_by_us"] = True
            try:
                info["proc"].send_signal(signal.SIGTERM)
            except OSError:
                pass
        deadline = time.time() + self.drain_grace_s
        while time.time() < deadline:
            self._poll_exits(procs)
            if all(i["exit"] is not None for i in procs.values()):
                break
            time.sleep(self.poll_s)
        for info in procs.values():
            if info["exit"] is None:
                try:
                    info["proc"].kill()
                except OSError:
                    pass
        for info in procs.values():
            if info["exit"] is None:
                try:
                    info["proc"].wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pass
                info["exit"] = info["proc"].poll()
                try:
                    info["log_f"].close()
                except OSError:
                    pass

    def _classify(self, procs: dict, stale: list[int]) -> dict:
        """Post-shutdown verdict per slot: LOST (hard exit or stale
        lease) vs SURVIVOR (exit 0, preemption exit, or killed by the
        supervisor's own drain)."""
        lost, survivors = [], []
        for slot, info in sorted(procs.items()):
            rc = info["exit"]
            if slot in stale:
                lost.append(slot)
            elif rc in (0, PREEMPTION_EXIT_CODE):
                survivors.append(slot)
            elif info["killed_by_us"]:
                survivors.append(slot)  # our own drain/kill, not a fault
            else:
                lost.append(slot)
        return {"lost": lost, "survivors": survivors}

    def _maybe_compact(self) -> Optional[dict]:
        if not self.compact_ledger:
            return None
        try:
            from multidisttorch_tpu.hpo.ledger import SweepLedger

            return SweepLedger(self.run_dir).compact()
        except Exception as e:  # noqa: BLE001 — compaction is best-effort
            return {"error": f"{type(e).__name__}: {e}"}

    def _run_preflight(self) -> None:
        """Probe the backend BEFORE forming a world: a wedged backend
        (ROADMAP item 5, the banked BENCH_r04/r05 shape) becomes a
        classified, skippable abort instead of N workers hanging into
        the boot grace. Emits ``preflight_*`` telemetry."""
        from multidisttorch_tpu.utils.preflight import run_preflight

        t = int(self.preflight_timeout_s)
        report = run_preflight(
            self.preflight_platform,
            init_timeout_s=t,
            retry_timeout_s=max(1, t // 2),
            canary_timeout_s=t,
        )
        self.preflight_report = report
        if not report["usable"]:
            raise RuntimeError(
                "supervisor: backend preflight verdict "
                f"{report['verdict']!r} ({report['verdict_reason']}) — "
                "refusing to form a world on a diagnosed-bad backend"
            )

    # -- the loop -----------------------------------------------------

    def run(self) -> dict:
        """Supervise the sweep. Opens a supervisor telemetry stream
        (``{run_dir}/telemetry/sup``) unless one is already configured,
        and ALWAYS lands the merged fleet artifacts on the way out —
        a failed sweep needs its fleet story more than a clean one."""
        from multidisttorch_tpu import telemetry as _telemetry

        own_telemetry = not _telemetry.enabled()
        if own_telemetry:
            _telemetry.configure(
                os.path.join(self.run_dir, "telemetry", "sup")
            )
        report = None
        try:
            report = self._run()
            return report
        finally:
            if self.export_fleet:
                try:
                    from multidisttorch_tpu.telemetry.fleet import (
                        export_fleet,
                    )

                    self.fleet = export_fleet(self.run_dir)["paths"]
                except Exception as e:  # noqa: BLE001 — best-effort
                    self.fleet = {"error": f"{type(e).__name__}: {e}"}
                if report is not None:
                    report["fleet"] = self.fleet
            if own_telemetry:
                _telemetry.disable()

    def _run(self) -> dict:
        if self.preflight:
            self._run_preflight()
        slots = list(range(self.nhosts))
        epoch = 0
        pending_tax: Optional[dict] = None
        while True:
            if epoch >= self.max_worlds:
                raise RuntimeError(
                    f"supervisor: {epoch} worlds formed without sweep "
                    "completion — the fault rate is outrunning recovery"
                )
            t0 = time.time()
            if epoch == 0:
                record_world(self.run_dir, epoch=0, hosts=slots)
            procs = self._launch_world(epoch, slots)
            emit_event("world_start", epoch=epoch, hosts=list(slots))
            if pending_tax is not None:
                # Relaunch phase closes the moment the replacement
                # world's processes exist; the restore / first-useful-
                # step phases live in the WORKERS' streams — the fleet
                # merge (telemetry/fleet.py) joins them onto this event.
                pending_tax["relaunch_s"] = round(
                    time.time() - pending_tax.pop("_teardown_done"), 3
                )
                pending_tax["world_epoch"] = epoch
                emit_event("restart_tax", **pending_tax)
                self.restart_taxes.append(pending_tax)
                pending_tax = None
            trigger = None
            while trigger is None:
                self._poll_exits(procs)
                exits = {s: i["exit"] for s, i in procs.items()}
                if all(rc == 0 for rc in exits.values()):
                    trigger = ("complete", [])
                    break
                hard = [
                    s
                    for s, rc in exits.items()
                    if rc not in (None, 0, PREEMPTION_EXIT_CODE)
                ]
                preempted = [
                    s for s, rc in exits.items()
                    if rc == PREEMPTION_EXIT_CODE
                ]
                stale = self._stale_slots(procs, epoch)
                if hard or stale:
                    trigger = ("host_lost", sorted(set(hard) | set(stale)))
                elif preempted and all(
                    rc is not None for rc in exits.values()
                ):
                    # Everyone is down, nobody is lost: the world tore
                    # itself down cleanly (a drain, or a wedge whose
                    # victim recovered) — relaunch at full strength.
                    trigger = ("preempted", [])
                elif time.time() - t0 > self.world_timeout_s:
                    trigger = ("world_timeout", list(exits))
                else:
                    time.sleep(self.poll_s)
            kind, lost_now = trigger
            emit_event(
                "world_end",
                epoch=epoch,
                outcome=kind,
                exits={
                    str(s): i["exit"] for s, i in sorted(procs.items())
                },
                wall_s=round(time.time() - t0, 3),
            )
            if kind == "complete":
                self.worlds.append(
                    {
                        "epoch": epoch,
                        "hosts": slots,
                        "outcome": "complete",
                        "exits": {
                            s: i["exit"] for s, i in sorted(procs.items())
                        },
                        "logs": {
                            s: i["log"] for s, i in sorted(procs.items())
                        },
                        "wall_s": round(time.time() - t0, 3),
                    }
                )
                return self._report(success=True)
            if kind == "world_timeout":
                self._shutdown_world(procs)
                self.worlds.append(
                    {
                        "epoch": epoch,
                        "hosts": slots,
                        "outcome": "world_timeout",
                        "exits": {
                            s: i["exit"] for s, i in sorted(procs.items())
                        },
                    }
                )
                raise RuntimeError(
                    f"supervisor: world {epoch} exceeded "
                    f"{self.world_timeout_s:g}s without completing or "
                    "failing — a sync escaped its watchdog"
                )
            # host_lost or preempted: tear down, classify, re-form.
            # Restart-tax detect phase: the gap between the last
            # heartbeat any lost host managed and THIS trigger moment —
            # how long the fault existed before the supervisor saw it.
            trigger_ts = time.time()
            leases = self.view.hosts()
            victim_beats = [
                float(leases[s].get("ts", 0.0))
                for s in lost_now
                if s in leases
            ]
            detect_s = (
                round(trigger_ts - max(victim_beats), 3)
                if victim_beats
                else 0.0
            )
            stale = self._stale_slots(procs, epoch)
            drain_t0 = time.time()
            self._shutdown_world(procs)
            pending_tax = {
                "trigger": kind,
                "lost": sorted(lost_now),
                "detect_s": detect_s,
                "drain_s": round(time.time() - drain_t0, 3),
                "_teardown_done": time.time(),
            }
            verdict = self._classify(procs, sorted(set(lost_now) | set(stale)))
            for slot in verdict["lost"]:
                emit_event(
                    "host_lost",
                    slot=slot,
                    world_epoch=epoch,
                    exit=procs[slot]["exit"],
                    stale=slot in stale,
                )
            self.worlds.append(
                {
                    "epoch": epoch,
                    "hosts": slots,
                    "outcome": kind,
                    "lost": verdict["lost"],
                    "exits": {
                        s: i["exit"] for s, i in sorted(procs.items())
                    },
                    "logs": {s: i["log"] for s, i in sorted(procs.items())},
                    "wall_s": round(time.time() - t0, 3),
                }
            )
            slots = [s for s in slots if s not in verdict["lost"]]
            if not slots:
                raise RuntimeError(
                    "supervisor: every host slot lost; nothing left to "
                    "re-form a world from"
                )
            compact_stats = self._maybe_compact()
            record_world(
                self.run_dir,
                epoch=epoch + 1,
                hosts=slots,
                lost=verdict["lost"],
                reason=kind,
            )
            if compact_stats is not None:
                self.worlds[-1]["ledger_compaction"] = compact_stats
            epoch += 1

    def _report(self, *, success: bool) -> dict:
        all_lost = sorted(
            {s for w in self.worlds for s in w.get("lost", [])}
        )
        return {
            "success": success,
            "worlds": self.worlds,
            "worlds_formed": len(self.worlds),
            "hosts_initial": self.nhosts,
            "hosts_final": len(self.worlds[-1]["hosts"]),
            "hosts_lost": all_lost,
            "restart_tax": self.restart_taxes,
            "preflight": self.preflight_report,
            "run_dir": self.run_dir,
            "log_dir": self.log_dir,
        }


def main() -> int:
    parser = argparse.ArgumentParser(
        description="elastic multi-host sweep supervisor "
        "(docs/RESILIENCE.md); worker argv follows `--`"
    )
    parser.add_argument("--hosts", type=int, required=True)
    parser.add_argument("--run-dir", required=True)
    parser.add_argument("--devs-per-host", type=int, default=2)
    parser.add_argument("--heartbeat-deadline", type=float, default=3.0)
    parser.add_argument("--max-worlds", type=int, default=8)
    parser.add_argument("--world-timeout", type=float, default=600.0)
    parser.add_argument(
        "--no-compact", action="store_true",
        help="skip ledger compaction between worlds",
    )
    parser.add_argument(
        "--preflight", action="store_true",
        help="run the classified backend preflight (tools/preflight.py "
        "taxonomy) before forming the first world; a non-usable "
        "verdict aborts the launch instead of wedging N workers",
    )
    parser.add_argument(
        "--preflight-platform", default=None,
        help="platform the preflight probes (default: default backend)",
    )
    parser.add_argument(
        "--preflight-timeout", type=float, default=60.0,
        help="per-stage preflight deadline in seconds",
    )
    parser.add_argument(
        "--no-fleet", action="store_true",
        help="skip merging the fleet artifacts "
        "(telemetry/fleet/) on exit",
    )
    parser.add_argument("worker", nargs=argparse.REMAINDER,
                        help="worker argv (prefix with --)")
    args = parser.parse_args()
    worker = args.worker
    if worker and worker[0] == "--":
        worker = worker[1:]
    if not worker:
        parser.error("worker argv required after --")
    sup = ElasticSupervisor(
        worker,
        args.run_dir,
        args.hosts,
        devs_per_host=args.devs_per_host,
        heartbeat_deadline_s=args.heartbeat_deadline,
        max_worlds=args.max_worlds,
        world_timeout_s=args.world_timeout,
        compact_ledger=not args.no_compact,
        preflight=args.preflight,
        preflight_platform=args.preflight_platform,
        preflight_timeout_s=args.preflight_timeout,
        export_fleet=not args.no_fleet,
    )
    report = sup.run()
    print(json.dumps(report, indent=2))
    return 0 if report["success"] else 1


if __name__ == "__main__":
    sys.exit(main())
