#!/usr/bin/env python
"""Human-readable dump of a sweep ledger (``hpo/ledger.py``).

    python tools/ledger_view.py <out-dir-or-sweep_ledger.jsonl>

Shows, per config hash: the trial id, the full attempt history
(attempt number, status, error, executed steps), and whether the
config is SETTLED (completed/diverged under that exact config — a
restarted ``run_hpo(resume=True)`` will skip it) or IN-FLIGHT (an
``attempt_start`` with no matching end: the driver died mid-attempt).

Formatting is shared with ``tools/sweep_top.py`` via
``telemetry.console`` so the two tools read as one family.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.telemetry.console import (  # noqa: E402
    fmt_duration,
    fmt_table,
    fmt_ts,
    status_glyph,
)

LEDGER_NAME = "sweep_ledger.jsonl"


def resolve_ledger_path(path: str) -> str:
    if os.path.isdir(path):
        return os.path.join(path, LEDGER_NAME)
    return path


def load_ledger(path: str) -> list[dict]:
    # Torn-tail-tolerant JSONL read — same contract as SweepLedger.load
    # but importable without jax (the ledger module pulls no heavy deps
    # either; reuse it).
    from multidisttorch_tpu.hpo.ledger import SweepLedger

    led = SweepLedger(os.path.dirname(path) or ".", enabled=True)
    led.path = path
    return led.load()


def fold(events: list[dict]) -> dict[str, dict]:
    """config_hash -> {trial_id, attempts: [...], settled, in_flight}."""
    out: dict[str, dict] = {}
    for ev in events:
        h = ev.get("config_hash")
        if not h or ev.get("event") not in ("attempt_start", "attempt_end"):
            continue  # compaction summaries etc. carry no attempt row
        rec = out.setdefault(
            h, {"trial_id": ev.get("trial_id"), "attempts": {}}
        )
        a = int(ev.get("attempt", 0))
        att = rec["attempts"].setdefault(
            a, {"attempt": a, "status": "in_flight", "error": "",
                "steps": None, "ts": ev.get("ts")}
        )
        if ev.get("event") == "attempt_end":
            att["status"] = ev.get("status", "?")
            att["error"] = ev.get("error", "") or ""
            att["ts"] = ev.get("ts")
            s = ev.get("summary") or {}
            steps = s.get("steps", s.get("steps_at_failure"))
            if steps is not None:
                att["steps"] = int(steps)
    for rec in out.values():
        atts = [rec["attempts"][k] for k in sorted(rec["attempts"])]
        rec["attempts"] = atts
        last = atts[-1] if atts else None
        rec["settled"] = bool(
            last and last["status"] in ("completed", "diverged")
        )
        rec["in_flight"] = bool(last and last["status"] == "in_flight")
    return out


def render(folded: dict[str, dict], path: str) -> str:
    lines = [f"sweep ledger  {path}", ""]
    settled = sum(1 for r in folded.values() if r["settled"])
    in_flight = sum(1 for r in folded.values() if r["in_flight"])
    lines.append(
        f"configs {len(folded)}  settled {settled}  in-flight {in_flight}"
        f"  other {len(folded) - settled - in_flight}"
    )
    lines.append("")
    rows = []
    for h, rec in sorted(
        folded.items(), key=lambda kv: (kv[1].get("trial_id") or 0, kv[0])
    ):
        history = " -> ".join(
            f"#{a['attempt']}:{status_glyph(a['status'])}"
            for a in rec["attempts"]
        )
        last = rec["attempts"][-1] if rec["attempts"] else {}
        rows.append(
            [
                rec.get("trial_id", "?"),
                h[:10],
                "SETTLED" if rec["settled"]
                else ("IN-FLIGHT" if rec["in_flight"] else "open"),
                len(rec["attempts"]),
                history,
                last.get("steps") if last.get("steps") is not None else "-",
                fmt_ts(last.get("ts")),
                (last.get("error") or "")[:48],
            ]
        )
    lines.append(
        fmt_table(
            rows,
            ["trial", "config", "state", "att", "history", "steps",
             "last", "error"],
        )
    )
    return "\n".join(lines)


def render_queue(folded: dict[str, dict], path: str) -> str:
    """Service-queue panel: every submission's lifecycle state with
    tenant, age, and shape bucket (docs/SERVICE.md)."""
    import time

    from multidisttorch_tpu.service.queue import QueueStats

    now = time.time()
    stats = QueueStats.of(folded)
    lines = [f"service queue  {path}", ""]
    lines.append(
        "  ".join(
            f"{state} {n}"
            for state, n in sorted(stats.by_state.items())
        )
        or "empty"
    )
    lines.append("")
    rows = []
    order = {"placed": 0, "admitted": 1, "pending": 2, "settled": 3,
             "rejected": 4}
    for sid, rec in sorted(
        folded.items(),
        key=lambda kv: (
            order.get(kv[1]["state"], 9), kv[1].get("submit_ts") or 0.0
        ),
    ):
        age = (
            fmt_duration(now - rec["submit_ts"])
            if rec.get("submit_ts")
            else "-"
        )
        rows.append(
            [
                sid[:24],
                rec.get("tenant", "?"),
                rec.get("priority", "-"),
                rec["state"],
                rec.get("trial_id") if rec.get("trial_id") is not None
                else "-",
                rec.get("size", 1),
                (rec.get("bucket") or "-")[:24],
                age,
                (rec.get("status") or "")[:12],
                (rec.get("error") or "")[:32],
            ]
        )
    lines.append(
        fmt_table(
            rows,
            ["submission", "tenant", "pri", "state", "trial", "size",
             "bucket", "age", "status", "error"],
        )
    )
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="human-readable sweep-ledger dump "
        "(attempt history per config hash, settled vs in-flight)"
    )
    parser.add_argument(
        "path",
        help="sweep out-dir (containing sweep_ledger.jsonl) or the file",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="machine-readable snapshot of the same fold (per-config "
        "attempt history, settled/in-flight) instead of the rendered "
        "table — for CI and scripts",
    )
    parser.add_argument(
        "--compact", action="store_true",
        help="atomically rewrite the ledger to its minimal equivalent "
        "state first (SweepLedger.compact: latest attempt_start/_end "
        "per config hash + a summary record carrying the attempt and "
        "infra-failure counters) — restart storms grow the attempt "
        "history without bound; this caps it. Torn-tail safe; the "
        "restart folds (settled-skip, attempt numbering, retry "
        "budgets) are provably unchanged",
    )
    parser.add_argument(
        "--queue", action="store_true",
        help="render the sweep SERVICE's submission queue instead of "
        "the attempt ledger: pending/admitted/placed/settled "
        "submissions with tenant, age, and shape bucket "
        "(docs/SERVICE.md; reads {dir}/queue.jsonl)",
    )
    args = parser.parse_args(argv)
    if args.queue:
        from multidisttorch_tpu.service.queue import (
            fold_queue,
            load_queue,
            queue_path,
        )

        service_dir = (
            args.path if os.path.isdir(args.path)
            else os.path.dirname(args.path) or "."
        )
        qpath = queue_path(service_dir)
        folded = fold_queue(load_queue(service_dir))
        if args.json:
            import json

            print(json.dumps(
                {"path": qpath, "by_submission": folded}, default=str
            ))
            return 0
        if not folded:
            print(f"no decodable queue records at {qpath}")
            return 0 if os.path.exists(qpath) else 1
        print(render_queue(folded, qpath))
        return 0
    path = resolve_ledger_path(args.path)
    if not os.path.exists(path):
        print(f"no ledger at {path}", file=sys.stderr)
        return 1
    if args.compact:
        from multidisttorch_tpu.hpo.ledger import SweepLedger

        led = SweepLedger(os.path.dirname(path) or ".", enabled=True)
        led.path = path
        stats = led.compact()
        print(
            f"compacted {path}: {stats['lines_before']} -> "
            f"{stats['lines_after']} lines over {stats['hashes']} "
            "configs",
            file=sys.stderr,
        )
    events = load_ledger(path)
    folded = fold(events)
    if args.json:
        import json

        print(
            json.dumps(
                {
                    "path": path,
                    "configs": len(folded),
                    "settled": sum(
                        1 for r in folded.values() if r["settled"]
                    ),
                    "in_flight": sum(
                        1 for r in folded.values() if r["in_flight"]
                    ),
                    "by_config": folded,
                },
                default=str,
            )
        )
        return 0
    if not events:
        print(f"ledger at {path} holds no decodable events")
        return 0
    print(render(folded, path))
    return 0


if __name__ == "__main__":
    sys.exit(main())
