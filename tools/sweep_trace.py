#!/usr/bin/env python
"""Per-submission latency breakdown over a sweep service directory.

    python tools/sweep_trace.py <service-dir-or-fabric-root>            # list
    python tools/sweep_trace.py <dir> <submission-id>                   # one
    python tools/sweep_trace.py <dir> --worst                           # p99 offender
    python tools/sweep_trace.py <dir> --export out/                     # bank files
    python tools/sweep_trace.py <dir> <submission-id> --json

Reconstructs one contiguous span tree per submission — offline, from
the durable files alone (queue journal + sweep ledger, telemetry event
shards when present; docs/OBSERVABILITY.md "Tracing & SLOs") — and
renders where the time went: spool wait, admission, fair-share queue,
dataset prefetch, compile wait, per-attempt train, settle. Fabric
roots are walked shard by shard; failover submissions show their spans
tagged with both fence epochs. ``--worst`` jumps straight from the
books' p99 exemplar (queue-wait / placement histograms) to the trace
behind it. Open spans (a SIGKILLed daemon's in-flight work) print as
``open`` — never a fabricated end.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from multidisttorch_tpu.telemetry import trace as ttrace  # noqa: E402


def fmt_s(v) -> str:
    if v is None:
        return "open"
    if v < 0.001:
        return f"{v * 1e6:.0f}us"
    if v < 1.0:
        return f"{v * 1e3:.1f}ms"
    if v < 120.0:
        return f"{v:.2f}s"
    return f"{v / 60.0:.1f}m"


def render_breakdown(bd: dict) -> str:
    lines = [
        f"submission {bd['submission_id']}  trace {bd['trace_id']}",
        f"tenant {bd.get('tenant') or '?'}  state {bd['state']}"
        + (f"/{bd['status']}" if bd.get("status") else "")
        + f"  total {fmt_s(bd['total_s'])}"
        + (
            f"  fence epochs {bd['epochs']}"
            if len(bd.get("epochs") or []) > 1
            else ""
        ),
        "",
        f"{'phase':<24}{'total':>10}",
    ]
    total = bd.get("total_s")
    for phase, v in bd["phase_totals_s"].items():
        pct = f"  {100.0 * v / total:5.1f}%" if total else ""
        lines.append(f"{phase:<24}{fmt_s(v):>10}{pct}")
    lines.append("")
    lines.append(f"{'at':>10}  {'dur':>9}  span")
    for row in bd["spans"]:
        at = f"+{row['at_s']:.3f}s" if row["at_s"] is not None else "?"
        dur = (
            fmt_s(row["dur_s"])
            if not row["open"]
            else "OPEN"
        )
        if row["kind"] == "instant":
            dur = "·"
        tag_bits = []
        # mode/persist_in_flight: the checkpoint drain's snapshot vs
        # background-persist split (docs/RESILIENCE.md).
        for k in (
            "status", "epoch", "requeued", "unplaced_reason",
            "mode", "persist_in_flight",
        ):
            if row["tags"].get(k) not in (None, ""):
                tag_bits.append(f"{k}={row['tags'][k]}")
        tags = ("  [" + ", ".join(tag_bits) + "]") if tag_bits else ""
        lines.append(f"{at:>10}  {dur:>9}  {row['name']}{tags}")
    return "\n".join(lines)


def worst_offenders(root: str) -> list[tuple[str, str, dict]]:
    """(histogram, submission id, exemplar) rows from every shard's
    service books — the percentile→trace jump."""
    out = []
    for sdir in ttrace.service_dirs_of(root):
        try:
            with open(os.path.join(sdir, "service_books.json")) as f:
                books = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        for key in ("queue_wait", "placement_latency"):
            ex = (books.get(key) or {}).get("p99_exemplar")
            if ex and ex.get("id"):
                out.append((key, str(ex["id"]), ex))
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="per-submission trace/latency breakdown "
        "(docs/OBSERVABILITY.md)"
    )
    parser.add_argument("path", help="service dir or fabric root")
    parser.add_argument("submission", nargs="?", default=None)
    parser.add_argument(
        "--worst", action="store_true",
        help="render the books' p99 exemplar submissions (queue-wait "
        "and placement worst offenders)",
    )
    parser.add_argument(
        "--export", metavar="DIR", default=None,
        help="write submission_spans.json + the Perfetto trace",
    )
    parser.add_argument("--json", action="store_true")
    args = parser.parse_args(argv)

    if args.export is not None:
        out = ttrace.export_traces(args.path, args.export)
        print(json.dumps(out, indent=1, default=str))
        return 0 if out["completeness"]["complete"] else 1

    traces = ttrace.build_submission_traces(args.path)
    if not traces:
        print(f"no submissions found under {args.path}", file=sys.stderr)
        return 1

    targets: list[str] = []
    if args.worst:
        rows = worst_offenders(args.path)
        if not rows:
            print(
                "no p99 exemplars in the books (no service_books.json, "
                "or histograms empty)",
                file=sys.stderr,
            )
            return 1
        for key, sid, ex in rows:
            print(
                f"# {key} p99 worst offender: {sid} "
                f"({fmt_s(ex.get('value_s'))})"
            )
            if sid in traces and sid not in targets:
                targets.append(sid)
    elif args.submission is not None:
        if args.submission not in traces:
            # Accept a trace id too.
            hit = [
                sid
                for sid, tr in traces.items()
                if tr["trace_id"] == args.submission
            ]
            if not hit:
                print(
                    f"unknown submission/trace id {args.submission!r}",
                    file=sys.stderr,
                )
                return 1
            targets = hit[:1]
        else:
            targets = [args.submission]
    else:
        # Listing: one row per submission, slowest first.
        rows = []
        for sid, tr in traces.items():
            bd = ttrace.latency_breakdown(tr)
            rows.append((bd["total_s"] if bd["total_s"] else -1.0, bd))
        rows.sort(key=lambda r: -(r[0] if r[0] is not None else -1.0))
        if args.json:
            print(
                json.dumps(
                    [bd for _, bd in rows], indent=1, default=str
                )
            )
            return 0
        comp = ttrace.trace_completeness(traces)
        print(
            f"{len(traces)} submissions  settled "
            f"{comp['settled']}  complete "
            f"{comp['settled_complete']}/{comp['settled']}  orphans "
            f"{comp['orphan_spans']}  takeovers "
            f"{comp['epoch_takeovers']}"
        )
        print(f"{'total':>9}  {'state':<10} {'tenant':<10} submission")
        for _, bd in rows:
            print(
                f"{fmt_s(bd['total_s']):>9}  "
                f"{(bd['status'] or bd['state']):<10} "
                f"{(bd.get('tenant') or '?'):<10} "
                f"{bd['submission_id']}  [{bd['trace_id']}]"
            )
        return 0

    outs = [ttrace.latency_breakdown(traces[sid]) for sid in targets]
    if args.json:
        print(json.dumps(outs if len(outs) > 1 else outs[0],
                         indent=1, default=str))
    else:
        for bd in outs:
            print(render_breakdown(bd))
            print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
