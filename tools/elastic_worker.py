#!/usr/bin/env python
"""Elastic worker: one host process of a supervised multi-host sweep.

Launched (and relaunched, in shrinking worlds) by
``tools/sweep_supervisor.py``; also driven directly by the multihost
elastic tests. Environment contract (see the supervisor's docstring):
``OMPI_COMM_WORLD_SIZE/RANK`` + ``MASTER_ADDR/PORT`` (the framework's
own launcher detection), ``MH_DEVS_PER_PROC``, ``MDT_HOST_SLOT`` (the
stable host identity across worlds), ``MDT_WORLD_EPOCH``, and
``MDT_ELASTIC_RUN_DIR``.

The worker:

1. starts the sideband heartbeat (``parallel/membership.py``) — the
   supervisor's collective-free liveness signal;
2. arms the fault injector with this host's slot and a durable
   fired-log, so host-scoped faults (``host_lost``/``wedge``) stay
   one-shot across world restarts;
3. runs the chaos sweep with full supervision, ``resume="scan"`` on
   any world after the first (ledger skips settled trials; in-flight
   trials restore via the agreed scan-back), and submeshes re-carved
   over the CURRENT, possibly smaller, device world;
4. emits ``trial_migrated`` telemetry for trials whose submesh
   assignment changed vs the previous world;
5. dies by the exit-code contract: 0 = sweep complete here,
   ``cluster.PREEMPTION_EXIT_CODE`` = healthy host, lost world
   (preemption / WedgedCollective / drain), anything else = this host
   is suspect.
"""

import json
import os
import sys
import traceback

_DEVS_PER_PROC = int(os.environ.get("MH_DEVS_PER_PROC", "2"))
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_DEVS_PER_PROC}"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    run_dir = os.environ.get("MDT_ELASTIC_RUN_DIR") or sys.argv[2]
    mode = sys.argv[1] if len(sys.argv) > 1 else "chaos_sweep"
    slot = int(os.environ.get("MDT_HOST_SLOT", "0"))
    world_epoch = int(os.environ.get("MDT_WORLD_EPOCH", "0"))
    trials = int(os.environ.get("MDT_MH_TRIALS", "6"))
    epochs = int(os.environ.get("MDT_MH_EPOCHS", "3"))
    data_rows = int(os.environ.get("MDT_MH_DATA_ROWS", "128"))
    groups_mode = os.environ.get("MDT_MH_GROUPS", "per_host")

    import multidisttorch_tpu as mdt
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.faults.inject import FaultInjector
    from multidisttorch_tpu.faults.plan import FaultPlan
    from multidisttorch_tpu.hpo.driver import run_hpo
    from multidisttorch_tpu.hpo.supervision import (
        RetryPolicy,
        exit_code_for,
    )
    from multidisttorch_tpu.parallel import membership
    from multidisttorch_tpu.telemetry.events import get_bus

    nproc, pid = mdt.initialize_runtime()
    assert nproc == int(os.environ["OMPI_COMM_WORLD_SIZE"]), (
        nproc, os.environ["OMPI_COMM_WORLD_SIZE"],
    )
    # Sideband liveness: the lease file is keyed by the STABLE slot, so
    # a host keeps one identity across shrinking worlds.
    membership.start_heartbeat(
        run_dir,
        slot,
        interval_s=float(os.environ.get("MDT_HEARTBEAT_INTERVAL_S", "0.25")),
        world_epoch=world_epoch,
        world_size=nproc,
    )
    # Per-process telemetry sink under the shared run dir (PR 3's
    # multi-controller naming), one subdir per WORLD: ranks renumber
    # across worlds and the sink truncates on open, so world k+1's
    # rank 0 must not clobber world k's stream. The fleet merge
    # (telemetry/fleet.py) folds the union of every world's files —
    # the explicit host/world identity here is what lets it attribute
    # this shard's lines after this process is gone (the env default
    # would resolve identically; explicit beats implicit for the one
    # tag the whole fleet story hangs off).
    telemetry.configure(
        os.path.join(run_dir, "telemetry", f"w{world_epoch}"),
        host=slot,
        world=world_epoch,
    )

    configs = None
    injector = None
    plan_path = os.path.join(run_dir, "fault_plan.json")
    if os.path.exists(plan_path):
        with open(plan_path) as f:
            plan = FaultPlan.from_json(f.read())
        injector = FaultInjector(
            plan,
            host_slot=slot,
            fired_log=os.path.join(
                membership.membership_dir(run_dir), f"fired-{slot}.jsonl"
            ),
        )

    if mode == "chaos_sweep":
        from multidisttorch_tpu.faults.harness import standard_configs

        configs = standard_configs(trials, epochs)
    else:
        raise SystemExit(f"unknown elastic worker mode {mode!r}")

    num_groups = (
        jax.process_count()
        if groups_mode == "per_host"
        else int(groups_mode)
    )

    train = synthetic_mnist(data_rows, seed=0)

    # Trial-migration telemetry: compare the previous world's
    # deterministic assignment with this one's.
    if world_epoch > 0:
        from multidisttorch_tpu.hpo.driver import (
            balanced_assignment,
            predicted_cost,
        )
        from multidisttorch_tpu.parallel.membership import world_history

        prev_worlds = [
            w
            for w in world_history(run_dir)
            if w.get("epoch") == world_epoch - 1
        ]
        if prev_worlds and len(prev_worlds[-1].get("hosts", [])) >= 1:
            costs = [predicted_cost(cfg, data_rows) for cfg in configs]
            old_n = (
                len(prev_worlds[-1]["hosts"])
                if groups_mode == "per_host"
                else num_groups
            )
            old = balanced_assignment(costs, max(1, old_n))
            new = balanced_assignment(costs, max(1, num_groups))
            bus = get_bus()
            if bus is not None:
                for cfg, g_old, g_new in zip(configs, old, new):
                    if g_old != g_new:
                        bus.emit(
                            "trial_migrated",
                            trial_id=cfg.trial_id,
                            from_group=g_old,
                            to_group=g_new,
                            world_epoch=world_epoch,
                        )

    try:
        results = run_hpo(
            configs,
            train,
            None,
            num_groups=num_groups,
            out_dir=run_dir,
            verbose=False,
            save_images=False,
            save_checkpoints=True,
            ckpt_keep_last=3,
            resilient=True,
            retry=RetryPolicy(max_retries=2, backoff_base_s=0.01,
                              jitter=True, jitter_seed=0),
            fault_plan=injector,
            resume="scan" if world_epoch > 0 else False,
            ledger=True,
        )
        # End-of-sweep collection barrier (bounded: MDT_SYNC_TIMEOUT_S)
        # — the drill's wedge surface: a host stalled mid-sweep leaves
        # its peers here, and the watchdog converts the wait into a
        # named WedgedCollective instead of a hang.
        mdt.sync_hosts("elastic sweep end")
    except Exception as e:  # noqa: BLE001 — exit-code contract
        from multidisttorch_tpu.parallel.cluster import (
            PREEMPTION_EXIT_CODE,
        )

        code = exit_code_for(e)
        preempted = code == PREEMPTION_EXIT_CODE
        print(
            f"PREEMPTED {type(e).__name__}: {e}"
            if preempted
            else f"WORKER-ERROR {type(e).__name__}: {e}",
            flush=True,
        )
        if not preempted:
            traceback.print_exc()
        membership.stop_heartbeat()
        telemetry.disable()
        return code

    summary = {
        "pid": pid,
        "slot": slot,
        "world_epoch": world_epoch,
        "world_size": nproc,
        "trials": {
            r.trial_id: {
                "status": r.status,
                "steps": r.steps,
                "resumed_from_step": r.resumed_from_step,
                "final_train_loss": r.final_train_loss,
                "attempt": r.attempt,
                "group_id": r.group_id,
            }
            for r in results
        },
    }
    out_path = os.path.join(run_dir, f"results-h{slot}-w{world_epoch}.json")
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(summary, f, indent=2)
    os.replace(tmp, out_path)
    print("RESULT " + json.dumps(summary), flush=True)
    membership.stop_heartbeat()
    telemetry.disable()
    return 0


if __name__ == "__main__":
    sys.exit(main())
