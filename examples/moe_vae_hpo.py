"""Mixture-of-experts VAE HPO over device subgroups, with expert
parallelism INSIDE each trial.

Same scaffolding as ``examples/vae_hpo.py`` (the reference's trial
dispatch, ``/root/reference/vae-hpo.py:177-202``), composed two ways:
the flagship model swaps to :class:`models.moe_vae.MoEVAE` via
``model_builder``, and ``--model-parallel m`` carves each trial's
submesh 2-D so ``param_shardings_builder`` shards the experts over the
trial's model axis — trial-parallel x data-parallel x expert-parallel
from one driver call. Each trial sweeps the expert count.

Run (8 virtual CPU devices; 2 trials x (2 data x 2 model) devices):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/moe_vae_hpo.py --ngroups 2 --epochs 1 \
            --model-parallel 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import multidisttorch_tpu as mdt  # noqa: E402
from multidisttorch_tpu.data import load_mnist  # noqa: E402
from multidisttorch_tpu.hpo import TrialConfig, run_hpo  # noqa: E402
from multidisttorch_tpu.models import MoEVAE, moe_vae_ep_shardings  # noqa: E402


def main():
    parser = argparse.ArgumentParser(description="MoE-VAE HPO (TPU-native)")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--ngroups", type=int, default=2)
    parser.add_argument(
        "--experts-base", type=int, default=2,
        help="trial g uses experts-base * 2^g experts",
    )
    parser.add_argument(
        "--model-parallel", type=int, default=1,
        help="model-axis extent per trial submesh; >1 shards each "
        "trial's experts over it (expert parallelism)",
    )
    parser.add_argument("--synthetic-size", type=int, default=2048)
    parser.add_argument("--out-dir", default="results-moe")
    args = parser.parse_args()

    mdt.initialize_runtime()
    train_data = load_mnist(train=True, synthetic_size=args.synthetic_size)
    test_data = load_mnist(
        train=False, synthetic_size=max(args.batch_size, args.synthetic_size // 6)
    )

    experts = {g: args.experts_base * (2**g) for g in range(args.ngroups)}
    configs = [
        TrialConfig(
            trial_id=g, epochs=args.epochs, batch_size=args.batch_size,
            seed=g, fused_steps=4,
        )
        for g in range(args.ngroups)
    ]

    results = run_hpo(
        configs,
        train_data,
        test_data,
        out_dir=args.out_dir,
        save_images=False,
        model_builder=lambda cfg: MoEVAE(
            hidden_dim=cfg.hidden_dim,
            latent_dim=cfg.latent_dim,
            num_experts=experts[cfg.trial_id],
        ),
        model_parallel=args.model_parallel,
        param_shardings_builder=(
            moe_vae_ep_shardings if args.model_parallel > 1 else None
        ),
    )
    for r in results:
        print(
            f"trial {r.trial_id} ({experts[r.trial_id]} experts): "
            f"train loss {r.final_train_loss:.4f}, "
            f"test loss {r.final_test_loss:.4f}, wall {r.wall_s:.2f}s"
        )


if __name__ == "__main__":
    main()
