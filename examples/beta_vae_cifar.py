"""β-VAE on CIFAR-10, N concurrent trials sweeping β (BASELINE.md
config 3: "8 trials x 4-chip submesh, stress per-trial all-reduce").

Same subgroup scaffolding as vae_hpo.py — only the model (ConvVAE) and
the swept hyperparameter (β instead of epochs) change, via the driver's
``model_builder`` hook.

Run (8 virtual CPU devices, 8 trials of 1 device each):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/beta_vae_cifar.py --ngroups 8 --epochs 1
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import multidisttorch_tpu as mdt  # noqa: E402
from multidisttorch_tpu.data import load_cifar10  # noqa: E402
from multidisttorch_tpu.hpo import TrialConfig, run_hpo  # noqa: E402
from multidisttorch_tpu.models import ConvVAE  # noqa: E402


def main():
    parser = argparse.ArgumentParser(description="beta-VAE CIFAR-10 HPO (TPU-native)")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--ngroups", type=int, default=8)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--latent-dim", type=int, default=64)
    parser.add_argument("--base-channels", type=int, default=32)
    parser.add_argument("--out-dir", default="results-beta-vae")
    parser.add_argument("--synthetic-size", type=int, default=None)
    args = parser.parse_args()

    mdt.initialize_runtime()
    train_data = load_cifar10(train=True, synthetic_size=args.synthetic_size)
    test_data = load_cifar10(
        train=False,
        synthetic_size=args.synthetic_size and max(args.batch_size, args.synthetic_size // 6),
    )

    # β sweep: one trial per subgroup, β doubling per trial.
    configs = [
        TrialConfig(
            trial_id=g,
            epochs=args.epochs,
            batch_size=args.batch_size,
            lr=args.lr,
            beta=float(2**g) / 2.0,  # 0.5, 1, 2, 4, ...
            seed=g,
        )
        for g in range(args.ngroups)
    ]

    results = run_hpo(
        configs,
        train_data,
        test_data,
        out_dir=args.out_dir,
        model_builder=lambda cfg: ConvVAE(
            latent_dim=args.latent_dim, base_channels=args.base_channels
        ),
    )
    for r in results:
        print(
            f"trial {r.trial_id} (beta={r.config.beta}): "
            f"test loss {r.final_test_loss:.2f}, wall {r.wall_s:.2f}s"
        )


if __name__ == "__main__":
    main()
