"""Minimal subgroup demo — TPU-native mirror of
/root/reference/example-subgroup.py.

The reference needs an 8-process mpirun/srun launch, a TCP rendezvous,
and world-collective ``dist.new_group`` handshakes; then ranks 0-3 and
4-7 each all-gather their ranks within their own subgroup. Here the same
program runs in ONE process: 8 devices (real chips, or virtual CPU
devices via ``XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu``), two metadata-only submeshes, two independent
gathers compiled onto disjoint device sets.

Expected output (parity with the reference's eyeball check):
    subgroup 0 gathered: [0, 1, 2, 3]
    subgroup 1 gathered: [4, 5, 6, 7]
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import multidisttorch_tpu as mdt  # noqa: E402


def run():
    ndev, _ = mdt.device_world()
    # The reference hard-asserts an 8-process world
    # (example-subgroup.py:39); we accept any even-divisible world but
    # keep the canonical demo at 8.
    assert ndev % 2 == 0, f"need an even device world, got {ndev}"

    groups = mdt.setup_groups(2)

    for g in groups:
        # Each member device contributes its global rank; the gather is
        # scoped to the submesh (example-subgroup.py:25-33).
        contrib = jnp.array(g.global_ranks, dtype=jnp.int32)
        gathered = mdt.group_all_gather(g, contrib)
        mdt.log0(
            f"subgroup {g.group_id} gathered: {list(map(int, gathered))}",
            trial=g,
        )


if __name__ == "__main__":
    nproc, pid = mdt.initialize_runtime()
    print(f"devices: {len(jax.devices())}, processes: {nproc}")
    run()
