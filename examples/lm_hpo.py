"""Concurrent LM trials, each sequence-parallel on its own submesh.

The composition the long-context mandate meets the reference's raison
d'être (concurrent per-subgroup trials, vae-hpo.py:122-174) in: carve
the job into N submeshes, and inside EACH one train a causal
TransformerLM with its context sharded T/k over that submesh's ring
(ring or ring-flash attention). Trials sweep the learning rate and run
under the same cooperative no-barrier dispatch as every other sweep.
``--model-parallel m`` adds a third axis: each trial's submesh becomes
(data x model), heads + q/k/v/proj + the MLP pair shard over the model
axis (2-D sequence x head attention) — trial x sequence x tensor
parallelism in one sweep. ``--moe E`` swaps in the MoE transformer
(E experts per block); with ``--model-parallel`` the experts claim the
model axis instead (trial x sequence x EXPERT parallelism).

Run (8 virtual CPU devices — two 4-device rings):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/lm_hpo.py --ngroups 2 --seq-len 128 --steps 40
    # two (2-ring x 2-TP) trials:
    ... python examples/lm_hpo.py --ngroups 2 --seq-len 64 --model-parallel 2
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import multidisttorch_tpu as mdt  # noqa: E402
from multidisttorch_tpu.models.transformer import TransformerLM  # noqa: E402
from multidisttorch_tpu.ops.ring_attention import make_ring_attention  # noqa: E402
from multidisttorch_tpu.parallel.mesh import DATA_AXIS  # noqa: E402
from multidisttorch_tpu.train.lm import (  # noqa: E402
    create_lm_state,
    lm_chunk_sharding,
    make_lm_eval_step,
    make_lm_multi_step,
    make_lm_train_step,
)


def _plan_mpmd_pipeline(args) -> None:
    """Plan (and print) a 2-stage MPMD pipelined LM trial over this
    device world: the balanced param split, the slice-vector placement
    (``SlicePool.alloc_multi`` — the service's all-or-nothing rule),
    the GPipe schedule model, and the ZeRO sharded-update
    optimizer-memory table (docs/PARALLEL.md). Exits before training —
    the executing MPMD runner covers the VAE family; the LM family
    plugs into the same generic stage contract when a deep split
    lands."""
    from multidisttorch_tpu.parallel.pipeline import (
        analytic_bubble_fraction,
    )
    from multidisttorch_tpu.service.scheduler import SlicePool

    world = len(jax.devices())
    groups = mdt.setup_groups(1)
    model = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, max_len=args.seq_len,
        attention=make_ring_attention(groups[0], causal=True),
    )
    abstract = jax.eval_shape(
        lambda rng: model.init(
            {"params": rng}, jnp.zeros((1, args.seq_len), jnp.int32)
        )["params"],
        jax.random.key(0),
    )
    leaves = jax.tree.leaves_with_path(abstract) if hasattr(
        jax.tree, "leaves_with_path"
    ) else [
        ((), leaf) for leaf in jax.tree.leaves(abstract)
    ]
    sizes = [int(np.prod(l.shape)) for _, l in leaves]
    total = sum(sizes)
    # Balanced 2-stage split by cumulative parameter count.
    acc, cut = 0, len(sizes)
    for i, s in enumerate(sizes):
        acc += s
        if acc >= total / 2:
            cut = i + 1
            break
    stage_params = [sum(sizes[:cut]), sum(sizes[cut:])]

    per_stage = max(1, world // 4)
    pool = SlicePool(world)
    starts = pool.alloc_multi([per_stage, per_stage])
    m = max(2, args.fused_steps)
    n_data = per_stage  # 1 device per slice in the example world
    opt_total = 2 * total * 4  # Adam mu+nu, f32
    opt_zero = opt_total // max(1, n_data)

    print(f"MPMD pipeline plan ({world}-device world, docs/PARALLEL.md)")
    print(
        f"  model: TransformerLM vocab={args.vocab} d_model="
        f"{args.d_model} layers={args.layers} -> {total:,} params"
    )
    print(
        f"  2-stage balanced split: stage0 {stage_params[0]:,} / "
        f"stage1 {stage_params[1]:,} params (cut after leaf {cut})"
    )
    print(
        f"  slice vector: sizes ({per_stage}, {per_stage}) -> "
        f"all-or-nothing starts {starts} "
        f"(SlicePool.alloc_multi, largest-first, rollback-on-failure)"
    )
    for mm in sorted({m, 4, 8, 16}):
        print(
            f"  schedule model: S=2 M={mm} -> bubble "
            f"{analytic_bubble_fraction(2, mm):.3f}  "
            "((S-1)/(S-1+M))"
        )
    print(
        f"  optimizer memory: replicated {opt_total:,} B/device -> "
        f"zero_update {opt_zero:,} B/device over data extent {n_data} "
        "(+ small replicated leaves)"
    )
    print(
        "  dry run: plan only — submit a pipeline_stages=2 VAE-family "
        "config to the sweep service, or run bench.py --pipeline, for "
        "an executing trial"
    )


def main():
    parser = argparse.ArgumentParser(
        description="trial-parallel x sequence-parallel LM sweep"
    )
    parser.add_argument("--ngroups", type=int, default=2)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--batch-size", type=int, default=2)
    parser.add_argument("--vocab", type=int, default=32)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--steps", type=int, default=40)
    parser.add_argument(
        "--fused-steps", type=int, default=1, metavar="K",
        help="optimizer steps per device dispatch (make_lm_multi_step's "
        "lax.scan). 1 = a dispatch per step; larger K amortizes the "
        "host enqueue that otherwise caps concurrent trials "
        "(docs/DISPATCH.md sizing rule). Must divide --steps.",
    )
    parser.add_argument(
        "--ring-flash", action="store_true",
        help="flash-kernel hops (ops/pallas_attention.py) inside each "
        "trial's K/V ring",
    )
    parser.add_argument(
        "--model-parallel", type=int, default=1,
        help="model-axis extent per trial: heads + q/k/v/proj + MLP "
        "pair shard over it (2-D sequence x head attention), composing "
        "trial x sequence x tensor parallelism in one sweep",
    )
    parser.add_argument(
        "--moe", type=int, default=0, metavar="E",
        help="use the MoE transformer with E experts per block; with "
        "--model-parallel the experts shard over the model axis "
        "(expert parallelism) while the context rides the ring",
    )
    parser.add_argument(
        "--pipeline", action="store_true",
        help="plan a cross-submesh MPMD pipelined LM trial "
        "(docs/PARALLEL.md): balanced 2-stage param split, the "
        "all-or-nothing slice-vector placement over this world, the "
        "GPipe schedule model, and the ZeRO optimizer-memory table — "
        "then exit (the executing MPMD runner covers the VAE family; "
        "see bench.py --pipeline)",
    )
    parser.add_argument(
        "--dry-run", action="store_true",
        help="with --pipeline: plan only (implied; kept explicit for "
        "the CI smoke)",
    )
    args = parser.parse_args()
    if args.dry_run and not args.pipeline:
        parser.error("--dry-run only applies with --pipeline")
    if args.fused_steps < 1 or args.steps % args.fused_steps:
        parser.error(
            f"--fused-steps {args.fused_steps} must be >= 1 and divide "
            f"--steps {args.steps}"
        )

    mdt.initialize_runtime()
    if args.pipeline:
        _plan_mpmd_pipeline(args)
        return
    if args.model_parallel > 1:
        if args.moe:
            if args.moe % args.model_parallel:
                parser.error(
                    f"--model-parallel {args.model_parallel} must "
                    f"divide the --moe {args.moe} experts (whole "
                    f"experts per model-axis device)"
                )
        elif 4 % args.model_parallel:
            # TransformerLM's default head count; ring head sharding
            # needs whole heads per model-axis device
            parser.error(
                f"--model-parallel {args.model_parallel} must divide "
                f"the model's 4 attention heads"
            )
    groups = mdt.setup_groups(args.ngroups, model_parallel=args.model_parallel)
    if args.seq_len % groups[0].data_size:
        parser.error(
            f"--seq-len must divide by {groups[0].data_size} "
            f"(ring devices per {args.ngroups}-group trial)"
        )
    if args.ring_flash:
        from multidisttorch_tpu.ops.pallas_attention import (
            make_ring_flash_attention as make_attn,
        )
    else:
        make_attn = make_ring_attention

    # lr sweep, one trial per submesh (the reference's epochs+group_id
    # knob generalized, SURVEY.md Q7)
    lrs = [1e-3 * (3.0**g) for g in range(args.ngroups)]

    # Shared periodic corpus (data/datasets.py synthetic_corpus):
    # perfectly learnable, so final perplexity ~1 is the correctness
    # signal. Each trial samples its own fixed windows (seeded by
    # group id), so trials see distinct data.
    from multidisttorch_tpu.data import synthetic_corpus

    corpus = synthetic_corpus(
        n=max(65536, 4 * args.seq_len), vocab_size=args.vocab
    )

    trials = []
    for g, lr in zip(groups, lrs):
        if not g.is_local_member:  # multi-host: skip remote submeshes
            continue
        if args.moe:
            from multidisttorch_tpu.models.transformer import MoETransformerLM

            # experts claim the model axis, so heads stay replicated
            model = MoETransformerLM(
                vocab_size=args.vocab, d_model=args.d_model,
                num_layers=args.layers, max_len=args.seq_len,
                num_experts=args.moe,
                attention=make_attn(g, causal=True, shard_heads=False),
            )
        else:
            model = TransformerLM(
                vocab_size=args.vocab, d_model=args.d_model,
                num_layers=args.layers, max_len=args.seq_len,
                attention=make_attn(g, causal=True),
            )
        tx = optax.adam(lr)
        psh = sh = None
        if args.model_parallel > 1:
            from multidisttorch_tpu.models.transformer import (
                moe_lm_ep_shardings,
                transformer_tp_shardings,
            )
            from multidisttorch_tpu.train.steps import state_shardings

            psh = (
                moe_lm_ep_shardings(g, model)
                if args.moe
                else transformer_tp_shardings(g, model)
            )
        rows = corpus.batch(
            np.random.default_rng(g.group_id), args.batch_size, args.seq_len
        )
        state = create_lm_state(
            g, model, tx, jax.random.key(g.group_id),
            example_len=args.seq_len, param_shardings=psh,
        )
        if psh is not None:
            sh = state_shardings(state)
        entry = {
            "trial": g,
            "lr": lr,
            "state": state,
            "eval": make_lm_eval_step(
                g, model, sequence_parallel=True, shardings=sh
            ),
            # g.device_put (not jax.device_put): on a process-
            # spanning submesh each owner feeds only its
            # addressable shards
            "tokens": g.device_put(
                rows,
                g.sharding(None, DATA_AXIS),
            ),
        }
        if args.fused_steps > 1:
            # Production dispatch shape: K steps per host round-trip
            # (the sizing rule from docs/DISPATCH.md). The demo trains
            # on one fixed batch, so the stacked chunk just repeats it.
            entry["step"] = make_lm_multi_step(
                g, model, tx, sequence_parallel=True, shardings=sh
            )
            entry["input"] = g.device_put(
                np.ascontiguousarray(
                    np.broadcast_to(rows, (args.fused_steps,) + rows.shape)
                ),
                lm_chunk_sharding(g, sequence_parallel=True),
            )
        else:
            entry["step"] = make_lm_train_step(
                g, model, tx, sequence_parallel=True, shardings=sh
            )
            entry["input"] = entry["tokens"]
        trials.append(entry)

    kind = "ring-flash" if args.ring_flash else "ring"
    per_dev = args.seq_len // groups[0].data_size
    tp = (
        f" x {args.model_parallel}-way "
        + ("expert" if args.moe else "tensor/head")
        + " parallel"
        if args.model_parallel > 1
        else ""
    )
    mdt.log0(
        f"{len(groups)} concurrent {kind} trials; {args.seq_len} tokens "
        f"({per_dev}/device inside each {groups[0].data_size}-device "
        f"ring){tp}"
    )

    # Cooperative round-robin: one dispatch per trial per cycle (K
    # fused steps each under --fused-steps), no barriers.
    t0 = time.time()
    K = args.fused_steps
    interval = 10
    for i in range(args.steps // K):
        for t in trials:
            t["state"], t["m"] = t["step"](t["state"], t["input"])
        # Log the loss of EVERY step a per-step loop would have logged
        # in this chunk, labeled with that step (the fused metrics come
        # back (K,), so each cadence point is indexable — same contract
        # as hpo/driver.py's fused logging, incl. K > interval).
        first = i * K
        j = -(-first // interval) * interval  # ceil to the cadence
        while j < first + K:
            for t in trials:
                loss = (
                    t["m"]["loss"] if K == 1 else t["m"]["loss"][j - first]
                )
                mdt.log0(
                    f"step {j:4d}  loss {float(loss):.4f}",
                    trial=t["trial"],
                )
            j += interval

    for t in trials:
        ev = t["eval"](t["state"], t["tokens"])
        mdt.log0(
            f"lr={t['lr']:.0e}: final loss {float(ev['loss']):.4f}, "
            f"perplexity {float(ev['perplexity']):.3f}, "
            f"wall {time.time() - t0:.1f}s",
            trial=t["trial"],
        )


if __name__ == "__main__":
    main()
