"""Long-context LM demo: one sequence sharded across the device group.

The sequence-parallel regime the framework treats as first-class: a
causal TransformerLM whose attention is exact ring attention
(``ops/ring_attention.py``) — each device holds ``T/N`` tokens of the
context, K/V blocks rotate around the submesh ring, and training runs
as ordinary jitted steps. On 8 virtual CPU devices a T=512 context
lives 64 tokens per "chip"; the same program on a TPU pod shards real
long contexts over ICI.

Run:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/lm_long_context.py --seq-len 512 --steps 60
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax  # noqa: E402
import optax  # noqa: E402

import multidisttorch_tpu as mdt  # noqa: E402
from multidisttorch_tpu.models.transformer import TransformerLM  # noqa: E402
from multidisttorch_tpu.ops.ring_attention import make_ring_attention  # noqa: E402
from multidisttorch_tpu.parallel.mesh import DATA_AXIS  # noqa: E402
from multidisttorch_tpu.train.lm import (  # noqa: E402
    create_lm_state,
    make_lm_train_step,
)


def main():
    parser = argparse.ArgumentParser(description="SP long-context LM demo")
    parser.add_argument("--seq-len", type=int, default=512)
    parser.add_argument("--batch-size", type=int, default=2)
    parser.add_argument("--vocab", type=int, default=32)
    parser.add_argument("--d-model", type=int, default=64)
    parser.add_argument("--layers", type=int, default=2)
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--lr", type=float, default=3e-3)
    parser.add_argument(
        "--remat", action="store_true",
        help="per-block activation rematerialization (the long-context "
        "HBM lever: only block-boundary residuals are stored)",
    )
    parser.add_argument(
        "--flash", action="store_true",
        help="single-device blockwise Pallas attention "
        "(ops/pallas_attention.py) instead of the device-ring: the "
        "whole sequence on one chip, scores never in HBM — the "
        "single-chip half of the long-context design",
    )
    parser.add_argument(
        "--ring-flash", action="store_true",
        help="both halves composed: K/V ring over the device group AND "
        "the Pallas flash kernel inside every hop (scores only ever in "
        "VMEM) — the framework's full long-context configuration",
    )
    parser.add_argument(
        "--corpus", type=str, default=None, metavar="FILE",
        help="byte-level model a real local file (vocab 256, fresh "
        "random windows each step) instead of the synthetic periodic "
        "stream — zero-egress real data",
    )
    parser.add_argument(
        "--temperature", type=float, default=0.0,
        help="sampling temperature for the final decode (0 = greedy); "
        "text models read better with ~0.8 + --top-p",
    )
    parser.add_argument("--top-k", type=int, default=None)
    parser.add_argument("--top-p", type=float, default=None)
    args = parser.parse_args()
    if args.flash and args.ring_flash:
        parser.error("--flash and --ring-flash are mutually exclusive")
    try:
        # fail bad sampling combos in milliseconds, not after training
        from multidisttorch_tpu.train.lm import _validate_sampling

        _validate_sampling(args.temperature, args.top_k, args.top_p)
    except ValueError as e:
        parser.error(str(e))

    mdt.initialize_runtime()
    (g,) = mdt.setup_groups(1)
    if not args.flash and args.seq_len % g.size:
        # only the device-ring shards the sequence; flash keeps it whole
        parser.error(f"--seq-len must divide by {g.size} devices")
    if args.flash:
        from multidisttorch_tpu.ops.pallas_attention import make_flash_attention

        attention = make_flash_attention(causal=True)
        print(f"flash attention on 1 device; {args.seq_len} tokens resident")
    elif args.ring_flash:
        from multidisttorch_tpu.ops.pallas_attention import (
            make_ring_flash_attention,
        )

        attention = make_ring_flash_attention(g, causal=True)
        print(
            f"ring-flash over {g.size} devices; {args.seq_len} tokens "
            f"({args.seq_len // g.size} per device, flash-kernel hops)"
        )
    else:
        attention = make_ring_attention(g, causal=True)
        print(
            f"ring of {g.size} devices; {args.seq_len} tokens "
            f"({args.seq_len // g.size} per device)"
        )

    if args.corpus:
        from multidisttorch_tpu.data import byte_corpus

        corpus = byte_corpus(args.corpus)
        args.vocab = corpus.vocab_size
        print(f"byte-modeling {corpus.name}: {len(corpus):,} tokens, "
              f"vocab {corpus.vocab_size}")
    else:
        from multidisttorch_tpu.data import synthetic_corpus

        # Periodic stream: perfectly learnable, so the loss trend is
        # the whole story. Sized from the context so any --seq-len fits.
        corpus = synthetic_corpus(
            n=max(65536, 4 * args.seq_len), vocab_size=args.vocab, period=16
        )

    model = TransformerLM(
        vocab_size=args.vocab,
        d_model=args.d_model,
        num_layers=args.layers,
        max_len=args.seq_len,
        attention=attention,
        remat=args.remat,
    )
    tx = optax.adam(args.lr)
    state = create_lm_state(g, model, tx, jax.random.key(0),
                            example_len=args.seq_len)
    step = make_lm_train_step(g, model, tx,
                              sequence_parallel=not args.flash)

    if args.flash and args.batch_size % g.size:
        # flash mode shards the BATCH over the group (plain DP; the
        # sequence stays whole per device) — round the batch up.
        args.batch_size = ((args.batch_size // g.size) + 1) * g.size
        print(f"flash mode: batch rounded up to {args.batch_size} "
              f"(divisible by {g.size} devices)")
    sharding = g.batch_sharding if args.flash else g.sharding(None, DATA_AXIS)
    rng = np.random.default_rng(0)

    t0 = time.time()
    for i in range(args.steps):
        tokens = g.device_put(
            corpus.batch(rng, args.batch_size, args.seq_len), sharding
        )
        state, m = step(state, tokens)
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  next-token loss {float(m['loss']):.4f}")
    print(f"done in {time.time() - t0:.1f}s "
          f"(loss should fall well below ln(vocab)={np.log(args.vocab):.2f})")

    # Decode a continuation from a real prompt — the reference ends its
    # trials by sampling the model (vae-hpo.py:163-170); this is the LM
    # analog. Decoding needs the whole sequence per device, so it uses
    # the batch-sharded contract (prompt replicated to a full batch).
    # KV-cache decode (one cache-masked attention per token) — parity-
    # pinned to the full-recompute sampler in tests/test_lm_decode.py.
    from multidisttorch_tpu.train.lm_decode import make_cached_lm_sample

    sample = make_cached_lm_sample(
        g, model, temperature=args.temperature,
        top_k=args.top_k, top_p=args.top_p,
    )
    prompt_len = args.seq_len // 2
    window = corpus.batch(np.random.default_rng(1), 1, args.seq_len)
    # rows are identical prompts; g.size rows satisfy batch sharding
    # for any --batch-size
    buf = np.tile(window, (g.size, 1))
    out = np.asarray(
        sample(
            state,
            g.device_put(buf.astype(np.int32), g.batch_sharding),
            prompt_len,
            jax.random.key(0),
        )
    )
    if args.corpus:
        show = lambda a: bytes(a.tolist()).decode("latin-1")
        print(f"prompt:   {show(out[0, :prompt_len])!r}")
        print(f"decoded:  {show(out[0, prompt_len:])!r}")
    else:
        kind = "greedy" if args.temperature <= 0 else "sampled"
        match = (out[0, prompt_len:] == window[0, prompt_len:]).mean()
        print(f"{kind} decode matches the true continuation at "
              f"{100 * match:.0f}% of generated positions")


if __name__ == "__main__":
    main()
