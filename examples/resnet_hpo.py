"""ResNet-18 classifier HPO over device subgroups (BASELINE.md config 4:
"swap model; reuse subgroup scaffolding").

Demonstrates that the subgroup machinery is model-agnostic: the same
``setup_groups`` carving, ``TrialDataIterator`` feeding, and cooperative
round-robin dispatch as the VAE sweep, with classifier train/eval steps.
Each trial sweeps the learning rate.

Run (8 virtual CPU devices):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/resnet_hpo.py --ngroups 2 --epochs 1 \
            --base-channels 8 --synthetic-size 1024
"""

import argparse
import os
import sys
import time

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import multidisttorch_tpu as mdt  # noqa: E402
import optax  # noqa: E402
from multidisttorch_tpu.data import TrialDataIterator, load_cifar10  # noqa: E402
from multidisttorch_tpu.models import ResNet18  # noqa: E402
from multidisttorch_tpu.train.classifier import (  # noqa: E402
    create_classifier_state,
    make_classifier_eval_step,
    make_classifier_multi_step,
    make_classifier_train_step,
)


def main():
    parser = argparse.ArgumentParser(description="ResNet-18 HPO (TPU-native)")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--ngroups", type=int, default=2)
    parser.add_argument("--base-channels", type=int, default=64)
    parser.add_argument("--synthetic-size", type=int, default=None)
    parser.add_argument(
        "--fused-steps", type=int, default=4,
        help="train steps fused into one device dispatch via lax.scan",
    )
    args = parser.parse_args()

    mdt.initialize_runtime()
    train_data = load_cifar10(train=True, synthetic_size=args.synthetic_size)
    test_data = load_cifar10(
        train=False,
        synthetic_size=args.synthetic_size and max(args.batch_size, args.synthetic_size // 6),
    )

    groups = mdt.setup_groups(args.ngroups)
    model = ResNet18(num_classes=10, base_channels=args.base_channels)
    # lr sweep: trial g trains with lr = 1e-3 * 2^g
    lrs = [1e-3 * (2.0**g) for g in range(args.ngroups)]

    trials = []
    for g, lr in zip(groups, lrs):
        if not g.is_local_member:  # multi-host: skip remote submeshes
            continue
        tx = optax.adam(lr)
        state = create_classifier_state(g, model, tx, jax.random.key(g.group_id))
        trials.append(
            {
                "trial": g,
                "lr": lr,
                "state": state,
                "step": make_classifier_multi_step(g, model, tx),
                "tail_step": make_classifier_train_step(g, model, tx),
                "eval": make_classifier_eval_step(g, model),
                "iter": TrialDataIterator(
                    train_data, g, args.batch_size,
                    seed=g.group_id, with_labels=True,
                ),
            }
        )

    # Cooperative round-robin across subgroups (same no-barrier execution
    # model as hpo.driver.run_hpo), one scan-fused chunk per dispatch.
    # Epoch-tail chunks shorter than fused_steps run batch-by-batch
    # through the single-step compile instead of triggering a second
    # scan compilation for the odd length.
    t0 = time.time()
    for epoch in range(args.epochs):
        iters = [
            t["iter"].epoch_chunks(epoch, args.fused_steps) for t in trials
        ]
        live = list(range(len(trials)))
        while live:
            for i in list(live):
                try:
                    _, images, labels = next(iters[i])
                except StopIteration:
                    live.remove(i)
                    continue
                t = trials[i]
                if images.shape[0] == args.fused_steps:
                    t["state"], m = t["step"](t["state"], images, labels)
                else:
                    for j in range(images.shape[0]):
                        t["state"], m = t["tail_step"](
                            t["state"], images[j], labels[j]
                        )
                t["last_metrics"] = m

    for t in trials:
        g = t["trial"]
        correct, total = 0.0, 0
        ev_iter = TrialDataIterator(
            test_data, g, args.batch_size, with_labels=True
        )
        for images, labels in ev_iter.epoch(0):
            out = t["eval"](t["state"], images, labels)
            correct += float(out["correct"])
            total += images.shape[0]
        mdt.log0(
            f"trial {g.group_id} (lr={t['lr']:.0e}): "
            f"test acc {correct / total:.3f} "
            f"({int(correct)}/{total}), wall {time.time() - t0:.1f}s",
            trial=g,
        )


if __name__ == "__main__":
    main()
