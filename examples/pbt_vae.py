"""Population-based training of VAEs (BASELINE.md config 5:
"inter-subgroup weight broadcast/exploit across submeshes").

Run (8 virtual CPU devices, population of 4 on 2-device submeshes):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/pbt_vae.py --population 4 --generations 3

``--fused`` runs the population as K lanes of ONE vmapped program on a
single submesh instead: a whole generation (train + eval +
exploit/explore) is one dispatch of the registered ``pbt_gen`` program
(docs/PBT.md), bit-identical to the per-submesh mode under the shared
seeding contract.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import multidisttorch_tpu as mdt  # noqa: E402
from multidisttorch_tpu.data import load_mnist  # noqa: E402
from multidisttorch_tpu.hpo import PBTConfig, run_pbt  # noqa: E402


def main():
    parser = argparse.ArgumentParser(description="PBT VAE (TPU-native)")
    parser.add_argument("--population", type=int, default=4)
    parser.add_argument("--generations", type=int, default=3)
    parser.add_argument("--steps-per-generation", type=int, default=50)
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--out-dir", default="results-pbt")
    parser.add_argument("--synthetic-size", type=int, default=None)
    parser.add_argument(
        "--fused", action="store_true",
        help="run the population as lanes of one fused generation "
        "program (one dispatch per generation) instead of one member "
        "per submesh",
    )
    args = parser.parse_args()

    mdt.initialize_runtime()
    train_data = load_mnist(train=True, synthetic_size=args.synthetic_size)
    eval_data = load_mnist(
        train=False,
        synthetic_size=args.synthetic_size and max(args.batch_size, args.synthetic_size // 6),
    )

    cfg = PBTConfig(
        population=args.population,
        generations=args.generations,
        steps_per_generation=args.steps_per_generation,
        batch_size=args.batch_size,
    )
    result = run_pbt(
        cfg, train_data, eval_data, out_dir=args.out_dir, fused=args.fused
    )
    book = result.dispatch_book
    print(
        f"[{result.mode}] best member {result.best_member}: eval loss "
        f"{result.best_eval_loss:.2f}; final lrs "
        f"{['%.1e' % lr for lr in result.final_lrs]}; "
        f"wall {result.wall_s:.1f}s; "
        f"{book.get('dispatches_per_generation')} dispatches/gen"
    )


if __name__ == "__main__":
    main()
