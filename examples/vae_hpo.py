"""Concurrent VAE HPO trials, one per device subgroup — TPU-native mirror
of /root/reference/vae-hpo.py (same CLI flags).

The reference: N process subgroups, each running a DDP-wrapped VAE on
MNIST, the trial's hyperparameter being ``epochs + group_id``
(vae-hpo.py:202). Here: N disjoint submeshes, each running a
jit-compiled data-parallel train step, dispatched concurrently by the
host driver with no cross-trial barriers. Extra flags expose the knobs
the reference hard-codes (lr, β, data sharding mode).

Run (8 virtual CPU devices):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/vae_hpo.py --epochs 1 --ngroups 2
"""

import argparse
import os
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import multidisttorch_tpu as mdt  # noqa: E402
from multidisttorch_tpu.data import load_mnist  # noqa: E402
from multidisttorch_tpu.hpo import TrialConfig, run_hpo  # noqa: E402


def main():
    parser = argparse.ArgumentParser(description="VAE MNIST Example (TPU-native)")
    # Reference flags, same names and defaults (vae-hpo.py:178-194):
    parser.add_argument(
        "--batch-size", type=int, default=128, metavar="N",
        help="input batch size for training (default: 128)",
    )
    parser.add_argument(
        "--epochs", type=int, default=3, metavar="N",
        help="number of epochs to train (default: 3)",
    )
    parser.add_argument("--ngroups", type=int, default=2, help="number of groups")
    # Knobs the reference hard-codes:
    parser.add_argument("--lr", type=float, default=1e-3, help="Adam lr (vae-hpo.py:131)")
    parser.add_argument("--beta", type=float, default=1.0, help="beta-VAE KL weight")
    parser.add_argument("--out-dir", default="results", help="output root (per-trial subdirs)")
    parser.add_argument(
        "--shard-across-trials", action="store_true",
        help="reproduce the reference's cross-trial data sharding (SURVEY.md Q1)",
    )
    parser.add_argument(
        "--synthetic-size", type=int, default=None,
        help="rows for the synthetic fallback dataset (default: MNIST-sized)",
    )
    parser.add_argument(
        "--fused-steps", type=int, default=10,
        help="train steps fused into one device dispatch via lax.scan "
        "(default 10 = the log cadence; 1 reproduces the reference's "
        "one-dispatch-per-batch loop shape)",
    )
    parser.add_argument(
        "--eval-sampled", action="store_true",
        help="reproduce the reference's sampled-z test loss "
        "(vae-hpo.py:101-105) instead of the default posterior-mean eval",
    )
    parser.add_argument(
        "--remat", action="store_true",
        help="rematerialize activations in the backward pass "
        "(jax.checkpoint) — trade FLOPs for HBM",
    )
    args = parser.parse_args()

    nproc, pid = mdt.initialize_runtime()
    ndev, _ = mdt.device_world()
    print(f"devices: {ndev}, processes: {nproc}")

    train_data = load_mnist(train=True, synthetic_size=args.synthetic_size)
    test_data = load_mnist(
        train=False,
        synthetic_size=args.synthetic_size and max(args.batch_size, args.synthetic_size // 6),
    )

    # The reference's HPO sweep: trial g trains epochs + g epochs
    # (vae-hpo.py:202). Config generalizes the rest of the knobs.
    configs = [
        TrialConfig(
            trial_id=g,
            epochs=args.epochs + g,
            batch_size=args.batch_size,
            lr=args.lr,
            beta=args.beta,
            seed=g,
            fused_steps=args.fused_steps,
            eval_sampled=args.eval_sampled,
            remat=args.remat,
        )
        for g in range(args.ngroups)
    ]

    results = run_hpo(
        configs,
        train_data,
        test_data,
        out_dir=args.out_dir,
        shard_across_trials=args.shard_across_trials,
    )
    for r in results:
        print(
            f"trial {r.trial_id}: {r.steps} steps, "
            f"final train loss {r.final_train_loss:.4f}, "
            f"test loss {r.final_test_loss:.4f}, wall {r.wall_s:.2f}s "
            f"-> {r.out_dir}"
        )


if __name__ == "__main__":
    main()
