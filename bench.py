"""Benchmark: VAE training samples/sec/chip vs the reference implementation.

Measures the flagship workload (MNIST-shaped VAE, batch 128 — the
reference's defaults, /root/reference/vae-hpo.py:131,183) as a
jit-compiled train step on the available accelerator, against the
reference's torch train loop executed in-process on CPU (the only
hardware its stack can use here; the reference publishes no numbers of
its own — see BASELINE.md).

Prints exactly ONE JSON line:
  {"metric": "vae_train_samples_per_sec_per_chip", "value": ...,
   "unit": "samples/sec/chip", "vs_baseline": ...}

vs_baseline = our throughput / reference-loop throughput.
"""

import contextlib
import json
import subprocess
import sys
import time
import warnings
from functools import partial

warnings.filterwarnings("ignore")

import os

import jax

# The environment's sitecustomize may pre-import jax with a TPU plugin
# pinned; honor an explicit JAX_PLATFORMS override (same trick as
# tests/conftest.py) so the concurrency mode can run on virtual CPU
# devices via XLA_FLAGS=--xla_force_host_platform_device_count=N.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import numpy as np
import optax

BATCH = 128
HIDDEN, LATENT = 400, 20
CHUNK_STEPS = 100  # inner lax.scan steps per dispatch (make_multi_step)
CHUNK_STEPS_TPU = 1000  # on the real chip a 100-step chunk is ~1 ms of
# device time at the recorded rate — the same order as ONE host enqueue
# (docs/DISPATCH.md), so the flagship was host-bound on TPU. 1000 steps
# ≈ 10 ms device per dispatch (enqueue ≪ compute) at 401 MB of stacked
# batch data — comfortable in 16 GB HBM. CPU runs keep the smaller
# chunk (compute-bound there; bigger chunks only slow the fallback).
MEASURE_CHUNKS = 10
MEASURE_REPEATS = 5  # timed passes per number; report the median. The
# chip is reached through a tunnel with ~2x run-to-run throughput
# variance (round 4: 6.5M vs 12.7M on the identical program) — one
# pass is a coin flip; five passes give a defensible median AND a
# p10/p90 spread the artifact can report (VERDICT r4 item 4). Each
# pass is ~128k samples, so the extra passes cost well under a second.
TORCH_MEASURE_STEPS = 30


def _chunk_steps() -> int:
    """Backend-resolved scan chunk (one policy for every bench mode)."""
    return CHUNK_STEPS_TPU if jax.default_backend() == "tpu" else CHUNK_STEPS

# The TPU probe/triage engine moved to utils/preflight.py (ISSUE 6):
# the same banked BENCH_r04/r05 triage now also backs tools/preflight.py
# and the elastic supervisor's pre-world probe. The aliases keep this
# file's artifact schema (and tests/test_bench.py) unchanged.
from multidisttorch_tpu.utils.preflight import (  # noqa: E402
    PREFLIGHT_TIMEOUT_S,
    RETRY_DELAY_S,
    RETRY_TIMEOUT_S,
    plugin_scan as _tpu_triage,
    preflight_default_backend as _preflight_default_backend,
    probe_init as _probe_once,
)


def _ensure_backend() -> dict:
    """Pick the bench platform; never hang or crash on a wedged TPU.

    Priority: MDT_PLATFORM override (see parallel/cluster.py) →
    JAX_PLATFORMS=cpu test harness → preflight-verified default backend →
    CPU fallback carrying the TPU diagnostic. Returns provenance for the
    emitted JSON: {"platform", "device_kind", "tpu_error"?}.
    """
    from multidisttorch_tpu.parallel.cluster import select_platform

    forced = select_platform()
    if forced:
        d = jax.devices()[0]
        return {"platform": d.platform, "device_kind": d.device_kind,
                "forced_by": "MDT_PLATFORM"}
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
        d = jax.devices()[0]
        return {"platform": d.platform, "device_kind": d.device_kind}
    probe = _preflight_default_backend()
    if probe["ok"]:
        out = {
            "platform": probe["platform"],
            "device_kind": probe["device_kind"],
        }
        # A first-probe wedge that cleared on retry is still evidence —
        # keep it in the artifact (transient wedges are exactly what the
        # retry exists to distinguish from permanent ones).
        if "triage_after_first_failure" in probe:
            out["tpu_triage"] = probe["triage_after_first_failure"]
        return out
    jax.config.update("jax_platforms", "cpu")
    d = jax.devices()[0]
    return {
        "platform": d.platform,
        "device_kind": d.device_kind,
        "tpu_error": probe["error"],
        "tpu_stderr_tail": probe.get("stderr_tail", ""),
        "tpu_triage": probe.get("tpu_triage", {}),
    }


def _train_flops_per_sample() -> float:
    """Analytic matmul FLOPs for one optimizer step, per sample.

    Forward = 2·MACs over the five dense layers of the flagship VAE
    (784-400-(20,20)-400-784); backward for a dense stack is ~2x forward
    (grad-activations + grad-weights matmuls), so train ≈ 3x forward.
    Elementwise/optimizer FLOPs are negligible next to the matmuls.
    """
    dims = [
        (784, HIDDEN),
        (HIDDEN, LATENT),
        (HIDDEN, LATENT),
        (LATENT, HIDDEN),
        (HIDDEN, 784),
    ]
    fwd = 2.0 * sum(a * b for a, b in dims)
    return 3.0 * fwd


def _peak_flops_per_chip(device_kind: str) -> float | None:
    # The peak table moved to telemetry/device.py (the device books'
    # MFU needs it at sweep time); bench delegates so the two MFU
    # computations can never disagree on what "peak" means.
    from multidisttorch_tpu.telemetry.device import peak_flops_per_chip

    return peak_flops_per_chip(device_kind)


def _flops_agreement(
    analytic: float, fn, args, per_step_divisor: float, devices: int = 1
) -> dict:
    """Cross-check an analytic FLOPs estimate against XLA's own
    ``cost_analysis`` of the compiled program (telemetry/device.py).

    ``per_step_divisor`` converts the compiled dispatch's total FLOPs
    to the analytic estimate's unit (per sample / per token);
    ``devices`` is the submesh size the program is partitioned over —
    ``cost_analysis`` describes the PER-DEVICE module (measured:
    1/n of global on an n-device data-sharded program), while the
    divisor counts global samples/tokens, so the per-device figure is
    scaled back to global first. The banked MFU numbers stop being
    trust-me arithmetic: the artifact records both figures and flags
    >10% disagreement.

    Known caveat the flag is EXPECTED to trip on: XLA:CPU rewrites
    large dots to library custom calls (oneDNN/Eigen) whose FLOPs the
    analysis does not count, so the CPU fallback undercounts matmul-
    heavy programs. The check's authority is the TPU path, where dots
    stay HLO dots; a CPU-artifact flag documents that undercount
    rather than an arithmetic error."""
    from multidisttorch_tpu.telemetry.device import compiled_cost_analysis

    ca = compiled_cost_analysis(fn, args)
    if ca["flops"] is None:
        return {"analytic": analytic, "cost_analysis": None,
                "reason": ca["reason"]}
    measured = ca["flops"] * max(1, devices) / per_step_divisor
    ratio = measured / analytic if analytic else None
    return {
        "analytic": analytic,
        "cost_analysis": round(measured, 1),
        "ratio": round(ratio, 4) if ratio is not None else None,
        # XLA counts every op post-optimization; the analytic figure is
        # matmuls-only —>10% disagreement means the banked MFU's
        # numerator needs a second look, in either direction.
        "disagrees_over_10pct": (
            bool(abs(ratio - 1.0) > 0.10) if ratio is not None else None
        ),
    }


def _flagship_setup(num_groups: int = 1):
    """The benchmark subject shared by every mode: the flagship VAE at
    the reference's defaults (batch 128, Adam 1e-3 — vae-hpo.py:131,183)
    carved over ``num_groups`` submeshes. bfloat16 matmuls on the MXU,
    float32 params/loss — the TPU-first configuration; on CPU runs it
    silently behaves like float32."""
    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.mesh import setup_groups

    groups = setup_groups(num_groups)
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    model = VAE(hidden_dim=HIDDEN, latent_dim=LATENT, dtype=dtype)
    tx = optax.adam(1e-3)
    return groups, model, tx


def _timed_chunks(
    trial, model, tx, agreement: bool = True, **step_kwargs
) -> tuple[float, list, dict]:
    """The one measurement protocol: scan-fused dispatch (a
    backend-sized chunk of optimizer updates per host round-trip —
    ``_chunk_steps()`` — the TPU-idiomatic shape of the reference's
    per-batch loop, vae-hpo.py:67-74), one warmup
    compile, then MEASURE_REPEATS passes of MEASURE_CHUNKS timed chunks.
    Returns ``(median, per_pass_rates)`` in samples/sec (whole submesh) —
    the tunnel to the chip has ~2x run-to-run variance, so single-pass
    numbers aren't defensible and the artifact reports the distribution. Both single-trial throughput modes (the headline number
    and the fused-loss comparison that decides defaults against it) go
    through here so those two can't drift; bench_concurrency and
    bench_to_elbo measure deliberately different things (interleaved
    multi-trial dispatch; loss-gated wall-clock) with their own loops."""
    from multidisttorch_tpu.train.steps import create_train_state, make_multi_step
    from multidisttorch_tpu.utils.profiling import profile_trace

    chunk = _chunk_steps()
    state = create_train_state(trial, model, tx, jax.random.key(0))
    multi = make_multi_step(trial, model, tx, **step_kwargs)
    # Synthetic batches generated ON DEVICE, directly into the data
    # sharding: at the TPU chunk size this is 401 MB that would
    # otherwise cross the (slow, intermittent) tunnel per timed mode.
    batches = jax.jit(
        lambda k: jax.random.uniform(k, (chunk, BATCH, 784), jnp.float32),
        out_shardings=trial.sharding(None, "data"),
    )(jax.random.key(0))
    key = jax.random.key(1)
    state, _ = multi(state, batches, key)  # compile + warmup
    jax.block_until_ready(state.params)
    # MDT_BENCH_TRACE=<dir>: wrap the first timed pass in a JAX
    # profiler trace (TensorBoard/Perfetto-loadable; device timelines
    # on TPU) — evidence for where a bad number comes from.
    trace_dir = os.environ.get("MDT_BENCH_TRACE")
    rates = []
    for r in range(MEASURE_REPEATS):
        ctx = (
            profile_trace(trace_dir)
            if trace_dir and r == 0
            else contextlib.nullcontext()
        )
        with ctx:
            t0 = time.perf_counter()
            for i in range(MEASURE_CHUNKS):
                state, _ = multi(
                    state, batches,
                    jax.random.fold_in(key, r * MEASURE_CHUNKS + i),
                )
            jax.block_until_ready(state.params)
            dt = time.perf_counter() - t0
        rates.append(MEASURE_CHUNKS * chunk * BATCH / dt)
    # MFU cross-check (unit: FLOPs per sample): XLA's cost analysis of
    # the exact program timed above vs the analytic matmul count.
    # agreement=False skips it — the AOT lower+compile is a real extra
    # compile, wasted on callers that discard the dict (the fused-loss
    # comparison times two program variants and keeps only the rates).
    agree = (
        _flops_agreement(
            _train_flops_per_sample(), multi, (state, batches, key),
            chunk * BATCH, devices=trial.size,
        )
        if agreement
        else {}
    )
    return float(np.median(rates)), rates, agree


def bench_ours() -> dict:
    """Flagship throughput with its pass distribution (VERDICT r4 #4):
    median + p10/p90 over MEASURE_REPEATS timed windows in ONE process,
    so the headline is never a single-shot coin flip through the
    variable tunnel."""
    ndev = len(jax.devices())
    (trial,), model, tx = _flagship_setup(1)
    med, rates, flops_agreement = _timed_chunks(trial, model, tx)
    per_chip = [r / ndev for r in rates]
    return {
        "samples_per_sec_per_chip": round(med / ndev, 1),
        "pass_samples_per_sec_per_chip": [round(r, 1) for r in per_chip],
        "p10": round(float(np.percentile(per_chip, 10)), 1),
        "p90": round(float(np.percentile(per_chip, 90)), 1),
        "passes": len(per_chip),
        # Analytic-vs-XLA FLOPs/sample for the timed program — the
        # flagship MFU's numerator, cross-checked (>10% flags).
        "flops_agreement": flops_agreement,
        # Measurement shape provenance: the chunk became
        # backend-dependent in r5, so cross-round artifact comparisons
        # need the value recorded next to the number it produced.
        "chunk_steps": _chunk_steps(),
    }


def bench_fused_loss_comparison() -> dict:
    """Pallas ELBO kernel vs XLA's own fusion, on real hardware only.

    VERDICT r3 item 5's decision data: the tiled kernel
    (ops/pallas_elbo.py) has never been timed against XLA on a TPU.
    This times the identical scan-fused train program with
    use_fused_loss on/off and records both rates; the winner decides
    use_fused_loss's default. Skipped off-TPU (interpret-mode Pallas
    timings are meaningless).
    """
    (trial,), model, tx = _flagship_setup(1)
    out = {}
    for label, fused in (("xla_loss", False), ("pallas_fused_loss", True)):
        med, rates, _agree = _timed_chunks(
            trial, model, tx, agreement=False, use_fused_loss=fused
        )
        out[label + "_samples_per_sec"] = round(med, 1)
        out[label + "_pass_rates"] = [round(r, 1) for r in rates]
    out["winner"] = (
        "pallas"
        if out["pallas_fused_loss_samples_per_sec"]
        > out["xla_loss_samples_per_sec"]
        else "xla"
    )
    return out


# Stacked-trial bench shape: a fixed pool of 8 pending flagship trials
# (the stacking precondition — trials outnumber groups), run at K lanes
# per single-device group through the vmapped stacked step
# (train.steps.make_stacked_train_step), per-step dispatch (chunk 1 —
# the loop shape where small-trial sweeps are host-bound,
# docs/DISPATCH.md: blocked share 0.85-0.98). K=1 is today's
# one-trial-per-group path; higher K packs the same trials onto fewer
# chips, one dispatch advancing K trials. The headline is
# samples/sec per OCCUPIED chip: the consolidation win — the same sweep
# on 1/K of the chips (equivalently, K sweeps on the same chips) — is
# exactly what stacking buys, and per-occupied-chip throughput is the
# number that states it without crediting idle hardware.
STACKED_TRIALS = 8
STACKED_MEASURE_STEPS = 100  # optimizer steps per trial per timed pass
STACKED_REPEATS = 3
STACKED_LEVELS = (1, 2, 4, 8)


def bench_stacked() -> dict:
    """Per-occupied-chip throughput of 8 flagship trials at K lanes/group.

    The artifact the trial-stacking mode is judged by (ISSUE 1
    acceptance: >= 1.5x samples/sec/chip at K=4 vs K=1 on the CPU
    fallback): same 8 trials, same per-trial batch, same model — only
    the lanes-per-group packing varies. ``dispatches_per_trial_step``
    (1/K) states the mechanism next to the outcome. On the CPU fallback
    the groups are virtual single-CPU devices (the same harness
    topology as bench_concurrency and docs/DISPATCH.md), and the same
    caveat applies: virtual chips share host cores, so the ratio is a
    methodology proof of the packing win, not a hardware number — the
    real-chip rerun banks itself through the suite when a TPU window
    opens.
    """
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import (
        TrialHypers,
        create_stacked_train_state,
        make_stacked_train_step,
    )

    ndev = len(jax.devices())
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    from multidisttorch_tpu.models.vae import VAE

    model = VAE(hidden_dim=HIDDEN, latent_dim=LATENT, dtype=dtype)
    all_groups = setup_groups(ndev)  # single-device groups
    out = {
        "trials": STACKED_TRIALS,
        "chunk_steps": 1,
        "measure_steps": STACKED_MEASURE_STEPS,
        "n_devices": ndev,
        "levels": [],
    }
    if jax.default_backend() == "cpu":
        out["cpu_caveat"] = (
            "virtual CPU devices share host cores: per-occupied-chip "
            "ratios prove the packing methodology, not real-chip "
            "throughput (same caveat as bench --concurrency)"
        )
    rates = {}
    for k in [lv for lv in STACKED_LEVELS if lv <= STACKED_TRIALS]:
        buckets = STACKED_TRIALS // k
        chips_used = min(ndev, buckets)
        units = []
        for b in range(buckets):
            g = all_groups[b % chips_used]
            step = make_stacked_train_step(g, model)
            state = create_stacked_train_state(g, model, list(range(k)))
            base_rngs = jnp.stack(
                [jax.random.key(s + 1) for s in range(k)]
            )
            batch = jax.jit(
                lambda key, k=k, g=g: jax.random.uniform(
                    key, (k, BATCH, 784), jnp.float32
                ),
                out_shardings=g.sharding(None, "data"),
            )(jax.random.key(0))
            units.append(
                {
                    "step": step,
                    "state": state,
                    "base": base_rngs,
                    "batch": batch,
                    "hypers": TrialHypers.stack([1e-3] * k, [1.0] * k),
                }
            )
        lane_steps = [
            jnp.full((k,), i, jnp.int32)
            for i in range(STACKED_MEASURE_STEPS)
        ]
        for u in units:  # compile + warmup every unit
            u["state"], _ = u["step"](
                u["state"], u["hypers"], u["batch"], u["base"], lane_steps[0]
            )
        for u in units:
            jax.block_until_ready(u["state"].params)
        pass_rates = []
        for _ in range(STACKED_REPEATS):
            t0 = time.perf_counter()
            for i in range(STACKED_MEASURE_STEPS):
                for u in units:  # the driver's round-robin dispatch shape
                    u["state"], _ = u["step"](
                        u["state"], u["hypers"], u["batch"], u["base"],
                        lane_steps[i],
                    )
            for u in units:
                jax.block_until_ready(u["state"].params)
            dt = time.perf_counter() - t0
            agg = STACKED_MEASURE_STEPS * STACKED_TRIALS * BATCH / dt
            pass_rates.append(agg / chips_used)
        med = float(np.median(pass_rates))
        rates[k] = med
        out["levels"].append(
            {
                "k": k,
                "buckets": buckets,
                "chips_used": chips_used,
                "samples_per_sec_per_chip": round(med, 1),
                "pass_rates": [round(r, 1) for r in pass_rates],
                "dispatches_per_trial_step": round(1.0 / k, 4),
            }
        )
    for lvl in out["levels"]:
        lvl["speedup_vs_k1"] = round(rates[lvl["k"]] / rates[1], 3)
    out["k4_vs_k1"] = (
        round(rates[4] / rates[1], 3) if 4 in rates and 1 in rates else None
    )
    # Telemetry overhead A/B (ISSUE 3 acceptance: <= 2% step-time
    # overhead with telemetry ON vs OFF, both recorded in the artifact).
    try:
        out["telemetry_overhead"] = bench_telemetry_overhead()
    except Exception as e:  # record, never lose the packing numbers
        out["telemetry_overhead"] = {"error": repr(e)[:300]}
    if any(lvl["chips_used"] < lvl["buckets"] for lvl in out["levels"]):
        # Fewer devices than buckets (e.g. the suite on a 1-chip TPU or
        # un-flagged CPU): buckets time-share chips, so per-occupied-
        # chip ratios no longer isolate the packing win the protocol
        # documents — say so in the artifact instead of leaving a
        # degenerate number that reads like a real one.
        out["packing_limited"] = True
        out["packing_note"] = (
            "buckets exceed devices at some K: levels time-share chips "
            "and speedup_vs_k1 is NOT the per-occupied-chip packing "
            "ratio of docs/STACKING.md (run via `bench.py --stacked`, "
            "which forces the 8-virtual-device topology on CPU)"
        )
    return out


DATAPLANE_LANES = 8
DATAPLANE_ROWS = 2048   # per lane-dataset; 16 batches/round at BATCH=128
DATAPLANE_ROUNDS = 4    # measured lockstep rounds per mode


def bench_dataplane() -> dict:
    """The production data plane's banked evidence (docs/DATA.md):
    K=8 heterogeneous lanes — eight DISTINCT datasets through one
    vmapped dispatch — comparing the pipelined sharded input path
    against the synchronous reference on three axes:

    - **bit-parity**: the fused heterogeneous dispatch's final params,
      lane by lane, against each lane's classic ``make_train_step`` run
      on its own dataset (the PR 1 parity recipe, now across dataset
      boundaries), and pipelined vs synchronous feeds byte-for-byte;
    - **input_bound_frac**: fraction of dispatch wall spent blocked on
      the host gather+transfer, pipeline ON vs OFF — the "gather is off
      the critical path" gate (< 5% with the pipeline);
    - **packing across datasets**: the service scheduler co-packs 8
      tenants with 8 different dataset refs of one shape class into ONE
      placement (no per-dataset bucket splitting).
    """
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.data.sampler import StackedTrialDataIterator
    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import (
        TrialHypers,
        create_stacked_train_state,
        create_train_state,
        make_stacked_train_step,
        make_train_step,
    )

    K, rows, rounds = DATAPLANE_LANES, DATAPLANE_ROWS, DATAPLANE_ROUNDS
    g = setup_groups(1)[0]
    model = VAE(hidden_dim=HIDDEN, latent_dim=LATENT)
    datasets = [synthetic_mnist(rows, seed=100 + k) for k in range(K)]
    seeds = list(range(K))
    lrs = [1e-3 * (1 + 0.1 * k) for k in range(K)]
    hypers = TrialHypers.stack(lrs, [1.0] * K)
    base_rngs = jnp.stack([jax.random.key(s + 1) for s in seeds])
    sstep = make_stacked_train_step(g, model)
    steps_per_round = rows // BATCH

    def run_mode(prefetch: bool) -> dict:
        state = create_stacked_train_state(g, model, seeds)
        waits = {"wait_s": 0.0, "bytes": 0}

        def wait_hook(dt, nb):
            waits["wait_s"] += dt
            waits["bytes"] += nb

        it = StackedTrialDataIterator(
            datasets[0], g, BATCH, seeds, datasets=datasets,
            use_native=False, prefetch=prefetch, wait_hook=wait_hook,
        )
        # warmup compile outside the timed window — on a throwaway
        # state (the stacked step donates its input state buffers)
        warm_state = create_stacked_train_state(g, model, seeds)
        warm = jnp.zeros((K, BATCH, 784), jnp.float32)
        w, _ = sstep(
            warm_state, hypers, warm, base_rngs, jnp.zeros((K,), jnp.int32)
        )
        jax.block_until_ready(w.params)
        del warm_state, w
        step_no = 0
        t0 = time.perf_counter()
        for _ in range(rounds):
            for batch in it.round_batches():
                state, _ = sstep(
                    state, hypers, batch, base_rngs,
                    jnp.full((K,), step_no, jnp.int32),
                )
                step_no += 1
        jax.block_until_ready(state.params)
        wall = time.perf_counter() - t0
        return {
            "wall_s": round(wall, 4),
            "wait_s": round(waits["wait_s"], 4),
            "bytes": waits["bytes"],
            "input_bound_frac": round(waits["wait_s"] / wall, 4),
            "bytes_per_s": round(waits["bytes"] / wall, 1),
            "steps": step_no,
            "state": state,
        }

    sync = run_mode(False)
    pipe = run_mode(True)
    pipeline_parity = bool(
        jax.tree_util.tree_all(
            jax.tree.map(
                lambda a, b: bool(jnp.all(a == b)),
                sync["state"].params,
                pipe["state"].params,
            )
        )
    )

    # Per-lane classic reference across dataset boundaries: lane k's
    # final params must be bit-identical to make_train_step fed by a
    # TrialDataIterator-equivalent stream over ITS dataset.
    from multidisttorch_tpu.data.sampler import epoch_permutation

    lane_parity = True
    for k in range(K):
        su = create_train_state(
            g, model, optax.adam(lrs[k]), jax.random.key(seeds[k])
        )
        ustep = make_train_step(g, model, optax.adam(lrs[k]), beta=1.0)
        step_no = 0
        for epoch in range(1, rounds + 1):
            perm = epoch_permutation(
                seeds[k], epoch, np.arange(rows)
            )
            for b in range(steps_per_round):
                idx = perm[b * BATCH : (b + 1) * BATCH]
                batch = jax.device_put(
                    datasets[k].images[idx], g.batch_sharding
                )
                su, _ = ustep(
                    su, batch,
                    jax.random.fold_in(
                        jax.random.key(seeds[k] + 1), step_no
                    ),
                )
                step_no += 1
        lane_params = jax.tree.map(
            lambda x, k=k: x[k], pipe["state"].params
        )
        same = jax.tree_util.tree_all(
            jax.tree.map(
                lambda a, b: bool(jnp.all(a == b)), lane_params, su.params
            )
        )
        lane_parity = lane_parity and bool(same)

    # Scheduler-level co-pack across dataset refs: pure logic, no jax.
    from multidisttorch_tpu.service.scheduler import (
        FairShareScheduler,
        PendingTrial,
        SlicePool,
    )

    sched = FairShareScheduler()
    shape_bucket = (("shape",), (784, steps_per_round))
    for k in range(K):
        sched.push(
            PendingTrial(
                sub_id=f"s{k}",
                tenant=f"tenant-{k}",
                priority=1,
                cfg=None,
                bucket=shape_bucket,  # dataset identity NOT in the key
                size=1,
                cost=10.0,
                submit_ts=0.0,
                trial_id=k,
            )
        )
    placements = sched.schedule(SlicePool(2), max_lanes=K)
    copack = (
        len(placements) == 1 and placements[0].lanes == K
    )

    for mode in (sync, pipe):
        mode.pop("state")
    out = {
        "lanes": K,
        "rows_per_dataset": rows,
        "batch": BATCH,
        "rounds": rounds,
        "distinct_datasets": K,
        "prefetch_depth": int(
            os.environ.get("MDT_STACKED_PREFETCH_DEPTH", "2")
        ),
        "synchronous": sync,
        "pipelined": pipe,
        "wall_ratio_sync_over_pipelined": round(
            sync["wall_s"] / pipe["wall_s"], 3
        ),
        "bytes_per_s_per_host": pipe["bytes_per_s"],
        "gates": {
            "fused_bitwise_vs_per_lane_reference": lane_parity,
            "pipeline_bitwise_vs_synchronous": pipeline_parity,
            "input_bound_frac_pipelined_lt_5pct": (
                pipe["input_bound_frac"] < 0.05
            ),
            "copack_across_datasets_single_placement": copack,
        },
    }
    if jax.default_backend() == "cpu":
        out["cpu_caveat"] = (
            "virtual CPU devices share host cores with the gather "
            "threads: input_bound_frac proves the overlap methodology; "
            "absolute bytes/sec is not a TPU-host number"
        )
    return out


TELEMETRY_AB_PASSES = 6  # alternating OFF/ON timed passes (3 each)


def bench_pipeline() -> dict:
    """Giant-model trials' banked evidence (docs/PARALLEL.md): the
    ZeRO-style sharded weight update and cross-submesh MPMD pipeline
    parallelism, three gates:

    - **sharded-update parity + memory**: a zero_update trial's
      per-step losses match the replicated reference within the pinned
      tolerance, and its per-device optimizer bytes are <= 1/n_data x
      replicated + epsilon (analytic books — CPU included);
    - **service vector placement**: a 2-stage pipelined submission is
      placed by the real service as an ALL-OR-NOTHING vector of slice
      blocks (journal evidence) and completes;
    - **schedule model**: the completed trial's measured bubble
      fraction is within 10% of the analytic (S-1)/(S-1+M); stage
      parity of the pipelined execution against the single-mesh
      reference step rides the same run. Wall-clock recorded, never
      gated (CPU fallback time-shares one host — the standing MFU
      caveat; the device books carry null-with-reason until open
      item 5's real-TPU run).
    """
    import tempfile

    import optax

    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.data.sampler import TrialDataIterator
    from multidisttorch_tpu.hpo.driver import TrialConfig
    from multidisttorch_tpu.hpo.pipeline_run import (
        PIPELINE_BOOKS_NAME,
        run_pipeline_trial,
    )
    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.fsdp import (
        optimizer_state_bytes,
        place_zero_state,
    )
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.parallel.pipeline import (
        make_mpmd_reference_step,
        make_vae_stage_fns,
    )
    from multidisttorch_tpu.service.queue import SweepClient
    from multidisttorch_tpu.service.runtime import SweepService
    from multidisttorch_tpu.train.steps import (
        build_train_state,
        create_train_state,
        make_train_step,
    )

    ZERO_TOL = 2e-6  # pinned parity tolerance (docs/PARALLEL.md)
    EPS = 1.02  # small-leaf epsilon on the 1/n optimizer-bytes gate
    rows, batch, epochs, microbatches = 512, 64, 2, 4
    model = VAE()
    tx = optax.adam(1e-3)

    # -- gate 1: sharded weight update ------------------------------
    trial = setup_groups(2)[0]  # 4 devices
    n_data = trial.data_size
    ref_state = create_train_state(trial, model, tx, jax.random.key(0))
    z_state, z_sh = place_zero_state(
        trial, create_train_state(trial, model, tx, jax.random.key(0))
    )
    ref_bytes = optimizer_state_bytes(ref_state)
    z_bytes = optimizer_state_bytes(z_state)
    ref_step = make_train_step(trial, model, tx)
    z_step = make_train_step(trial, model, tx, shardings=z_sh)
    rs = np.random.RandomState(0)
    key = jax.random.key(1)
    max_rel = 0.0
    zero_losses = []
    for i in range(8):
        b = jax.device_put(
            jnp.asarray(rs.rand(batch, 784), jnp.float32),
            trial.batch_sharding,
        )
        r = jax.random.fold_in(key, i)
        ref_state, mr = ref_step(ref_state, b, r)
        z_state, mz = z_step(z_state, b, r)
        lr_, lz_ = float(mr["loss_sum"]), float(mz["loss_sum"])
        zero_losses.append([lz_, lr_])
        max_rel = max(max_rel, abs(lz_ - lr_) / max(1e-12, abs(lr_)))
    opt_ratio = z_bytes["per_device_bytes"] / ref_bytes["per_device_bytes"]
    sharded_update = {
        "n_data": n_data,
        "losses_zero_vs_replicated": zero_losses,
        "max_rel_loss_diff": max_rel,
        "tolerance": ZERO_TOL,
        "optimizer_bytes_replicated_per_device": ref_bytes[
            "per_device_bytes"
        ],
        "optimizer_bytes_zero_per_device": z_bytes["per_device_bytes"],
        "optimizer_bytes_ratio": round(opt_ratio, 4),
    }

    # -- gates 2+3: service MPMD placement + schedule model ---------
    train = synthetic_mnist(rows, seed=0)
    cfg_dict = {
        "epochs": epochs,
        "batch_size": batch,
        "grad_accum": microbatches,
        "pipeline_stages": 2,
    }
    svc_dir = tempfile.mkdtemp(prefix="bench_pipeline_")
    client = SweepClient(svc_dir, tenant="whale")
    sid = client.submit(dict(cfg_dict), size=2)
    t0 = time.perf_counter()
    svc = SweepService(svc_dir, train_data=train, verbose=False)
    served = svc.serve(exit_when_drained=True, max_wall_s=600)
    service_wall = time.perf_counter() - t0
    placed = [
        json.loads(line)
        for line in open(os.path.join(svc_dir, "queue.jsonl"))
        if '"placed"' in line
    ]
    placed = [p for p in placed if p.get("event") == "placed"]
    blocks = placed[0].get("blocks") if placed else None
    disjoint = False
    if blocks and len(blocks) == 2:
        spans = [set(range(s, s + n)) for s, n in blocks]
        disjoint = not (spans[0] & spans[1]) and all(
            len(sp) == 2 for sp in spans
        )
    tid = placed[0]["trial_id"] if placed else None
    sched_books = None
    if tid is not None:
        books_path = os.path.join(
            svc_dir, f"trial-{tid}", PIPELINE_BOOKS_NAME
        )
        if os.path.exists(books_path):
            sched_books = json.load(open(books_path))["schedule"]
    bubble_ok = False
    if sched_books and sched_books.get("measured_bubble") is not None:
        analytic = sched_books["analytic_bubble"]
        bubble_ok = (
            abs(sched_books["measured_bubble"] - analytic)
            <= 0.10 * analytic
        )

    # -- stage parity: the same pipelined mechanism (direct runner,
    # same data stream) against the single-mesh reference step -------
    groups = setup_groups(4)  # 4 x 2 devices
    cfg = TrialConfig(trial_id=0, **cfg_dict)
    par_dir = tempfile.mkdtemp(prefix="bench_pipeline_parity_")
    t0 = time.perf_counter()
    pres = run_pipeline_trial(
        cfg, train, stage_meshes=[groups[0], groups[1]],
        out_dir=par_dir, save_checkpoint=False,
    )
    pipeline_wall = time.perf_counter() - t0
    stage_fns, last_fn, _ = make_vae_stage_fns(model, cfg.beta)
    ref_mesh = groups[2]
    rstate = ref_mesh.device_put(
        build_train_state(model, tx, jax.random.key(cfg.seed))
    )
    rstep = make_mpmd_reference_step(
        ref_mesh, stage_fns, last_fn, tx, microbatches=microbatches
    )
    it = TrialDataIterator(train, ref_mesh, batch, seed=cfg.seed)
    rkey = jax.random.key(cfg.seed + 1)
    step_no = 0
    ref_history = []
    t0 = time.perf_counter()
    for epoch in range(1, epochs + 1):
        sum_dev = None
        for b in it.epoch(epoch):
            r = jax.random.fold_in(rkey, step_no)
            rstate, m = rstep(rstate, b, r)
            step_no += 1
            sum_dev = (
                m["loss_sum"] if sum_dev is None else sum_dev + m["loss_sum"]
            )
        ref_history.append(float(sum_dev) / it.samples_per_epoch)
    reference_wall = time.perf_counter() - t0
    parity_rel = max(
        abs(h["avg_train_loss"] - r) / max(1e-12, abs(r))
        for h, r in zip(pres.history, ref_history)
    )

    gates = {
        "sharded_update_loss_parity": max_rel <= ZERO_TOL,
        "optimizer_bytes_within_1_over_n": (
            z_bytes["per_device_bytes"]
            <= ref_bytes["per_device_bytes"] / n_data * EPS
        ),
        "service_vector_all_or_nothing": bool(
            placed
            and served["settled"].get(sid) == "completed"
            and disjoint
        ),
        "bubble_within_10pct_of_analytic": bubble_ok,
        "stage_parity_vs_single_mesh": parity_rel <= ZERO_TOL,
    }
    return {
        "protocol": {
            "rows": rows,
            "batch": batch,
            "epochs": epochs,
            "stages": 2,
            "microbatches": microbatches,
            "zero_tolerance": ZERO_TOL,
        },
        "sharded_update": sharded_update,
        "service": {
            "submission": sid,
            "settled": served["settled"],
            "placed_blocks": blocks,
            "wall_s": round(service_wall, 3),
        },
        "schedule": sched_books,
        "stage_parity": {
            "pipeline_history": [
                h["avg_train_loss"] for h in pres.history
            ],
            "reference_history": ref_history,
            "max_rel_diff": parity_rel,
            "pipeline_wall_s": round(pipeline_wall, 3),
            "reference_wall_s": round(reference_wall, 3),
            "pipeline_optimizer_state_bytes": pres.optimizer_state_bytes,
        },
        "gates": gates,
        # Standing caveat: CPU fallback time-shares one host — bubble
        # here is a SCHEDULE measurement; wall-clock overlap and MFU
        # need the real-TPU run (device books carry null-with-reason).
        "mfu": None,
        "mfu_reason": (
            "CPU fallback: no peak FLOP/s table; the pipeline's device "
            "cost books land per-trial via record_pipeline_cost and "
            "print MFU on a TPU backend"
        ),
    }


def bench_telemetry_overhead() -> dict:
    """Step-time overhead of the telemetry seams, ON vs OFF.

    The subject is the stacked K=4 flagship dispatch loop carrying
    EXACTLY the instrumentation the HPO driver threads per dispatch
    (``metrics.step_mark`` with the bucket key, lane count, and the
    sparse device-sample seam) — the hot-path cost the <= 2% budget
    (docs/OBSERVABILITY.md) bounds. Passes alternate OFF/ON so machine
    drift lands on both sides; each side reports its MIN-of-passes
    (the low-noise estimator of true cost — a CPU fallback's run-to-run
    variance would otherwise swamp a single-digit-percent comparison),
    plus a microbenched per-mark cost for scale.
    """
    from multidisttorch_tpu import telemetry
    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import (
        TrialHypers,
        create_stacked_train_state,
        make_stacked_train_step,
    )

    k = 4
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    model = VAE(hidden_dim=HIDDEN, latent_dim=LATENT, dtype=dtype)
    (g,) = setup_groups(1)
    step = make_stacked_train_step(g, model)
    state = create_stacked_train_state(g, model, list(range(k)))
    base_rngs = jnp.stack([jax.random.key(s + 1) for s in range(k)])
    hypers = TrialHypers.stack([1e-3] * k, [1.0] * k)
    batch = jax.jit(
        lambda key: jax.random.uniform(key, (k, BATCH, 784), jnp.float32),
        out_shardings=g.sharding(None, "data"),
    )(jax.random.key(0))
    lane_steps = [
        jnp.full((k,), i, jnp.int32) for i in range(STACKED_MEASURE_STEPS)
    ]
    state, _ = step(state, hypers, batch, base_rngs, lane_steps[0])
    jax.block_until_ready(state.params)

    from multidisttorch_tpu.telemetry import trace as ttrace

    # The ON side now also carries submission TRACING (ISSUE 14): the
    # service's per-dispatch seam installs/clears the prebuilt trace
    # attribution around every cooperative step (service/runtime.py
    # _step_actives), so the <=2% budget covers it too.
    trace_attr = ttrace.make_attribution(
        [(i, f"bench-trace-{i}") for i in range(k)]
    )

    def timed_pass(reg, mon) -> float:
        nonlocal state
        t0 = time.perf_counter()
        for i in range(STACKED_MEASURE_STEPS):
            if reg is not None:
                ttrace.set_attribution(trace_attr)
            state, m = step(state, hypers, batch, base_rngs, lane_steps[i])
            if reg is not None:
                # EXACTLY the driver's per-dispatch seam, device books
                # included: the mark plus the straggler detector's
                # observe (hpo/driver.py's _device_seam) — the <=2%
                # budget now covers the anomaly layer too.
                dt = reg.step_mark("bucket-g0", m["loss_sum"], lanes=k)
                if mon is not None and dt is not None:
                    mon.observe_step("bucket-g0", dt)
                ttrace.set_attribution(None)
        jax.block_until_ready(state.params)
        return (time.perf_counter() - t0) / STACKED_MEASURE_STEPS

    off_times, on_times = [], []
    # host/world tags on the bus: the ON side now carries the FLEET
    # identity stamping (ISSUE 6) too, so the <=2% gate covers it —
    # an elastic worker's bus is always tagged.
    with telemetry.telemetry_run(None, host=0, world=0):
        reg = telemetry.get_registry()
        mon = telemetry.get_monitor()
        for p in range(TELEMETRY_AB_PASSES):
            if p % 2 == 0:
                off_times.append(timed_pass(None, None))
            else:
                on_times.append(timed_pass(reg, mon))
        # Per-mark microbench: the emit seam's cost in isolation
        # (mark + anomaly observe, the full per-dispatch hot path).
        n = 10000
        t0 = time.perf_counter()
        for _ in range(n):
            dt = reg.step_mark("microbench", None, lanes=k)
            if mon is not None and dt is not None:
                mon.observe_step("microbench", dt)
        per_mark_us = (time.perf_counter() - t0) / n * 1e6
        # Per-EMIT microbench, tagged vs untagged bus (in-memory ring,
        # no sink): the incremental cost of the fleet identity stamp
        # at the event seam, for scale. Events fire at boundaries (not
        # per dispatch), so this is bookkeeping, not a hot-path term.
        from multidisttorch_tpu.telemetry.events import Bus

        per_emit_us = {}
        for label, bus_kw in (
            ("untagged", {}),
            ("tagged", {"host": 0, "world": 0}),
        ):
            b = Bus(path=None, queue_max=256, **bus_kw)
            for i in range(1000):  # warm the ring/allocator first
                b.emit("epoch", trial_id=1, step=i)
            t0 = time.perf_counter()
            for i in range(n):
                b.emit("epoch", trial_id=1, step=i)
            per_emit_us[label] = round(
                (time.perf_counter() - t0) / n * 1e6, 3
            )
            b.close()
    off_s, on_s = min(off_times), min(on_times)
    overhead = on_s / off_s - 1.0
    return {
        "k": k,
        "measure_steps": STACKED_MEASURE_STEPS,
        "passes_each": TELEMETRY_AB_PASSES // 2,
        "off_step_time_s": round(off_s, 8),
        "on_step_time_s": round(on_s, 8),
        "off_pass_step_times_s": [round(t, 8) for t in off_times],
        "on_pass_step_times_s": [round(t, 8) for t in on_times],
        "overhead_frac": round(overhead, 5),
        "within_2pct": bool(overhead <= 0.02),
        "per_mark_cost_us": round(per_mark_us, 3),
        "fleet_tags": {"host": 0, "world": 0},
        "per_emit_cost_us": per_emit_us,
        # ISSUE 14: the ON side runs with submission-trace attribution
        # installed/cleared per dispatch (the service's seam), so the
        # standing <=2% bound covers tracing ON.
        "tracing_on": True,
        # ISSUE 18: telemetry_run arms the control-plane profiler too
        # (telemetry.configure -> ctlprof.configure), so the measured
        # window holds the <=2% budget with ctlprof ARMED — its seams
        # live in the scheduler, not this dispatch loop, and the
        # zero-cost-off contract keeps the OFF side clean.
        "ctlprof_on": True,
        # ISSUE 19: telemetry.configure arms the incident plane too —
        # every ON-side emit feeds the flight ring and the root-cause
        # detector's tap — so the <=2% budget now covers the black-box
        # recorder ARMED. The OFF side still constructs nothing.
        "flight_ring_on": True,
        "aggregation": "min-of-passes, OFF/ON interleaved",
    }


# LM bench shape: sized so one TPU v5e chip (16 GB HBM) is comfortably
# matmul-dominated — the MFU story the tiny flagship VAE cannot tell
# (its 784x400 matmuls are dispatch/bandwidth-bound by construction).
# LM_STEPS optimizer updates run as ONE scan-fused dispatch
# (make_lm_multi_step): at ~1 ms of device time per step on a v5e, a
# step-per-dispatch loop would time the host, not the MXU
# (docs/DISPATCH.md).
LM_VOCAB, LM_DMODEL, LM_HEADS, LM_LAYERS = 32768, 512, 8, 8
LM_SEQ, LM_BATCH, LM_STEPS = 512, 16, 40


def _lm_train_flops_per_token(
    d: int | None = None, layers: int | None = None, t: int | None = None,
    vocab: int | None = None,
) -> float:
    """Analytic matmul FLOPs for one LM optimizer step, per token.

    Forward per token: 24·d² per layer (q,k,v,out projections = 8·d²
    FLOPs, MLP up+down at 4x width = 16·d²) + causal attention
    2·T·d (QKᵀ + AV at 4·T·d, halved by the causal mask) + the
    d·vocab head (2·d·V). Train ≈ 3x forward (same dense-stack
    argument as :func:`_train_flops_per_sample`); embedding lookups
    are gathers, not FLOPs. Defaults resolve to the LM_* module
    globals at CALL time (None sentinels, not def-time binding), so a
    shrunk configuration always gets a consistent figure.
    """
    d = LM_DMODEL if d is None else d
    layers = LM_LAYERS if layers is None else layers
    t = LM_SEQ if t is None else t
    vocab = LM_VOCAB if vocab is None else vocab
    fwd = layers * (24.0 * d * d + 2.0 * t * d) + 2.0 * d * vocab
    return 3.0 * fwd


PBT_BENCH_POPULATION = 4
PBT_BENCH_GENERATIONS = 4
PBT_BENCH_STEPS_PER_GEN = 10
PBT_BENCH_BATCH = 64


def bench_pbt() -> dict:
    """Fused-lane vs per-submesh PBT A/B on the VAE workload.

    The artifact the fused population mode is judged by (ISSUE 8
    acceptance): the SAME population — same seeds, same data streams,
    same explore draws (the docs/PBT.md seeding contract) — run once as
    K members on K submeshes with host-side exploit/explore
    (``run_pbt(fused=False)``) and once as K lanes of one fused
    generation program (``fused=True``) on a submesh of the SAME shape
    (group 0 of the same carving, so the two legs' programs are
    bit-comparable). Banks dispatches/generation and wall-clock/
    generation for both legs, the headline dispatch-reduction ratio
    (floor: >= 3x at K=4), bit-parity of the whole population
    trajectory (per-generation loss sums, ranking, exploit edges, AND
    final member states — stronger than the best-member floor the
    acceptance names), and the compile-registry evidence that the
    ``pbt_gen`` program compiled ONCE with a cache_hit on every later
    generation. Wall-clock ratios are recorded, not gated: virtual CPU
    devices time-share host cores (same caveat as --stacked).
    """
    import tempfile

    from multidisttorch_tpu import telemetry as _telemetry
    from multidisttorch_tpu.compile.registry import get_executable_registry
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.hpo.pbt import PBTConfig, run_pbt
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.telemetry.events import EVENTS_NAME, read_events
    from multidisttorch_tpu.telemetry.export import SweepFold

    cfg = PBTConfig(
        population=PBT_BENCH_POPULATION,
        generations=PBT_BENCH_GENERATIONS,
        steps_per_generation=PBT_BENCH_STEPS_PER_GEN,
        batch_size=PBT_BENCH_BATCH,
        hidden_dim=HIDDEN,
        latent_dim=LATENT,
        exploit_fraction=0.5,
        lr_min=1e-4,
        lr_max=1e-1,
        seed=0,
    )
    train = synthetic_mnist(4096, seed=0)
    # Eval set = one batch (E=1): the per-submesh leg's eval is then K
    # dispatches/generation, the honest minimum — the fused leg folds
    # even that into its one dispatch.
    evals = synthetic_mnist(cfg.batch_size, seed=1)
    groups = setup_groups(cfg.population)

    ref = run_pbt(
        cfg, train, evals, groups=groups, verbose=False,
        return_states=True,
    )
    tel_dir = tempfile.mkdtemp(prefix="bench_pbt_tel_")
    with _telemetry.telemetry_run(tel_dir):
        fus = run_pbt(
            cfg, train, evals, groups=[groups[0]], fused=True,
            verbose=False, return_states=True,
        )
        events = read_events(os.path.join(tel_dir, EVENTS_NAME))
    fold = SweepFold()
    for ev in events:
        fold.feed(ev)

    # --- bit-parity of the population trajectory across the two legs
    mismatches = []
    for g in range(cfg.generations):
        r, f = ref.history[g], fus.history[g]
        for field in ("loss_sums", "order", "exploits"):
            if r[field] != f[field]:
                mismatches.append(
                    {"generation": g, "field": field,
                     "submesh": r[field], "fused": f[field]}
                )
    best_trajectory = [
        {"generation": g, "best": h["order"][0],
         "best_loss_sum": h["loss_sums"][h["order"][0]]}
        for g, h in enumerate(ref.history)
    ]
    states_equal = True
    for k in range(cfg.population):
        for a, b in zip(
            jax.tree.leaves(ref.final_states[k]),
            jax.tree.leaves(fus.final_states[k]),
        ):
            if not np.array_equal(
                np.asarray(a), np.asarray(b), equal_nan=True
            ):
                states_equal = False
                mismatches.append({"member": k, "field": "final_state"})
                break
    parity = not mismatches

    # --- compile-registry evidence: the pbt_gen program is in the
    # per-program table with ONE compile and a cache_hit per later
    # generation (the process-lifetime registry, PR 7).
    snap = get_executable_registry().snapshot()
    pbt_programs = {
        label: v for label, v in snap.items()
        if label.startswith("pbt_gen")
    }
    registry_ok = any(
        v["status"] == "ready" and v["hits"] >= cfg.generations - 1
        for v in pbt_programs.values()
    )
    compiles_ok = all(
        b["compiles"] == 1
        for p, b in fold.compile_books.items()
        if p.startswith("pbt_gen")
    ) and any(p.startswith("pbt_gen") for p in fold.compile_books)

    ref_dpg = ref.dispatch_book["dispatches_per_generation"]
    fus_dpg = fus.dispatch_book["dispatches_per_generation"]
    gens = max(1, cfg.generations)
    return {
        "config": {
            "population": cfg.population,
            "generations": cfg.generations,
            "steps_per_generation": cfg.steps_per_generation,
            "batch_size": cfg.batch_size,
            "hidden_dim": cfg.hidden_dim,
            "latent_dim": cfg.latent_dim,
            "exploit_fraction": cfg.exploit_fraction,
            "eval_batches": 1,
            "submesh_devices": groups[0].size,
        },
        "submesh": {
            "dispatch_book": ref.dispatch_book,
            "wall_s": round(ref.wall_s, 3),
            "wall_s_per_generation": round(ref.wall_s / gens, 3),
        },
        "fused": {
            "dispatch_book": fus.dispatch_book,
            "wall_s": round(fus.wall_s, 3),
            "wall_s_per_generation": round(fus.wall_s / gens, 3),
        },
        # the headline: K train + K eval dispatches + per-exploit host
        # round-trips per generation, collapsed into one dispatch
        "dispatch_reduction": round(ref_dpg / fus_dpg, 3),
        "wall_ratio_submesh_over_fused": (
            round(ref.wall_s / fus.wall_s, 3) if fus.wall_s else None
        ),
        "parity": parity,
        "parity_mismatches": mismatches[:10],
        "final_states_bit_identical": states_equal,
        "best_member_trajectory": best_trajectory,
        "exploits_total": sum(
            len(h["exploits"]) for h in ref.history
        ),
        "compile_registry": {
            "programs": pbt_programs,
            "one_compile_cache_hit_gen2plus": registry_ok,
            "compile_books_one_compile": compiles_ok,
        },
        "population_view": fold.pbt,
    }


def bench_lm() -> dict:
    """Transformer-LM training throughput + MFU on one chip.

    The flagship VAE matches the reference workload but its matmuls are
    too small to exercise the MXU; this is the framework's own
    MXU-bound headline (the TransformerLM that also drives the
    ring-attention long-context path). bf16 compute, f32 params, plain
    single-submesh training, median of MEASURE_REPEATS timed passes.
    On TPU, both attention paths are timed — XLA's dense softmax vs the
    Pallas flash kernel (ops/pallas_attention.py) — and the headline is
    the winner; the per-variant rates stay in the artifact as the
    kernel's keep-or-cut decision data.
    """
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.ops.pallas_attention import make_flash_attention
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.lm import (
        create_lm_state,
        lm_chunk_sharding,
        make_lm_multi_step,
    )

    (trial,) = setup_groups(1)
    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if on_tpu else jnp.float32
    tx = optax.adam(1e-3)
    # (LM_STEPS, B, T) stacked chunk, batch-sharded on dim 1 — one
    # scan-fused dispatch per timed pass.
    chunks = jax.device_put(
        jnp.asarray(
            np.random.default_rng(0).integers(
                0, LM_VOCAB, (LM_STEPS, LM_BATCH, LM_SEQ), dtype=np.int32
            )
        ),
        lm_chunk_sharding(trial),
    )

    def timed(attention) -> tuple[float, list, float, dict]:
        model = TransformerLM(
            vocab_size=LM_VOCAB, d_model=LM_DMODEL, num_heads=LM_HEADS,
            num_layers=LM_LAYERS, max_len=LM_SEQ, dtype=dtype,
            attention=attention,
        )
        state = create_lm_state(
            trial, model, tx, jax.random.key(0), example_len=LM_SEQ
        )
        multi = make_lm_multi_step(trial, model, tx)
        state, _ = multi(state, chunks)  # compile + warmup
        jax.block_until_ready(state.params)
        rates = []
        for _ in range(MEASURE_REPEATS):
            t0 = time.perf_counter()
            state, metrics = multi(state, chunks)
            jax.block_until_ready(state.params)
            rates.append(
                LM_STEPS * LM_BATCH * LM_SEQ / (time.perf_counter() - t0)
            )
        # MFU cross-check: XLA's own cost analysis of the program just
        # timed, vs the analytic per-token estimate the MFU line uses.
        agreement = _flops_agreement(
            _lm_train_flops_per_token(), multi, (state, chunks),
            LM_STEPS * LM_BATCH * LM_SEQ, devices=trial.size,
        )
        return (
            float(np.median(rates)), rates, float(metrics["loss"][-1]),
            agreement,
        )

    variants = {"dense_xla": timed(None)}
    flash_error = None
    if on_tpu:  # interpret-mode flash timings are meaningless off-TPU
        try:
            variants["flash_pallas"] = timed(make_flash_attention(causal=True))
        except Exception as e:
            # A kernel failure must not discard the dense result already
            # banked in this one-shot chip window (the round-4 ELBO
            # kernel failed exactly this way on its first hardware run).
            flash_error = repr(e)[:300]
    winner = max(variants, key=lambda k: variants[k][0])
    tok_s, rates, final_loss, flops_agreement = variants[winner]

    ndev = len(jax.devices())
    flops = _lm_train_flops_per_token()
    d0 = jax.devices()[0]
    peak = _peak_flops_per_chip(d0.device_kind) if on_tpu else None
    return {
        "tokens_per_sec_per_chip": round(tok_s / ndev, 1),
        "attention_winner": winner,
        "variants": {
            **{
                k: {"tokens_per_sec": round(v[0], 1),
                    "pass_rates": [round(r, 1) for r in v[1]]}
                for k, v in variants.items()
            },
            **({"flash_pallas": {"error": flash_error}}
               if flash_error else {}),
        },
        "train_flops_per_token": flops,
        # Analytic-vs-cost_analysis agreement for the winner's program
        # (unit: FLOPs per token): >10% disagreement is flagged so the
        # MFU line below is auditable, not trust-me arithmetic.
        "flops_agreement": flops_agreement,
        "mfu": round(tok_s / ndev * flops / peak, 5) if peak else None,
        "config": {
            "vocab": LM_VOCAB, "d_model": LM_DMODEL, "heads": LM_HEADS,
            "layers": LM_LAYERS, "seq_len": LM_SEQ, "batch": LM_BATCH,
        },
        "final_loss": final_loss,
    }


def bench_decode() -> dict:
    """KV-cached generation throughput (the serving-side metric).

    The sampler runs prefill + generation in one jitted program, so a
    raw end-to-end timing would mix the compute-bound prefill into the
    bandwidth-bound decode number. Two timed configurations isolate
    it: a full pass (prompt T/2) and a prefill-dominated pass (prompt
    T-1, one generated token); the difference in time over the
    difference in generated tokens is the per-token decode rate —
    which tracks HBM bandwidth (each token touches the whole cache +
    weights once), not MXU peak.
    """
    from multidisttorch_tpu.models.transformer import TransformerLM
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.lm import create_lm_state
    from multidisttorch_tpu.train.lm_decode import make_cached_lm_sample
    from multidisttorch_tpu.train.lm_quant import quantize_lm_params

    (trial,) = setup_groups(1)
    model = TransformerLM(
        vocab_size=LM_VOCAB, d_model=LM_DMODEL, num_heads=LM_HEADS,
        num_layers=LM_LAYERS, max_len=LM_SEQ,
    )
    state = create_lm_state(
        trial, model, optax.adam(1e-3), jax.random.key(0),
        example_len=LM_SEQ,
    )
    fn = make_cached_lm_sample(trial, model)
    prompt_len = LM_SEQ // 2
    buf = jax.device_put(
        jnp.asarray(
            np.random.default_rng(0).integers(
                0, LM_VOCAB, (LM_BATCH, LM_SEQ), dtype=np.int32
            )
        ),
        trial.batch_sharding,
    )
    gen_full = LM_BATCH * (LM_SEQ - prompt_len)
    gen_pre = LM_BATCH * 1  # prompt T-1: prefill + one generated token
    ndev = len(jax.devices())

    def decode_rate(st) -> float | None:
        out = fn(st, buf, prompt_len, jax.random.key(1))  # compile
        jax.block_until_ready(out)

        def timed(plen: int) -> float:
            t0 = time.perf_counter()
            o = fn(st, buf, plen, jax.random.key(2))
            jax.block_until_ready(o)
            return time.perf_counter() - t0

        rates = []
        for _ in range(MEASURE_REPEATS):
            dt = timed(prompt_len) - timed(LM_SEQ - 1)
            if dt > 0:
                rates.append((gen_full - gen_pre) / dt)
        return float(np.median(rates)) / ndev if rates else None

    f32_rate = decode_rate(state)
    int8_rate = decode_rate(
        state.replace(params=quantize_lm_params(state.params))
    )
    measured = {
        k: v for k, v in (("f32", f32_rate), ("int8", int8_rate))
        if v is not None
    }
    if not measured:  # prefill noise swamped both decode deltas
        return {"error": "decode delta not measurable (timing noise)"}
    winner = max(measured, key=measured.get)
    return {
        "decode_tokens_per_sec_per_chip": round(measured[winner], 1),
        "weights_winner": winner,
        "variants": {
            "f32": round(f32_rate, 1) if f32_rate is not None else None,
            "int8": round(int8_rate, 1) if int8_rate is not None else None,
        },
        "generated_per_pass": gen_full,
        "prompt_len": prompt_len,
        "config": {
            "vocab": LM_VOCAB, "d_model": LM_DMODEL, "heads": LM_HEADS,
            "layers": LM_LAYERS, "seq_len": LM_SEQ, "batch": LM_BATCH,
        },
    }


def bench_kernel_smoke() -> dict:
    """Per-kernel, per-dtype compiled pass/fail for the Pallas set.

    VERDICT r4 item 3: interpret-mode tests cannot catch Mosaic dtype
    rules (the round-4 bf16 ELBO store failure class), so the banked
    suite artifact must itself prove each shipped kernel compiles and
    matches its XLA reference on the hardware it ran on. Tiny shapes,
    fwd AND bwd, f32 AND bf16 — run FIRST in the suite so a kernel
    regression is recorded even if a later timing section crashes.
    Off-TPU this still runs (interpret mode, semantics only); the
    ``platform`` field says which kind of proof the artifact carries.
    """
    from multidisttorch_tpu.ops.losses import elbo_loss_sum
    from multidisttorch_tpu.ops.pallas_attention import flash_attention
    from multidisttorch_tpu.ops.pallas_elbo import fused_elbo_loss_sum
    from multidisttorch_tpu.ops.ring_attention import dense_attention_reference

    out = {"platform": jax.default_backend()}
    rng = np.random.default_rng(0)

    def check(name, fn):
        t0 = time.perf_counter()
        try:
            fn()
            out[name] = {"ok": True}
        except Exception as e:
            out[name] = {"ok": False, "error": repr(e)[:300]}
        out[name]["wall_s"] = round(time.perf_counter() - t0, 1)

    def rel_close(got, want, tol):
        got, want = np.asarray(got, np.float64), np.asarray(want, np.float64)
        denom = max(float(np.max(np.abs(want))), 1e-6)
        err = float(np.max(np.abs(got - want))) / denom
        if not err <= tol:  # explicit raise: `assert` dies under -O and
            # would bank a false hardware proof (NaN err also lands here)
            raise ValueError(f"kernel mismatch: rel err {err:.3e} > {tol}")

    def flash_case(dt, tol, shape=(1, 256, 2, 64)):
        # Default shape: T=256 → the tiled 128-block grid path, fwd and
        # bwd. One body serves every flash smoke variant.
        q, k, v = (
            jnp.asarray(rng.normal(size=shape), dt) for _ in range(3)
        )

        def run(attn):
            f = lambda q, k, v: jnp.sum(
                attn(q, k, v, causal=True).astype(jnp.float32) ** 2
            )
            return jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))(q, k, v)

        (got, g_got), (want, g_want) = run(flash_attention), run(
            dense_attention_reference
        )
        rel_close(got, want, tol)
        for a, b in zip(g_got, g_want):
            rel_close(a.astype(jnp.float32), b.astype(jnp.float32), tol)

    for dt_name, dt in (("f32", jnp.float32), ("bf16", jnp.bfloat16)):
        # bf16 operands round at ~2^-8; sums over hundreds of terms in a
        # shared-f32 accumulation still differ per-path at that scale.
        tol = 3e-2 if dt == jnp.bfloat16 else 2e-4

        def elbo_case(dt=dt, tol=tol):
            # batch 256 forces a multi-block grid under the shrunken
            # VMEM budget used in tests; here it just exercises the
            # production accumulation path (same 784/20 widths as the
            # flagship, targets f32 like the real train step feeds).
            logits = jnp.asarray(rng.normal(size=(256, 784)), dt)
            x = jnp.asarray(rng.uniform(size=(256, 784)), jnp.float32)
            mu = jnp.asarray(rng.normal(size=(256, 20)), dt)
            logvar = jnp.asarray(rng.normal(size=(256, 20)), dt)

            def run(loss_fn):
                f = lambda l, m, lv: loss_fn(l, x, m, lv, 1.0)
                return jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))(
                    logits, mu, logvar
                )

            (got, g_got), (want, g_want) = run(fused_elbo_loss_sum), run(
                elbo_loss_sum
            )
            rel_close(got, want, tol)
            for a, b in zip(g_got, g_want):
                rel_close(a.astype(jnp.float32), b.astype(jnp.float32), tol)

        check(f"fused_elbo_{dt_name}", elbo_case)

        check(f"flash_attention_{dt_name}", partial(flash_case, dt, tol))

    # The causal pad-to-tile path for large non-128-divisible T (new in
    # r5): T=1300 pads to 1408 and must stay exact against the dense
    # reference, fwd and bwd. f32 only — one compile's worth of
    # hardware proof for the pad path's grid shape.
    check(
        "flash_attention_pad_f32",
        partial(flash_case, jnp.float32, 2e-4, shape=(1, 1300, 1, 32)),
    )
    return out


def bench_suite(checkpoint=None) -> dict:
    """Every measurement in ONE process, for one-shot chip windows.

    The machine's chip is intermittently available and rapid back-to-back
    processes re-wedge it (round-4 finding), so the way to bank a full
    set of hardware numbers is a single process that captures everything
    while it holds the tunnel. Each sub-bench is independent: a failure
    records its error and the rest still run. ``checkpoint``, if given,
    is called with the partial results dict after EVERY section — a
    wedged tunnel hangs rather than raising, so sections already
    captured (kernel_smoke runs first for exactly this reason) must hit
    disk before a later section can block until the driver kills us.
    """
    on_tpu = jax.default_backend() == "tpu"
    out = {}
    for name, fn in (
        # Kernel pass/fail FIRST: cheapest section, and the one that
        # must survive even if a timing section wedges the tunnel.
        ("kernel_smoke", bench_kernel_smoke),
        ("flagship", bench_ours),
        # Interpret-mode Pallas timings are meaningless and very slow —
        # same off-TPU gate as the default mode's comparison.
        ("fused_loss_comparison", bench_fused_loss_comparison if on_tpu
         else (lambda: {"skipped": "interpret-mode timings meaningless"})),
        # Full-size LM on a CPU fallback is hours of wall-clock; the
        # suite must always finish inside the driver's budget.
        ("lm", bench_lm if on_tpu
         else (lambda: {"skipped": "full-size LM needs the TPU"})),
        ("decode", bench_decode if on_tpu
         else (lambda: {"skipped": "full-size decode needs the TPU"})),
        ("to_elbo_150", lambda: bench_to_elbo(150.0)),
        ("loader", bench_loader),
        # Trial-stacking artifact (ISSUE 1): K trials per dispatch vs
        # one — cheap on any backend, and the stacked mode's win must be
        # banked from real chips too when a window opens.
        ("stacked", bench_stacked),
    ):
        t0 = time.perf_counter()
        try:
            out[name] = fn()
        except Exception as e:  # record, keep banking the rest
            out[name] = {"error": repr(e)[:300]}
        out[name]["wall_s"] = round(time.perf_counter() - t0, 1)
        if checkpoint is not None:
            try:
                checkpoint(out)
            except OSError as e:  # never let banking kill the capture
                print(f"suite checkpoint failed: {e!r}", file=sys.stderr)
    return out


def bench_reference_torch() -> float:
    """The reference's train inner loop (vae-hpo.py:61-74) on torch CPU."""
    import torch
    import torch.nn.functional as F
    from torch import nn, optim

    torch.manual_seed(0)

    class VAE(nn.Module):
        # Architecture per /root/reference/vae-hpo.py:19-45.
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(784, HIDDEN)
            self.fc21 = nn.Linear(HIDDEN, LATENT)
            self.fc22 = nn.Linear(HIDDEN, LATENT)
            self.fc3 = nn.Linear(LATENT, HIDDEN)
            self.fc4 = nn.Linear(HIDDEN, 784)

        def forward(self, x):
            h = F.relu(self.fc1(x))
            mu, logvar = self.fc21(h), self.fc22(h)
            std = torch.exp(0.5 * logvar)
            z = mu + torch.randn_like(std) * std
            recon = torch.sigmoid(self.fc4(F.relu(self.fc3(z))))
            return recon, mu, logvar

    model = VAE()
    opt = optim.Adam(model.parameters(), lr=1e-3)
    data = torch.rand(BATCH, 784)

    def one_step():
        opt.zero_grad()
        recon, mu, logvar = model(data)
        bce = F.binary_cross_entropy(recon, data, reduction="sum")
        kld = -0.5 * torch.sum(1 + logvar - mu.pow(2) - logvar.exp())
        (bce + kld).backward()
        opt.step()

    for _ in range(3):
        one_step()
    t0 = time.perf_counter()
    for _ in range(TORCH_MEASURE_STEPS):
        one_step()
    dt = time.perf_counter() - t0
    return TORCH_MEASURE_STEPS * BATCH / dt


def bench_concurrency(num_trials: int) -> dict:
    """North-star metric (BASELINE.md): per-chip throughput of N
    concurrent trials, each on its own disjoint submesh, relative to one
    trial running alone on an identical submesh. Target: >= 0.90 at 8
    trials."""
    from multidisttorch_tpu.train.steps import create_train_state, make_multi_step

    groups, model, tx = _flagship_setup(num_trials)
    # Same TPU chunk sizing as the flagship timing (docs/DISPATCH.md):
    # 100-step chunks on real chips would make this measure the host
    # loop, not per-trial chip efficiency.
    chunk = _chunk_steps()
    key = jax.random.key(1)

    def setup_trial(g):
        state = create_train_state(g, model, tx, jax.random.key(g.group_id))
        step = make_multi_step(g, model, tx)
        # On-device generation straight into each trial's submesh
        # sharding (same no-tunnel-transfer rationale as _timed_chunks).
        batches = jax.jit(
            lambda k: jax.random.uniform(
                k, (chunk, BATCH, 784), jnp.float32
            ),
            out_shardings=g.sharding(None, "data"),
        )(jax.random.key(0))
        return {"state": state, "step": step, "batches": batches}

    trials = [setup_trial(g) for g in groups]

    def run_chunks(active, nchunks):
        # Interleaved async dispatch: each trial's chunks queue on its own
        # disjoint submesh; the host never blocks until the end.
        for i in range(nchunks):
            for t in active:
                t["state"], _ = t["step"](
                    t["state"], t["batches"], jax.random.fold_in(key, i)
                )
        for t in active:
            jax.block_until_ready(t["state"].params)

    # warmup all compilations
    run_chunks(trials, 1)

    # trial 0 alone on its submesh
    t0 = time.perf_counter()
    run_chunks(trials[:1], MEASURE_CHUNKS)
    alone_sps = (
        MEASURE_CHUNKS * chunk * BATCH / (time.perf_counter() - t0)
    )

    # all trials concurrently
    t0 = time.perf_counter()
    run_chunks(trials, MEASURE_CHUNKS)
    dt = time.perf_counter() - t0
    # each trial did MEASURE_CHUNKS * chunk steps
    per_trial_sps = MEASURE_CHUNKS * chunk * BATCH / dt

    ndev = len(jax.devices())
    out = {
        "num_trials": num_trials,
        "chunk_steps": chunk,  # measurement-shape provenance (r5)
        "alone_samples_per_sec": round(alone_sps, 1),
        "concurrent_per_trial_samples_per_sec": round(per_trial_sps, 1),
        "aggregate_samples_per_sec": round(per_trial_sps * num_trials, 1),
        "efficiency_vs_alone": round(per_trial_sps / alone_sps, 3),
        "n_devices": ndev,
        # The north-star config is 8 trials x >=1 chip each (BASELINE.md,
        # >=0.90 efficiency). Say in the artifact itself when this
        # environment cannot measure that for real (VERDICT r1 weak #8):
        # fewer devices than trials = time-slicing one chip; virtual CPU
        # devices = every "device" shares the same host cores, so
        # efficiency_vs_alone is a methodology proof, not a hardware
        # number.
        "hardware_limited": ndev < num_trials
        or jax.default_backend() == "cpu",
    }
    if jax.default_backend() == "cpu":
        out["methodology_note"] = (
            "virtual CPU devices share one host's cores; "
            "efficiency_vs_alone is not hardware-representative"
        )
    elif ndev < num_trials:
        out["methodology_note"] = (
            f"{num_trials} trials time-sliced over {ndev} real device(s); "
            "north-star needs >=1 chip per trial"
        )
    return out


def bench_loader(rows: int = 60000, dim: int = 784, batch: int = BATCH) -> dict:
    """Host batch-assembly throughput: C++ prefetching gatherer
    (csrc/fastloader.cpp) vs the equivalent pure-numpy gather.

    The data path is the host-side hot loop of every sweep (SURVEY §7
    "hard parts": contention is host-side). Two conditions:

    - ``bare``: fetch batches back to back. This measures raw copy
      speed, where numpy fancy-indexing usually WINS — the native
      gatherer pays an extra copy-out. Recorded because an honest
      artifact must show where the native path does not help.
    - ``interleaved``: a bandwidth-heavy numpy matmul between fetches.
      Deliberately adversarial to the prefetch thread (the matmul
      releases the GIL and saturates memory bandwidth) — kept in the
      artifact as the native path's worst case.
    - ``train_loop`` (the headline): the REAL consumer — a
      ``TrialDataIterator`` feeding scan-fused train dispatches — with
      the native gatherer on vs off. This is the condition the
      auto-enable default is judged by: device dispatch holds the GIL
      briefly and leaves bandwidth idle, which is exactly when the
      background gather pays."""
    from multidisttorch_tpu.data import native

    rng = np.random.default_rng(0)
    images = rng.uniform(0, 1, (rows, dim)).astype(np.float32)
    perm = rng.permutation(rows)
    n_batches = rows // batch
    work_a = rng.normal(size=(256, 256)).astype(np.float32)

    def work():
        return work_a @ work_a

    def timed(fetch, interleave: bool) -> float:
        t0 = time.perf_counter()
        for _ in range(n_batches):
            fetch()
            if interleave:
                work()
        return n_batches * batch / (time.perf_counter() - t0)

    def numpy_fetch(i=[0]):
        j = i[0] % n_batches
        i[0] += 1
        return images[perm[j * batch : (j + 1) * batch]]

    out = {
        "bare": {
            "numpy_samples_per_sec": round(timed(numpy_fetch, False), 1)
        },
        "interleaved": {
            "numpy_samples_per_sec": round(timed(numpy_fetch, True), 1)
        },
        "native_available": native.available(),
    }
    if native.available():
        g = native.NativeBatchGatherer(images)
        for cond, interleave in (("bare", False), ("interleaved", True)):
            n = g.start_epoch(perm, batch)  # warm epoch per condition
            for _ in range(n):
                g.next_batch()
            n = g.start_epoch(perm, batch)
            sps = timed(g.next_batch, interleave)
            out[cond]["native_samples_per_sec"] = round(sps, 1)
            out[cond]["native_vs_numpy"] = round(
                sps / out[cond]["numpy_samples_per_sec"], 3
            )
        g.close()

    # Real-consumer condition runs either way (python-only rate still
    # meaningful without the native library).
    out["train_loop"] = _loader_train_loop(
        rows, batch, with_native=native.available()
    )
    return out


def _loader_train_loop(rows: int, batch: int, *, with_native: bool) -> dict:
    """Real-consumer loader A/B: one epoch of scan-fused training fed by
    TrialDataIterator with the native gatherer off vs on."""
    from multidisttorch_tpu.data.datasets import synthetic_mnist
    from multidisttorch_tpu.data.sampler import TrialDataIterator
    from multidisttorch_tpu.train.steps import create_train_state, make_multi_step

    chunk = 10
    (trial,), model, tx = _flagship_setup(1)
    data = synthetic_mnist(rows, seed=0)
    key = jax.random.key(1)
    res = {}
    for use_native in (False, True) if with_native else (False,):
        it = TrialDataIterator(
            data, trial, batch, seed=0, use_native=use_native
        )
        state = create_train_state(trial, model, tx, jax.random.key(0))
        multi = make_multi_step(trial, model, tx)
        state, _ = multi(state, next(it.stream_chunks(chunk)), key)
        jax.block_until_ready(state.params)
        t0 = time.perf_counter()
        n = 0
        for i, item in enumerate(it.epoch_chunks(1, chunk)):
            if item[1].shape[0] != chunk:
                break
            state, _ = multi(state, item[1], jax.random.fold_in(key, i))
            n += chunk * batch
        jax.block_until_ready(state.params)
        label = "native" if use_native else "python"
        res[label + "_samples_per_sec"] = round(
            n / (time.perf_counter() - t0), 1
        )
    if "native_samples_per_sec" in res:
        res["native_vs_python"] = round(
            res["native_samples_per_sec"] / res["python_samples_per_sec"], 3
        )
    return res


def bench_to_elbo(target: float, max_steps: int = 20000) -> dict:
    """BASELINE.json's second metric: HPO wall-clock to target ELBO.

    Trains the flagship VAE (reference defaults: batch 128, Adam 1e-3)
    on MNIST-shaped data until the per-sample train ELBO drops below
    ``target``, using the production fused dispatch; loss is checked
    once per chunk (the logging cadence), so the measurement includes
    exactly the syncs a real sweep pays.
    """
    from multidisttorch_tpu.data.datasets import load_mnist
    from multidisttorch_tpu.data.sampler import TrialDataIterator
    from multidisttorch_tpu.train.steps import create_train_state, make_multi_step

    chunk = 20
    (trial,), model, tx = _flagship_setup(1)
    data = load_mnist(train=True)
    it = TrialDataIterator(data, trial, BATCH, seed=0)
    state = create_train_state(trial, model, tx, jax.random.key(0))
    multi = make_multi_step(trial, model, tx)
    key = jax.random.key(1)

    # Compile outside the timed region (the sweep's one-off cost).
    warm = next(it.stream_chunks(chunk))
    state, _ = multi(state, warm, key)
    jax.block_until_ready(state.params)
    state = create_train_state(trial, model, tx, jax.random.key(0))

    steps = 0
    t0 = time.perf_counter()
    for batches in it.stream_chunks(chunk):
        state, metrics = multi(state, batches, jax.random.fold_in(key, steps))
        steps += chunk
        last = float(metrics["loss_sum"][-1]) / BATCH
        if last <= target or steps >= max_steps:
            break
    wall = time.perf_counter() - t0
    return {
        "target_elbo": target,
        "reached": last <= target,
        "final_per_sample_elbo": round(last, 3),
        "steps": steps,
        "wall_s": round(wall, 3),
        "synthetic_data": bool(getattr(data, "synthetic", False)),
    }


def _flagship_cpu_history(pattern: str = "BENCH_r*.json") -> list[dict]:
    """Prior rounds' CPU-fallback flagship rates, each with the scan
    chunk it was measured at.

    The driver banks every round's bench stdout as ``BENCH_r{N}.json``
    with the output's LAST bytes in ``tail`` — which means old rounds
    parse as a clean JSON line while long-output rounds arrive
    front-truncated (r05). Two extraction paths, strictest first: parse
    a complete JSON line (platform must be cpu), else regex the flat
    ``flagship_passes`` object out of the truncated tail (guarded by
    the cpu device marker; the embedded stale-TPU payload carries no
    flagship_passes, so it cannot be mistaken for the headline).
    Rounds before the chunk-provenance field measured at the then-
    constant chunk 100.
    """
    import glob
    import re

    out = []
    for p in sorted(glob.glob(pattern)):
        try:
            with open(p) as f:
                tail = json.load(f).get("tail") or ""
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        rec = None
        for line in tail.strip().splitlines():
            if not line.startswith("{"):
                continue
            try:
                j = json.loads(line)
            except ValueError:
                continue
            det = j.get("detail") or {}
            if not isinstance(det, dict) or det.get("platform") != "cpu":
                continue
            fp = det.get("flagship_passes") or {}
            # Top-level `value` is only a flagship rate on the flagship
            # metric line — other modes (--stacked, --to-elbo) also
            # emit cpu-platform JSON whose value means something else
            # entirely and must not pollute the drift history.
            fallback = (
                j.get("value")
                if j.get("metric") == "vae_train_samples_per_sec_per_chip"
                else None
            )
            if not fp.get("samples_per_sec_per_chip") and fallback is None:
                continue
            rec = {
                "file": p,
                "samples_per_sec_per_chip": fp.get(
                    "samples_per_sec_per_chip", fallback
                ),
                "chunk_steps": fp.get("chunk_steps", 100),
            }
            break
        if rec is None and '"device_kind": "cpu"' in tail:
            m = re.search(r'"flagship_passes": ({[^{}]*})', tail)
            if m:
                try:
                    fp = json.loads(m.group(1))
                except ValueError:
                    fp = {}
                if fp.get("samples_per_sec_per_chip"):
                    rec = {
                        "file": p,
                        "samples_per_sec_per_chip": fp[
                            "samples_per_sec_per_chip"
                        ],
                        "chunk_steps": fp.get("chunk_steps", 100),
                    }
        if rec and rec["samples_per_sec_per_chip"]:
            out.append(rec)
    return out


def _drift_vs_prev_rounds(
    current: float, chunk_steps: int, history: list[dict]
) -> dict | None:
    """Cross-round drift check for the CPU-fallback flagship number.

    Same-shape comparisons only (prior rounds keyed by ``chunk_steps``
    — a chunk change IS a measurement change, not drift). Returns the
    ``vs_prev_rounds`` block for the artifact, with
    ``drift_exceeds_20pct`` set when the current rate moved more than
    20% off the prior-round median — the machine got slower/faster, or
    the program did, and either way the round's number shouldn't be
    read as comparable without this flag.
    """
    same = [h for h in history if h["chunk_steps"] == chunk_steps]
    if not same:
        return None
    prior = [float(h["samples_per_sec_per_chip"]) for h in same]
    med = float(np.median(prior))
    ratio = current / med if med > 0 else float("nan")
    return {
        "prior_rounds": same,
        "median_prior": round(med, 1),
        "ratio_to_median": round(ratio, 3),
        "drift_exceeds_20pct": bool(abs(ratio - 1.0) > 0.20),
    }


def _last_tpu_artifact() -> dict | None:
    """Newest banked real-TPU artifact, for embedding (marked stale) in
    a CPU-fallback headline.

    VERDICT r4 item 6: when the chip is wedged at the driver's capture
    time, ``BENCH_r{N}.json`` records a CPU number that reads as a
    ~570x regression unless the reader digs into ``artifacts/``. This
    surfaces the evidence in the round headline itself: the most recent
    ``artifacts/bench_tpu_*.json`` whose payload proves a real TPU run,
    with heavyweight triage stripped and provenance (file, mtime) kept.
    """
    import glob

    candidates = []
    for p in glob.glob("artifacts/bench_tpu_*.json"):
        if p.endswith("_latest.json"):
            continue  # mutable alias of a timestamped file — not provenance
        try:
            with open(p) as f:
                d = json.load(f)
            mt = os.path.getmtime(p)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        if not isinstance(d, dict):  # stray non-artifact JSON in the dir
            continue
        det = d.get("detail") if isinstance(d.get("detail"), dict) else {}
        back = det.get("backend") if isinstance(det.get("backend"), dict) else {}
        plat = det.get("platform") or back.get("platform")
        if plat != "tpu":
            continue
        # Rank healthy captures (non-null headline value) above degraded
        # ones — a newer run whose flagship section errored must not
        # shadow an older good number.
        candidates.append((d.get("value") is not None, mt, p, d))
    if not candidates:
        return None
    _, mt, p, d = max(candidates)
    det = d.get("detail")
    if isinstance(det, dict):  # triage blobs dwarf the numbers; drop them
        det = {k: v for k, v in det.items() if "triage" not in k}
        if isinstance(det.get("backend"), dict):
            det["backend"] = {
                k: v for k, v in det["backend"].items() if "triage" not in k
            }
        d = {**d, "detail": det}
    return {
        "stale": True,
        "file": p,
        "captured_utc": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(mt)
        ),
        "payload": d,
    }


def _embed_stale_tpu_evidence(target: dict, backend: dict) -> None:
    """On a CPU fallback (chip wedged at capture time), surface the most
    recent banked real-TPU artifact inside the emitted detail (VERDICT
    r4 item 6). One shared guard so the suite and default paths cannot
    drift."""
    if backend.get("platform") == "cpu" and "tpu_error" in backend:
        art = _last_tpu_artifact()
        if art:
            target["last_tpu_artifact"] = art


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--concurrency", type=int, default=None,
        help="measure N concurrent trials' per-chip efficiency instead of "
        "the default single-chip throughput metric",
    )
    parser.add_argument(
        "--to-elbo", type=float, default=None,
        help="measure wall-clock (s) until the per-sample train ELBO "
        "drops below this target (BASELINE.json's second metric)",
    )
    parser.add_argument(
        "--loader", action="store_true",
        help="measure host batch-assembly throughput: native C++ "
        "gatherer vs pure numpy",
    )
    parser.add_argument(
        "--lm", action="store_true",
        help="measure Transformer-LM training tokens/sec/chip + MFU "
        "(the MXU-bound headline the tiny VAE cannot provide)",
    )
    parser.add_argument(
        "--decode", action="store_true",
        help="measure KV-cached generation throughput "
        "(tokens/sec/chip — the bandwidth-bound serving metric)",
    )
    parser.add_argument(
        "--stacked", action="store_true",
        help="measure K stacked trials per dispatch (K in {1,2,4,8}): "
        "samples/sec/chip and dispatches per trial-step — the "
        "trial-stacking mode's banked evidence",
    )
    parser.add_argument(
        "--chaos", action="store_true",
        help="run the standard fault schedule (faults/harness.py) "
        "against run_hpo supervision: recovery of every injected infra "
        "fault, goodput (useful/executed steps), and bit-parity of "
        "recovered trials vs the fault-free sweep",
    )
    parser.add_argument(
        "--chaos-mh", action="store_true",
        help="run the ELASTIC multi-host chaos drill (CPU, 3 virtual "
        "hosts under tools/sweep_supervisor.py): kill one host "
        "mid-sweep, supervised world-shrink restart, ledger-driven "
        "trial migration, goodput + bit-parity of recovered trials "
        "(docs/RESILIENCE.md \"Elastic multi-host\")",
    )
    parser.add_argument(
        "--pbt", action="store_true",
        help="A/B fused-lane PBT (whole generation = one dispatch of "
        "the registered pbt_gen program) vs per-submesh PBT on the VAE "
        "workload: dispatches/generation, wall/generation, bit-parity "
        "of the population trajectory, and the compile-registry "
        "one-compile evidence (docs/PBT.md; banks "
        "artifacts/bench_pbt_*.json)",
    )
    parser.add_argument(
        "--coldstart", action="store_true",
        help="measure cold vs precompiled (AOT farm) vs cache-warm "
        "(quarantined persistent cache) trial-admission latency over a "
        "fixed multi-bucket sweep, with a bit-parity gate across all "
        "three paths (docs/COMPILE.md; banks "
        "artifacts/bench_coldstart_*.json)",
    )
    parser.add_argument(
        "--service", action="store_true",
        help="run the sweep-service acceptance drill (docs/SERVICE.md): "
        "a real daemon killed with SIGKILL mid-sweep and restarted with "
        "zero lost submissions, 2-tenant fair-share ratio within 10% of "
        "weights, queue-wait/placement-latency books, and a "
        "defragmentation event that demonstrably unblocks a starved "
        "large-shape trial (banks artifacts/bench_service_*.json)",
    )
    parser.add_argument(
        "--dataplane", action="store_true",
        help="measure the per-tenant data plane (docs/DATA.md): K=8 "
        "heterogeneous lanes (8 distinct datasets, one vmapped "
        "dispatch) with the pipelined sharded input path vs the "
        "synchronous reference — bytes/sec per host, input_bound_frac "
        "< 5% gate, fused-vs-per-lane bit parity, and co-packing "
        "across dataset boundaries (banks "
        "artifacts/bench_dataplane_*.json)",
    )
    parser.add_argument(
        "--pipeline", action="store_true",
        help="run the giant-model-trial drill (docs/PARALLEL.md): "
        "ZeRO sharded-update loss parity vs the replicated reference "
        "+ per-device optimizer bytes <= 1/n_data, a 2-stage MPMD "
        "pipelined trial placed by the service as an all-or-nothing "
        "vector of slice blocks, and measured bubble fraction within "
        "10% of the analytic (S-1)/(S-1+M) schedule model (banks "
        "artifacts/bench_pipeline_*.json)",
    )
    parser.add_argument(
        "--fabric", action="store_true",
        help="run the service-fabric acceptance drill (docs/SERVICE.md "
        "\"Service fabric\"): 2 replica daemons, one SIGKILLed with "
        "work outstanding — the survivor adopts the orphaned shard "
        "through a lease-fenced epoch claim with zero lost submissions "
        "and bit-identical re-homed trials; a deadline trial "
        "checkpoint-drain preempts best-effort lanes within the "
        "anti-thrash budget; and a 1M-submission discrete-event "
        "loadgen replay against the pure scheduler core (p99 "
        "placement latency, fairness <= 10%, deadline hit rate, "
        "churn; MDT_FABRIC_LOADGEN_N overrides the count); plus the "
        "elastic-topology drills (docs/SERVICE.md \"Shard "
        "topology\"): a shard_split_lost fault SIGKILLs the "
        "splitting replica BETWEEN split-handoff records and the "
        "adopter must close the seam zero-lost/no-double-own, "
        "stacked + pipelined placements evict-and-resume "
        "bit-identical, and the loadgen scenario zoo "
        "(coordinated_burst, split_storm; MDT_FABRIC_SCENARIO_N "
        "overrides) holds the elastic arm within 10% of static "
        "routing (banks artifacts/bench_fabric_*.json)",
    )
    parser.add_argument(
        "--ckpt", action="store_true",
        help="run the checkpoint data-plane drill (docs/RESILIENCE.md "
        "\"Checkpoint format v2\"): v1<->v2 bitwise restore parity "
        "across classic/stacked/ZeRO/pipelined trials, incremental "
        "delta ratio < 0.5x full-model bytes on a multi-epoch "
        "fine-tune cadence, and the snapshot-fast drain — victim "
        "slices freed without blocking on persist, ledger `preempted` "
        "only after the persist lands, RAM-snapshot re-place (banks "
        "artifacts/bench_ckpt_*.json)",
    )
    parser.add_argument(
        "--telemetry-ab", action="store_true",
        help="run ONLY the standing telemetry overhead A/B (the "
        "stacked K=4 dispatch loop, OFF vs ON with device books, "
        "anomaly observe, fleet tags AND submission-trace attribution "
        "on the ON side) and bank it — the observability CI job's "
        "<=2% gate (banks artifacts/bench_telemetry_ab_*.json)",
    )
    parser.add_argument(
        "--incidents", action="store_true",
        help="replay the incident-plane chaos drill (docs/INCIDENTS.md): "
        "one scenario per fault family — daemon loss, fence race, "
        "wedged collective, torn split, backend wedge, SLO burn, "
        "divergence storm, checkpoint rot, preemption, host loss, "
        "duplicate steal grant — each through its own telemetry scope, "
        "gated on a 100% fault->verdict confusion-matrix diagonal, a "
        "zero-false-positive no-fault soak, published flight-ring "
        "bundles, and the offline autopsy re-deriving the torn-split "
        "verdict; re-measures the standing <=2% telemetry A/B with the "
        "flight ring armed (banks artifacts/bench_incidents_*.json)",
    )
    parser.add_argument(
        "--zoo", action="store_true",
        help="run the loadgen scenario zoo (docs/OBSERVABILITY.md "
        "\"Control-plane books\"): every named scenario "
        "(diurnal_wave, tenant_burst, deadline_gaming, "
        "pipeline_whale_shrimp, dataset_thrash, coordinated_burst, "
        "split_storm) replayed through the production scheduler "
        "classes with the control-plane profiler armed — banks one "
        "artifact per scenario (SLO verdicts + per-phase flight "
        "books + throughput headline) as artifacts/zoo_<name>_*.json "
        "and folds each round into artifacts/ctlprof_ledger.jsonl "
        "with cross-round drift flags (MDT_ZOO_N overrides the "
        "per-scenario submission count)",
    )
    parser.add_argument(
        "--zoo-n", type=int, default=None,
        help="submissions per zoo scenario (overrides MDT_ZOO_N and "
        "the scenario defaults)",
    )
    parser.add_argument(
        "--suite", action="store_true",
        help="bank every measurement (flagship, fused-loss comparison, "
        "LM, to-elbo, loader) in one process — for one-shot windows on "
        "the intermittently-available chip",
    )
    args = parser.parse_args()

    if sum(x is not None and x is not False
           for x in (args.concurrency, args.to_elbo, args.loader,
                     args.lm, args.suite, args.decode, args.stacked,
                     args.chaos, args.chaos_mh, args.coldstart,
                     args.pbt, args.service, args.dataplane,
                     args.pipeline, args.fabric, args.ckpt,
                     args.telemetry_ab, args.zoo, args.incidents)) > 1:
        parser.error("--concurrency/--to-elbo/--loader/--lm/--decode/"
                     "--suite/--stacked/--chaos/--chaos-mh/--coldstart/"
                     "--pbt/--service/--dataplane/--pipeline/--fabric/"
                     "--ckpt/--telemetry-ab/--zoo/--incidents are "
                     "mutually exclusive")

    if (args.stacked or args.chaos or args.chaos_mh or args.pbt
            or args.service or args.dataplane or args.pipeline
            or args.fabric or args.ckpt or args.telemetry_ab
            or args.incidents) and \
            "xla_force_host_platform_device_count" not in (
        os.environ.get("XLA_FLAGS", "")
    ):
        # The stacked protocol measures PACKING — 8 pending trials at K
        # lanes per single-device group — so the CPU fallback needs
        # multiple virtual devices (the same harness topology as
        # bench --concurrency / docs/DISPATCH.md). XLA parses this flag
        # at backend init, not at import, so setting it here (before
        # _ensure_backend's first jax.devices()) is effective; it shapes
        # only the host-platform client, so a real TPU's device count
        # is untouched.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )

    # Every mode goes through the preflight first: the train_loop loader
    # condition (and all training modes) touch jax.devices(), which on a
    # wedged-TPU machine blocks forever without the probe + CPU fallback.
    backend = _ensure_backend()

    if backend.get("platform") == "cpu":
        # Persistent XLA compile cache for the CPU fallback (shared
        # policy + dir resolution: utils/compile_cache.py) — repeated
        # suite retries against the wedged chip shouldn't pay full CPU
        # compiles every hour. Deliberately NOT enabled on TPU: the
        # rare chip window gets the exact, known-good compile path.
        from multidisttorch_tpu.utils.compile_cache import (
            enable_persistent_compile_cache,
        )

        enable_persistent_compile_cache()

    if args.suite:
        # Chip windows are rare and close without warning, and a wedged
        # tunnel HANGS rather than raising — so on TPU the suite banks
        # its evidence incrementally after every section, to a unique
        # per-run filename (ADVICE r4: a later degraded run must never
        # clobber a previously banked good capture) plus a refreshed
        # _latest alias at the end. Best-effort throughout: the backup
        # path must never kill the primary stdout contract.
        bank_path = None
        if backend.get("platform") == "tpu":
            try:
                os.makedirs("artifacts", exist_ok=True)
                stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
                bank_path = f"artifacts/bench_tpu_suite_{stamp}.json"
            except OSError as e:
                print(f"artifact dir unavailable: {e!r}", file=sys.stderr)

        def payload_for(results: dict) -> dict:
            flagship = results.get("flagship", {})
            return {
                "metric": "vae_train_samples_per_sec_per_chip",
                "value": flagship.get("samples_per_sec_per_chip")
                if isinstance(flagship, dict) else None,
                "unit": "samples/sec/chip",
                "vs_baseline": None,
                "detail": {**results, "backend": backend},
            }

        def bank(payload: dict) -> None:
            # Atomic replace: an in-place "w" rewrite would truncate
            # the artifact first, so a mid-write kill (the driver's
            # timeout) or disk-full would destroy every previously
            # banked section — the exact loss the incremental
            # checkpointing exists to prevent.
            tmp = bank_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, bank_path)

        def checkpoint(partial: dict) -> None:
            if bank_path:  # marked partial until the final write lands
                bank({**payload_for(partial), "partial": True})

        r = bench_suite(checkpoint)
        _embed_stale_tpu_evidence(r, backend)
        payload = payload_for(r)
        print(json.dumps(payload))  # the primary contract, always first
        if bank_path:
            try:
                bank(payload)
                with open("artifacts/bench_tpu_suite_latest.json", "w") as f:
                    json.dump({**payload, "banked_as": bank_path}, f)
                print(f"banked TPU suite artifact: {bank_path}",
                      file=sys.stderr)
            except OSError as e:
                print(f"artifact banking failed: {e!r}", file=sys.stderr)
        return

    if args.lm:
        r = bench_lm()
        r.update(backend)
        print(
            json.dumps(
                {
                    "metric": "lm_train_tokens_per_sec_per_chip",
                    "value": r["tokens_per_sec_per_chip"],
                    "unit": "tokens/sec/chip",
                    "vs_baseline": None,
                    "mfu": r["mfu"],
                    "detail": r,
                }
            )
        )
        return

    if args.decode:
        r = bench_decode()
        r.update(backend)
        print(
            json.dumps(
                {
                    "metric": "lm_decode_tokens_per_sec_per_chip",
                    "value": r["decode_tokens_per_sec_per_chip"],
                    "unit": "tokens/sec/chip",
                    "vs_baseline": None,
                    "detail": r,
                }
            )
        )
        return

    if args.loader:
        r = bench_loader()
        r.update(backend)
        tl = r["train_loop"]
        # Headline is always a train-loop rate — python-path when the
        # native library is absent, never the bare memcpy number (three
        # orders of magnitude larger and not comparable).
        print(
            json.dumps(
                {
                    "metric": "loader_train_loop_throughput",
                    "value": tl.get(
                        "native_samples_per_sec",
                        tl["python_samples_per_sec"],
                    ),
                    "unit": "samples/sec",
                    "vs_baseline": tl.get("native_vs_python"),
                    "detail": r,
                }
            )
        )
        return

    if args.coldstart:
        import tempfile

        from multidisttorch_tpu.compile.coldstart import run_coldstart_bench

        r = run_coldstart_bench(tempfile.mkdtemp(prefix="bench_coldstart_"))
        r["backend"] = backend
        # Bank the artifact (ISSUE 7 acceptance): a timestamped file so
        # a later degraded run never clobbers banked evidence, plus a
        # _latest alias for the CI gate/console.
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_coldstart_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1)
            os.replace(tmp, banked)
            latest = "artifacts/bench_coldstart_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        print(
            json.dumps(
                {
                    "metric": "coldstart_admission_speedup_precompiled",
                    "value": r["speedup_cold_over_precompiled"],
                    "unit": "x (cold mean / precompiled mean)",
                    # acceptance floor: >= 2x on the multi-bucket sweep
                    "vs_baseline": (
                        round(r["speedup_cold_over_precompiled"] / 2.0, 3)
                        if r["speedup_cold_over_precompiled"] is not None
                        else None
                    ),
                    "parity": r["parity"],
                    "admission_blocked_on_compile": r[
                        "admission_blocked_on_compile"
                    ],
                    "cache_warm_below_precompiled": r[
                        "cache_warm_below_precompiled"
                    ],
                    "cache_verdict": r["cache_verdict"],
                    "passed": r["passed"],
                    "banked_as": banked,
                    "detail": r,
                }
            )
        )
        return

    if args.chaos:
        import tempfile

        from multidisttorch_tpu.faults.harness import run_chaos_bench

        # Telemetry lands in artifacts/ (not the throwaway work dir):
        # the Perfetto trace where every injected fault, retry, and
        # lane refill appears as a tagged event is part of the chaos
        # run's banked evidence (ISSUE 3 acceptance).
        tel_dir = os.path.join("artifacts", "chaos_telemetry")
        try:
            os.makedirs(tel_dir, exist_ok=True)
        except OSError:
            tel_dir = None  # harness falls back to the work dir
        r = run_chaos_bench(
            tempfile.mkdtemp(prefix="bench_chaos_"),
            telemetry_dir=tel_dir,
        )
        r["backend"] = backend
        tel = r.get("telemetry") or {}
        print(
            json.dumps(
                {
                    "metric": "chaos_goodput_useful_over_executed_steps",
                    "value": r["goodput"],
                    "unit": "fraction",
                    # acceptance floor: goodput >= 0.8 of fault-free
                    "vs_baseline": round(r["goodput"] / 0.8, 3),
                    "all_infra_faults_recovered": r[
                        "all_infra_faults_recovered"
                    ],
                    "final_metrics_bit_identical": r[
                        "final_metrics_bit_identical"
                    ],
                    "telemetry_trace": tel.get("trace"),
                    "all_faults_traced": tel.get("all_faults_traced"),
                    "detail": r,
                }
            )
        )
        return

    if args.chaos_mh:
        import tempfile

        from multidisttorch_tpu.faults.harness import run_chaos_mh_bench

        r = run_chaos_mh_bench(tempfile.mkdtemp(prefix="bench_chaos_mh_"))
        r["backend"] = backend
        fleet = r["fleet"]
        # The merged fleet artifacts land in artifacts/ (not the
        # throwaway work dir): the cross-host trace + summary ARE the
        # drill's banked evidence (ISSUE 6 acceptance), same policy as
        # --chaos's telemetry dir.
        bank_dir = os.path.join("artifacts", "chaos_mh_fleet")
        try:
            import shutil

            os.makedirs(bank_dir, exist_ok=True)
            banked = {}
            for key, src in fleet["paths"].items():
                if src and os.path.exists(src):
                    dst = os.path.join(bank_dir, os.path.basename(src))
                    shutil.copyfile(src, dst)
                    banked[key] = dst
            fleet["banked_paths"] = banked
        except OSError as e:
            fleet["banked_paths"] = {"error": repr(e)[:200]}
        print(
            json.dumps(
                {
                    "metric": "chaos_mh_goodput_useful_over_executed_steps",
                    "value": r["goodput"],
                    "unit": "fraction",
                    # acceptance floor: goodput >= 0.8 with 1-of-3
                    # hosts killed mid-sweep and the world re-formed
                    "vs_baseline": round(r["goodput"] / 0.8, 3),
                    "all_trials_settled": r["all_trials_settled"],
                    "recovered_bit_identical": r["recovered_bit_identical"],
                    "worlds_formed": r["worlds_formed"],
                    "hosts_lost": r["hosts_lost"],
                    # fleet observability gates (ISSUE 6): ONE merged
                    # skew-corrected timeline spanning every host and
                    # world, fired faults + the shrink present in it,
                    # and a non-null restart-tax breakdown
                    "all_hosts_traced": fleet["all_hosts_traced"],
                    "all_faults_traced": fleet["all_faults_traced"],
                    "restart_tax_nonnull": fleet["restart_tax_nonnull"],
                    "fleet_trace": fleet["banked_paths"].get(
                        "trace", fleet["paths"].get("trace")
                    ),
                    "fleet_summary": fleet["banked_paths"].get(
                        "summary", fleet["paths"].get("summary")
                    ),
                    "detail": r,
                }
            )
        )
        return

    if args.pipeline:
        r = bench_pipeline()
        r["backend"] = backend
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_pipeline_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1)
            os.replace(tmp, banked)
            latest = "artifacts/bench_pipeline_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        print(
            json.dumps(
                {
                    "metric": "pipeline_measured_bubble_fraction",
                    "value": (
                        r["schedule"]["measured_bubble"]
                        if r["schedule"]
                        else None
                    ),
                    "unit": "idle fraction of the 2-stage GPipe "
                    "schedule at M=4 (analytic (S-1)/(S-1+M) = "
                    f"{r['schedule']['analytic_bubble'] if r['schedule'] else None})",
                    # acceptance: sharded-update parity + 1/n optimizer
                    # bytes, all-or-nothing vector placement by the
                    # service, bubble within 10% of the model, stage
                    # parity vs the single-mesh reference. Wall-clock
                    # recorded, not gated.
                    "optimizer_bytes_ratio": r["sharded_update"][
                        "optimizer_bytes_ratio"
                    ],
                    "ok": all(r["gates"].values()),
                    "banked_as": banked,
                    "detail": r,
                }
            )
        )
        return

    if args.dataplane:
        r = bench_dataplane()
        r["backend"] = backend
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_dataplane_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1)
            os.replace(tmp, banked)
            latest = "artifacts/bench_dataplane_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        print(
            json.dumps(
                {
                    "metric": "dataplane_host_to_device_bytes_per_s",
                    "value": r["bytes_per_s_per_host"],
                    "unit": "bytes/sec/host at K=8 heterogeneous lanes "
                    "(pipelined)",
                    # acceptance: fused dispatch bit-identical to the
                    # per-lane reference, input_bound_frac < 5% with
                    # the pipeline ON, co-packing across datasets
                    # preserved; wall ratio recorded, not gated.
                    "vs_baseline": r["wall_ratio_sync_over_pipelined"],
                    "input_bound_frac": [
                        r["synchronous"]["input_bound_frac"],
                        r["pipelined"]["input_bound_frac"],
                    ],
                    "ok": all(r["gates"].values()),
                    "banked_as": banked,
                    "detail": r,
                }
            )
        )
        return

    if args.telemetry_ab:
        # The standing <=2% budget, standalone (the observability CI
        # job's gate): same protocol as the --stacked block, but
        # without the rest of the stacked artifact — the ON side
        # carries device books + anomaly observe + fleet tags +
        # submission-trace attribution.
        r = {"protocol": "telemetry_ab_v2", "backend": backend}
        r["telemetry_overhead"] = bench_telemetry_overhead()
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_telemetry_ab_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1)
            os.replace(tmp, banked)
            latest = "artifacts/bench_telemetry_ab_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        ab = r["telemetry_overhead"]
        print(
            json.dumps(
                {
                    "metric": "telemetry_overhead_frac_tracing_on",
                    "value": ab.get("overhead_frac"),
                    "unit": "fractional step-time overhead, ON vs OFF "
                    "(min-of-passes, interleaved; ON = mark + device "
                    "books + anomaly + fleet tags + trace attribution)",
                    "within_2pct": ab.get("within_2pct"),
                    "per_mark_cost_us": ab.get("per_mark_cost_us"),
                    "ok": bool(ab.get("within_2pct")),
                    "banked_as": banked,
                    "detail": r,
                }
            )
        )
        return

    if args.incidents:
        import contextlib
        import tempfile

        from multidisttorch_tpu.service.incident_drill import (
            run_incidents_bench,
        )

        # MDT_INCIDENT_KEEP_SCOPES pins the scenario scope dirs to a
        # survivable path (CI uploads the ledgers + bundles from there);
        # unset, each run gets a throwaway tempdir.
        work = os.environ.get("MDT_INCIDENT_KEEP_SCOPES")
        if work:
            os.makedirs(work, exist_ok=True)
        else:
            work = tempfile.mkdtemp(prefix="bench_incidents_")

        # The drill and the A/B narrate; keep the one-JSON-line stdout
        # contract by routing their prints to stderr.
        with contextlib.redirect_stdout(sys.stderr):
            r = run_incidents_bench(work)
            r["telemetry_overhead"] = bench_telemetry_overhead()
        r["backend"] = backend
        ab = r["telemetry_overhead"]
        r["gates"]["ab_within_2pct_ring_on"] = bool(ab.get("within_2pct"))
        r["ok"] = bool(r["ok"] and ab.get("within_2pct"))
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_incidents_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1, default=str)
            os.replace(tmp, banked)
            latest = "artifacts/bench_incidents_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1,
                          default=str)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        diag = sum(
            1 for sc in r["scenarios"].values() if sc["ok"]
        )
        print(
            json.dumps(
                {
                    "metric": "incident_confusion_diagonal",
                    "value": f"{diag}/{len(r['scenarios'])}",
                    "unit": "chaos scenarios producing exactly one "
                    "incident with the expected root-cause verdict "
                    "(gate: all, plus zero-incident soak, published "
                    "flight-ring bundles, offline autopsy agreement, "
                    "and the <=2% telemetry A/B with the ring armed)",
                    "soak_incidents": r["soak"]["n_incidents"],
                    "autopsy_verdict": r["autopsy"].get("verdict"),
                    "ab_overhead_frac": ab.get("overhead_frac"),
                    **r["gates"],
                    "ok": r["ok"],
                    "banked": banked,
                }
            )
        )
        if not r["ok"]:
            sys.exit(1)
        return

    if args.ckpt:
        import tempfile

        from multidisttorch_tpu.service.ckpt_drill import run_ckpt_bench

        r = run_ckpt_bench(tempfile.mkdtemp(prefix="bench_ckpt_"))
        r["backend"] = backend
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_ckpt_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1)
            os.replace(tmp, banked)
            latest = "artifacts/bench_ckpt_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        prim = r["drain_primitive"]
        print(
            json.dumps(
                {
                    "metric": "ckpt_snapshot_drain_to_slices_freed_s",
                    "value": prim["arms"]["snapshot_v2"][
                        "drain_to_slices_freed_s"
                    ],
                    "vs_v1_full_persist_drain_s": prim["arms"][
                        "join_v1"
                    ]["drain_to_slices_freed_s"],
                    "speedup": prim["speedup"],
                    "unit": "seconds (wall ratios recorded, not "
                    "gated, on shared runners; the structural gates "
                    "below are what CI enforces)",
                    # acceptance: v2 restores bitwise-identical to v1
                    # across all four trial flavors; incremental saves
                    # < 0.5x full-model bytes on the fine-tune delta
                    # run; drain frees slices without blocking on
                    # persist + ledger honesty + RAM re-place.
                    **r["gates"],
                    "delta_ratio": r["delta"]["finetune"][
                        "delta_ratio_mean"
                    ],
                    "full_adam_contrast_ratio": r["delta"][
                        "full_adam_contrast"
                    ]["delta_ratio_mean"],
                    "ok": r["ok"],
                    "banked": banked,
                },
                indent=2,
            )
        )
        if not r["ok"]:
            sys.exit(1)
        return

    if args.zoo:
        from multidisttorch_tpu.service.loadgen import (
            run_scenario,
            zoo_names,
        )
        from multidisttorch_tpu.telemetry import ctlprof as _ctlprof

        n = args.zoo_n
        if n is None:
            env_n = os.environ.get("MDT_ZOO_N", "")
            n = int(env_n) if env_n else None
        os.makedirs("artifacts", exist_ok=True)
        stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
        platform = backend.get("platform", "cpu")
        ledger_path = "artifacts/ctlprof_ledger.jsonl"
        scenarios: dict = {}
        ok = True
        for name in zoo_names():
            # The sims are pure host logic but can narrate; keep the
            # one-JSON-line stdout contract.
            with contextlib.redirect_stdout(sys.stderr):
                art = run_scenario(
                    name,
                    n_submissions=n,
                    flame_path=f"artifacts/zoo_{name}_ctl_flame.txt",
                )
            art["backend"] = backend
            banked = None
            # Bank the Perfetto control-plane track standalone (CI
            # uploads it); the envelope keeps books only.
            ctl_trace = art.pop("ctl_trace", None)
            try:
                if ctl_trace and ctl_trace.get("traceEvents"):
                    tp = f"artifacts/zoo_{name}_ctl_trace.json"
                    with open(tp + ".tmp", "w") as f:
                        json.dump(ctl_trace, f)
                    os.replace(tp + ".tmp", tp)
                banked = f"artifacts/zoo_{name}_{platform}_{stamp}.json"
                tmp = banked + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(art, f, indent=1)
                os.replace(tmp, banked)
                latest = f"artifacts/zoo_{name}_latest.json"
                with open(latest + ".tmp", "w") as f:
                    json.dump({**art, "banked_as": banked}, f, indent=1)
                os.replace(latest + ".tmp", latest)
            except OSError as e:
                print(f"artifact banking failed: {e!r}", file=sys.stderr)
                banked = None
            folded = _ctlprof.fold_ledger_round(
                ledger_path,
                _ctlprof.ledger_record(
                    "zoo",
                    name,
                    art["ctl"],
                    platform=platform,
                    stamp=stamp,
                    n_submissions=art["spec"].get("n_submissions"),
                    submissions_per_wall_s=art["headline"][
                        "submissions_per_wall_s"
                    ],
                    slo_met=art["headline"]["slo_met"],
                    zero_lost=art["headline"]["zero_lost"],
                ),
            )
            scenario_ok = all(bool(v) for v in art["gates"].values())
            ok = ok and scenario_ok
            scenarios[name] = {
                "ok": scenario_ok,
                "gates": art["gates"],
                "headline": art["headline"],
                "vs_prev_rounds": folded.get("vs_prev_rounds"),
                "banked_as": banked,
            }
        print(
            json.dumps(
                {
                    "metric": "zoo_scenarios_ok",
                    "value": ok,
                    "unit": f"{len(scenarios)} named scenarios, "
                    "production scheduler classes under the "
                    "control-plane profiler",
                    # acceptance: every scenario's SLO verdicts +
                    # zero-lost hold, and every artifact carries
                    # per-phase control-plane flight books; drift
                    # vs prior ledger rounds is recorded, not gated.
                    "scenarios": scenarios,
                    "ledger": ledger_path,
                    "ok": ok,
                }
            )
        )
        return

    if args.fabric:
        import tempfile

        from multidisttorch_tpu.service.fabric_drill import (
            run_fabric_bench,
        )

        # The drills run real services in-process and their drivers
        # narrate (retry resumes etc.) on stdout; bench's stdout
        # contract is exactly ONE JSON line, so the narration joins
        # the diagnostics on stderr.
        with contextlib.redirect_stdout(sys.stderr):
            r = run_fabric_bench(tempfile.mkdtemp(prefix="bench_fabric_"))
        r["backend"] = backend
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_fabric_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1)
            os.replace(tmp, banked)
            latest = "artifacts/bench_fabric_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        # CI-uploadable evidence next to the banked JSON: the split
        # drill's topology log (the elastic fabric's flight recorder)
        # and the failover drill's merged trace export.
        try:
            import shutil as _sh

            _sh.copy(
                r["split_chaos"]["topology"]["log_path"],
                "artifacts/fabric_topology_log.jsonl",
            )
            for k, p in r["failover"]["trace"]["exported"].items():
                _sh.copy(p, f"artifacts/fabric_trace_{k}.json")
        except (OSError, KeyError) as e:
            print(f"evidence copy failed: {e!r}", file=sys.stderr)
        lg = r["loadgen"]
        # The full replay is the ctlprof ledger's BASELINE round: the
        # pre-rebuild per-phase control-plane cost alongside
        # submissions/s — the row the raw-speed rebuild (ROADMAP item
        # 4's incremental indexes) must visibly move.
        try:
            from multidisttorch_tpu.telemetry import ctlprof as _ctlprof

            _ctlprof.fold_ledger_round(
                "artifacts/ctlprof_ledger.jsonl",
                _ctlprof.ledger_record(
                    "baseline",
                    f"fabric_replay_{lg['spec']['n_submissions']}",
                    lg.get("ctl") or {},
                    platform=backend.get("platform", "cpu"),
                    stamp=time.strftime(
                        "%Y%m%d_%H%M%S", time.gmtime()
                    ),
                    n_submissions=lg["spec"]["n_submissions"],
                    submissions_per_wall_s=lg["submissions_per_wall_s"],
                    slo_met=lg["slo"]["met"],
                    zero_lost=lg["zero_lost"],
                ),
            )
        except (OSError, KeyError) as e:
            print(f"ctlprof ledger fold failed: {e!r}", file=sys.stderr)
        print(
            json.dumps(
                {
                    "metric": "fabric_loadgen_p99_placement_latency_s",
                    "value": lg["placement_latency_s"].get("p99"),
                    "unit": "virtual seconds at "
                    f"{lg['submitted']} submissions (overload "
                    "regime, pure scheduler core at simulation "
                    "speed)",
                    # acceptance: replica SIGKILL with work
                    # outstanding -> survivor adopts the shard, zero
                    # lost, re-homed trials bit-identical; deadline
                    # preemption within the anti-thrash budget; 1M
                    # loadgen fairness <= 10% + deadline hit rate.
                    "kill_exercised": r["failover"]["kill_exercised"],
                    "zero_lost": r["failover"]["zero_lost"],
                    "rehomed_bit_identical": r["failover"]["parity"][
                        "bit_identical"
                    ],
                    "deadline_drill_ok": r["deadline"]["ok"],
                    # Elastic topology (ISSUE 17): the kill-mid-split
                    # seam closed by the adopter, movable stacked/
                    # pipelined placements, scenario zoo within 10%
                    # of static routing.
                    "split_kill_exercised": r["split_chaos"][
                        "split_kill_exercised"
                    ],
                    "split_zero_lost": r["split_chaos"]["zero_lost"],
                    "split_no_double_own": r["split_chaos"][
                        "no_double_own"
                    ],
                    "stacked_evict_resume_bit_identical": r["movable"][
                        "stacked"
                    ]["bit_identical"],
                    "pipelined_evict_resume_bit_identical": r["movable"][
                        "pipelined"
                    ]["bit_identical"],
                    "scenario_gates_ok": r["fabric_scenarios"]["ok"],
                    "fairness_max_abs_ratio_error": lg["fairness"][
                        "max_abs_ratio_error"
                    ],
                    "deadline_hit_rate": lg["deadline"]["hit_rate"],
                    "churn_per_1k_placements": lg["churn"][
                        "evictions_per_1k_placements"
                    ],
                    "submissions_per_wall_s": lg[
                        "submissions_per_wall_s"
                    ],
                    "ok": r["ok"],
                    "banked_as": banked,
                    "detail": r,
                }
            )
        )
        return

    if args.service:
        import tempfile

        from multidisttorch_tpu.service.drill import run_service_bench

        r = run_service_bench(tempfile.mkdtemp(prefix="bench_service_"))
        r["backend"] = backend
        # Bank the scheduling artifact (ISSUE 10 acceptance):
        # timestamped + _latest alias, same policy as --pbt/--coldstart.
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_service_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1)
            os.replace(tmp, banked)
            latest = "artifacts/bench_service_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        fair = r["kill_restart"]["fair_share"]
        print(
            json.dumps(
                {
                    "metric": "service_contended_fair_share_ratio",
                    "value": fair["contended_ratio"],
                    "unit": "tenant-A/tenant-B contended placements "
                    "(weights 2:1)",
                    # acceptance: ratio within 10% of the weights,
                    # zero lost submissions across SIGKILL+restart,
                    # and a defrag event unblocking a starved trial
                    "vs_baseline": (
                        round(
                            fair["contended_ratio"]
                            / fair["expected_ratio"],
                            3,
                        )
                        if fair["contended_ratio"] is not None
                        else None
                    ),
                    "zero_lost_submissions": r["gates"][
                        "zero_lost_submissions"
                    ],
                    "tenant_goodput": r["kill_restart"]["tenant_goodput"],
                    "defrag_unblocks_starved_trial": r["gates"][
                        "defrag_unblocks_starved_trial"
                    ],
                    "queue_wait_p50_p99": [
                        (r["kill_restart"].get("queue_wait") or {}).get(
                            "p50_s"
                        ),
                        (r["kill_restart"].get("queue_wait") or {}).get(
                            "p99_s"
                        ),
                    ],
                    "placement_p50_p99": [
                        (
                            r["kill_restart"].get("placement_latency")
                            or {}
                        ).get("p50_s"),
                        (
                            r["kill_restart"].get("placement_latency")
                            or {}
                        ).get("p99_s"),
                    ],
                    "ok": r["ok"],
                    "banked_as": banked,
                    "detail": r,
                }
            )
        )
        return

    if args.pbt:
        r = bench_pbt()
        r["backend"] = backend
        # Bank the artifact (ISSUE 8 acceptance): timestamped file so a
        # later degraded run never clobbers banked evidence, plus a
        # _latest alias for the CI gate/console — same policy as
        # --coldstart.
        banked = None
        try:
            os.makedirs("artifacts", exist_ok=True)
            stamp = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            platform = backend.get("platform", "cpu")
            banked = f"artifacts/bench_pbt_{platform}_{stamp}.json"
            tmp = banked + ".tmp"
            with open(tmp, "w") as f:
                json.dump(r, f, indent=1)
            os.replace(tmp, banked)
            latest = "artifacts/bench_pbt_latest.json"
            with open(latest + ".tmp", "w") as f:
                json.dump({**r, "banked_as": banked}, f, indent=1)
            os.replace(latest + ".tmp", latest)
        except OSError as e:
            print(f"artifact banking failed: {e!r}", file=sys.stderr)
            banked = None
        print(
            json.dumps(
                {
                    "metric": "pbt_fused_dispatch_reduction",
                    "value": r["dispatch_reduction"],
                    "unit": "x fewer dispatches/generation (fused vs "
                    "per-submesh)",
                    # acceptance floor: >= 3x at K=4 with bit-identical
                    # trajectory
                    "vs_baseline": (
                        round(r["dispatch_reduction"] / 3.0, 3)
                        if r["dispatch_reduction"] is not None
                        else None
                    ),
                    "parity": r["parity"],
                    "final_states_bit_identical": r[
                        "final_states_bit_identical"
                    ],
                    "registry_one_compile_cache_hit": r[
                        "compile_registry"
                    ]["one_compile_cache_hit_gen2plus"],
                    "wall_ratio_submesh_over_fused": r[
                        "wall_ratio_submesh_over_fused"
                    ],
                    "banked_as": banked,
                    "detail": r,
                }
            )
        )
        return

    if args.stacked:
        r = bench_stacked()
        k4 = next(
            (lvl for lvl in r["levels"] if lvl["k"] == 4), r["levels"][-1]
        )
        r.update(backend)
        print(
            json.dumps(
                {
                    "metric": "stacked_vae_samples_per_sec_per_chip",
                    "value": k4["samples_per_sec_per_chip"],
                    "unit": "samples/sec/chip",
                    # the acceptance ratio: stacked K=4 over K=1, same
                    # protocol, same hardware, same timed window count
                    "vs_baseline": r["k4_vs_k1"],
                    "detail": r,
                }
            )
        )
        return

    if args.to_elbo is not None:
        r = bench_to_elbo(args.to_elbo)
        r.update(backend)
        print(
            json.dumps(
                {
                    "metric": "hpo_wallclock_to_target_elbo",
                    "value": r["wall_s"],
                    "unit": "seconds",
                    "vs_baseline": None,
                    "detail": r,
                }
            )
        )
        return

    if args.concurrency is not None and args.concurrency < 1:
        parser.error(f"--concurrency must be >= 1, got {args.concurrency}")
    if args.concurrency is not None:
        r = bench_concurrency(args.concurrency)
        r.update(backend)
        print(
            json.dumps(
                {
                    "metric": "concurrent_trial_efficiency",
                    "value": r["efficiency_vs_alone"],
                    "unit": "frac_of_single_trial_throughput",
                    "vs_baseline": round(r["efficiency_vs_alone"] / 0.90, 3),
                    "detail": r,
                }
            )
        )
        return

    flagship_stats = bench_ours()
    ours = flagship_stats["samples_per_sec_per_chip"]
    try:
        ref = bench_reference_torch()
    except Exception as e:
        print(f"reference torch bench failed: {e!r}", file=sys.stderr)
        ref = float("nan")
    vs = ours / ref if ref == ref and ref > 0 else float("nan")
    # MFU: hardware-meaningful single-chip framing (VERDICT r1 weak #3) —
    # fraction of the chip's peak dense bf16 FLOP/s the train loop
    # sustains. None off-TPU or on unknown device kinds.
    peak = (
        _peak_flops_per_chip(backend.get("device_kind", ""))
        if backend.get("platform") not in (None, "cpu")
        else None
    )
    mfu = (ours * _train_flops_per_sample() / peak) if peak else None
    detail = dict(backend)
    detail["flagship_passes"] = flagship_stats
    if backend.get("platform") == "cpu":
        # Cross-round drift tracking: the CPU fallback is the one
        # number every round can measure, so it doubles as the canary
        # for environment drift (slower container, changed BLAS, ...).
        drift = _drift_vs_prev_rounds(
            ours, _chunk_steps(), _flagship_cpu_history()
        )
        if drift is not None:
            detail["vs_prev_rounds"] = drift
            if drift["drift_exceeds_20pct"]:
                print(
                    "WARNING: flagship CPU rate moved "
                    f"{drift['ratio_to_median']}x vs prior-round median "
                    f"{drift['median_prior']} — same-shape comparison, "
                    "treat cross-round conclusions with care",
                    file=sys.stderr,
                )
    _embed_stale_tpu_evidence(detail, backend)
    if peak:
        detail["peak_flops_per_chip"] = peak
        detail["train_flops_per_sample"] = _train_flops_per_sample()
    if jax.default_backend() == "tpu":
        # Kernel-vs-XLA decision data (only meaningful on hardware).
        try:
            detail["fused_loss_comparison"] = bench_fused_loss_comparison()
        except Exception as e:  # record, don't lose the headline number
            detail["fused_loss_comparison"] = {"error": repr(e)[:300]}
    print(
        json.dumps(
            {
                "metric": "vae_train_samples_per_sec_per_chip",
                "value": round(ours, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 3) if vs == vs else None,
                "mfu": round(mfu, 5) if mfu is not None else None,
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
