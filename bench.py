"""Benchmark: VAE training samples/sec/chip vs the reference implementation.

Measures the flagship workload (MNIST-shaped VAE, batch 128 — the
reference's defaults, /root/reference/vae-hpo.py:131,183) as a
jit-compiled train step on the available accelerator, against the
reference's torch train loop executed in-process on CPU (the only
hardware its stack can use here; the reference publishes no numbers of
its own — see BASELINE.md).

Prints exactly ONE JSON line:
  {"metric": "vae_train_samples_per_sec_per_chip", "value": ...,
   "unit": "samples/sec/chip", "vs_baseline": ...}

vs_baseline = our throughput / reference-loop throughput.
"""

import json
import sys
import time
import warnings

warnings.filterwarnings("ignore")

import jax
import jax.numpy as jnp
import numpy as np
import optax

BATCH = 128
HIDDEN, LATENT = 400, 20
WARMUP_STEPS = 10
MEASURE_STEPS = 200
TORCH_MEASURE_STEPS = 30


def bench_ours() -> float:
    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import create_train_state, make_train_step

    ndev = len(jax.devices())
    (trial,) = setup_groups(1)
    # bfloat16 matmuls on the MXU, float32 params/loss — the TPU-first
    # configuration; on CPU runs it silently behaves like float32.
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    model = VAE(hidden_dim=HIDDEN, latent_dim=LATENT, dtype=dtype)
    tx = optax.adam(1e-3)
    state = create_train_state(trial, model, tx, jax.random.key(0))
    step = make_train_step(trial, model, tx)

    batch_np = (
        np.random.default_rng(0).uniform(0, 1, (BATCH, 784)).astype(np.float32)
    )
    batch = jax.device_put(jnp.asarray(batch_np), trial.batch_sharding)
    key = jax.random.key(1)

    for i in range(WARMUP_STEPS):
        state, m = step(state, batch, jax.random.fold_in(key, i))
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        state, m = step(state, batch, jax.random.fold_in(key, WARMUP_STEPS + i))
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0
    return MEASURE_STEPS * BATCH / dt / ndev


def bench_reference_torch() -> float:
    """The reference's train inner loop (vae-hpo.py:61-74) on torch CPU."""
    import torch
    import torch.nn.functional as F
    from torch import nn, optim

    torch.manual_seed(0)

    class VAE(nn.Module):
        # Architecture per /root/reference/vae-hpo.py:19-45.
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(784, HIDDEN)
            self.fc21 = nn.Linear(HIDDEN, LATENT)
            self.fc22 = nn.Linear(HIDDEN, LATENT)
            self.fc3 = nn.Linear(LATENT, HIDDEN)
            self.fc4 = nn.Linear(HIDDEN, 784)

        def forward(self, x):
            h = F.relu(self.fc1(x))
            mu, logvar = self.fc21(h), self.fc22(h)
            std = torch.exp(0.5 * logvar)
            z = mu + torch.randn_like(std) * std
            recon = torch.sigmoid(self.fc4(F.relu(self.fc3(z))))
            return recon, mu, logvar

    model = VAE()
    opt = optim.Adam(model.parameters(), lr=1e-3)
    data = torch.rand(BATCH, 784)

    def one_step():
        opt.zero_grad()
        recon, mu, logvar = model(data)
        bce = F.binary_cross_entropy(recon, data, reduction="sum")
        kld = -0.5 * torch.sum(1 + logvar - mu.pow(2) - logvar.exp())
        (bce + kld).backward()
        opt.step()

    for _ in range(3):
        one_step()
    t0 = time.perf_counter()
    for _ in range(TORCH_MEASURE_STEPS):
        one_step()
    dt = time.perf_counter() - t0
    return TORCH_MEASURE_STEPS * BATCH / dt


def bench_concurrency(num_trials: int) -> dict:
    """North-star metric (BASELINE.md): per-chip throughput of N
    concurrent trials, each on its own disjoint submesh, relative to one
    trial running alone on an identical submesh. Target: >= 0.90 at 8
    trials."""
    from multidisttorch_tpu.models.vae import VAE
    from multidisttorch_tpu.parallel.mesh import setup_groups
    from multidisttorch_tpu.train.steps import create_train_state, make_train_step

    groups = setup_groups(num_trials)
    dtype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
    model = VAE(hidden_dim=HIDDEN, latent_dim=LATENT, dtype=dtype)
    tx = optax.adam(1e-3)
    batch_np = (
        np.random.default_rng(0).uniform(0, 1, (BATCH, 784)).astype(np.float32)
    )
    key = jax.random.key(1)

    def setup_trial(g):
        state = create_train_state(g, model, tx, jax.random.key(g.group_id))
        step = make_train_step(g, model, tx)
        batch = jax.device_put(jnp.asarray(batch_np), g.batch_sharding)
        return {"state": state, "step": step, "batch": batch}

    trials = [setup_trial(g) for g in groups]

    def run_steps(active, nsteps):
        for i in range(nsteps):
            for t in active:
                t["state"], _ = t["step"](
                    t["state"], t["batch"], jax.random.fold_in(key, i)
                )
        for t in active:
            jax.block_until_ready(t["state"].params)

    # warmup all compilations
    run_steps(trials, WARMUP_STEPS)

    # trial 0 alone on its submesh
    t0 = time.perf_counter()
    run_steps(trials[:1], MEASURE_STEPS)
    alone_sps = MEASURE_STEPS * BATCH / (time.perf_counter() - t0)

    # all trials concurrently
    t0 = time.perf_counter()
    run_steps(trials, MEASURE_STEPS)
    dt = time.perf_counter() - t0
    per_trial_sps = MEASURE_STEPS * BATCH / dt  # each trial did MEASURE_STEPS

    return {
        "num_trials": num_trials,
        "alone_samples_per_sec": round(alone_sps, 1),
        "concurrent_per_trial_samples_per_sec": round(per_trial_sps, 1),
        "aggregate_samples_per_sec": round(per_trial_sps * num_trials, 1),
        "efficiency_vs_alone": round(per_trial_sps / alone_sps, 3),
    }


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--concurrency", type=int, default=None,
        help="measure N concurrent trials' per-chip efficiency instead of "
        "the default single-chip throughput metric",
    )
    args = parser.parse_args()

    if args.concurrency is not None and args.concurrency < 1:
        parser.error(f"--concurrency must be >= 1, got {args.concurrency}")
    if args.concurrency is not None:
        r = bench_concurrency(args.concurrency)
        print(
            json.dumps(
                {
                    "metric": "concurrent_trial_efficiency",
                    "value": r["efficiency_vs_alone"],
                    "unit": "frac_of_single_trial_throughput",
                    "vs_baseline": round(r["efficiency_vs_alone"] / 0.90, 3),
                    "detail": r,
                }
            )
        )
        return

    ours = bench_ours()
    try:
        ref = bench_reference_torch()
    except Exception as e:
        print(f"reference torch bench failed: {e!r}", file=sys.stderr)
        ref = float("nan")
    vs = ours / ref if ref == ref and ref > 0 else float("nan")
    print(
        json.dumps(
            {
                "metric": "vae_train_samples_per_sec_per_chip",
                "value": round(ours, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 3) if vs == vs else None,
            }
        )
    )


if __name__ == "__main__":
    main()
